"""Inference / deployment API: Config + Predictor.

Reference: the AnalysisPredictor stack
(``paddle/fluid/inference/api/analysis_predictor.h:105``,
``paddle_inference_api.h``, ``analysis_config.cc``) — load a saved program +
params, run an optimization pass pipeline, optionally convert to mixed
precision (``paddle/fluid/inference/analysis/passes/convert_to_mixed_precision.cc``),
then serve ``Run()`` with zero-copy input/output handles.

TPU-native redesign: the "program" is a serialized ``jax.export`` artifact
(StableHLO) produced by ``paddle_tpu.jit.save``; the pass pipeline and memory
optimization are XLA's job at compile time, so ``Config``'s IR-optim switches
gate *donation* and *precision casting* — the two knobs that exist on this
side of the compiler. Handles mirror the reference's zero-copy tensors: inputs
are staged host-side and device-put once per ``run()``; outputs stay on device
until ``copy_to_cpu()``.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "Config",
    "Predictor",
    "PredictorTensor",
    "create_predictor",
    "convert_to_mixed_precision",
    "PrecisionType",
    "AdmissionPolicy",
    "ContinuousBatchingEngine",
    "FIFOAdmission",
    "InferenceRequest",
    "HostKVTier",
    "NGramDrafter",
    "PrefixCache",
    "IntakeError",
    "EmptyPromptError",
    "InvalidTokenBudgetError",
    "PromptTooLongError",
    "RequestTooLongError",
    "RequestUnservableError",
]

from paddle_tpu.inference.kv_tier import HostKVTier  # noqa: E402
from paddle_tpu.inference.prefix_cache import PrefixCache  # noqa: E402
from paddle_tpu.inference.spec_decode import NGramDrafter  # noqa: E402
from paddle_tpu.inference.engine import (  # noqa: E402
    AdmissionPolicy,
    ContinuousBatchingEngine,
    EmptyPromptError,
    FIFOAdmission,
    InferenceRequest,
    IntakeError,
    InvalidTokenBudgetError,
    PromptTooLongError,
    RequestTooLongError,
    RequestUnservableError,
)


class PrecisionType:
    """Reference ``paddle_infer.PrecisionType`` parity."""

    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"  # weight-only int8: int8 weights in HBM, bf16 compute


def _quantize_weight_only_int8(params: Dict[str, Any], black: Any = ()) -> Dict[str, Any]:
    """Weight-only int8 (reference WINT8 / ``weight_only_linear``): every
    >=2-D float param becomes ``<name>@int8`` + per-output-channel
    ``<name>@scale``; the rest cast to bf16. Flat keys keep the pytree
    serializable through the existing bundle machinery. Halves weight bytes
    in HBM/on disk; the dequant multiply fuses into each consumer matmul.
    The scale/clip math is the quantization module's — ONE definition of
    int8 quantization in the codebase."""
    from paddle_tpu.quantization import _scales_absmax, quantize_linear

    out: Dict[str, Any] = {}
    for k, v in params.items():
        if (
            k not in black
            and jnp.issubdtype(v.dtype, jnp.floating)
            and v.ndim >= 2
            and v.shape[-1] >= 4
        ):
            s = _scales_absmax(v, v.ndim - 1, 8)
            out[k + "@int8"] = quantize_linear(v, s, bits=8, axis=v.ndim - 1)._data
            out[k + "@scale"] = s.astype(jnp.float32)
        elif jnp.issubdtype(v.dtype, jnp.floating):
            out[k] = v.astype(jnp.bfloat16)
        else:
            out[k] = v
    return out


def _dequantize_params(qparams: Dict[str, Any], dtype: Any = jnp.bfloat16) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in qparams.items():
        if k.endswith("@int8"):
            name = k[: -len("@int8")]
            out[name] = v.astype(dtype) * qparams[name + "@scale"].astype(dtype)
        elif not k.endswith("@scale"):
            out[k] = v
    return out


class Config:
    """Inference config (reference ``AnalysisConfig``).

    ``Config(prog_file, params_file)`` or ``Config(model_dir)`` where the dir
    contains ``inference.pdmodel`` / ``inference.pdiparams`` (also accepts the
    bare bundle prefix produced by ``paddle_tpu.jit.save``).
    """

    def __init__(self, prog_file: Optional[str] = None, params_file: Optional[str] = None) -> None:
        self._prefix: Optional[str] = None
        if prog_file is not None and params_file is None:
            # model_dir form, or a bundle prefix
            if os.path.isdir(prog_file):
                for stem in ("inference", "model", "__model__"):
                    cand = os.path.join(prog_file, stem)
                    if os.path.exists(cand + ".pdmodel"):
                        self._prefix = cand
                        break
                if self._prefix is None:
                    raise FileNotFoundError(f"no *.pdmodel bundle under {prog_file}")
            else:
                self._prefix = prog_file[:-8] if prog_file.endswith(".pdmodel") else prog_file
        elif prog_file is not None:
            self._prefix = prog_file[:-8] if prog_file.endswith(".pdmodel") else prog_file
        self._layer: Any = None
        self._input_spec: Optional[Sequence[Any]] = None
        self.device: str = "tpu"
        self.precision: str = PrecisionType.Float32
        self.memory_optim: bool = True  # donate input buffers
        self.ir_optim: bool = True  # kept for API parity; XLA always optimizes

    # -- construction from a live layer (the reference's memory-program path) --
    @classmethod
    def from_layer(cls, layer: Any, input_spec: Sequence[Any]) -> "Config":
        cfg = cls()
        cfg._layer = layer
        cfg._input_spec = input_spec
        return cfg

    # -- reference AnalysisConfig surface ------------------------------------
    def set_model(self, prog_file: str, params_file: Optional[str] = None) -> None:
        self._prefix = prog_file[:-8] if prog_file.endswith(".pdmodel") else prog_file

    def enable_use_gpu(self, memory_pool_init_size_mb: int = 100, device_id: int = 0) -> None:
        self.device = "tpu"  # accelerator serving; TPU is the accelerator here

    def disable_gpu(self) -> None:
        self.device = "cpu"

    def enable_mixed_precision(self, precision: str = PrecisionType.Bfloat16) -> None:
        self.precision = precision

    def enable_memory_optim(self, x: bool = True) -> None:
        self.memory_optim = bool(x)

    def switch_ir_optim(self, x: bool = True) -> None:
        self.ir_optim = bool(x)

    def set_cpu_math_library_num_threads(self, n: int) -> None:  # parity no-op
        pass

    def summary(self) -> str:
        return (
            f"Config(prefix={self._prefix}, device={self.device}, "
            f"precision={self.precision}, memory_optim={self.memory_optim})"
        )


class PredictorTensor:
    """Zero-copy style input/output handle (reference ``ZeroCopyTensor``)."""

    def __init__(self, name: str, shape: Sequence[int], dtype: str) -> None:
        self.name = name
        self._shape = list(shape)
        self._dtype = dtype
        self._host: Optional[np.ndarray] = None
        self._device: Optional[jax.Array] = None

    def shape(self) -> List[int]:
        if self._device is not None:
            return list(self._device.shape)
        return self._shape

    def copy_from_cpu(self, arr: np.ndarray) -> None:
        self._host = np.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        if self._device is None:
            raise RuntimeError(f"output '{self.name}' not produced yet; call run() first")
        return np.asarray(self._device)

    # reference aliases
    def reshape(self, shape: Sequence[int]) -> None:
        self._shape = list(shape)

    def type(self) -> str:
        return self._dtype


class Predictor:
    """Compiled serving predictor (reference ``AnalysisPredictor``).

    Construction compiles once; ``run()`` executes with resident weights.
    Weights are device-resident across calls; with ``memory_optim`` the input
    buffers are donated to XLA so activations can reuse them.
    """

    def __init__(self, config: Config) -> None:
        self._config = config
        # honor Config.device: "cpu" pins execution to the host backend even
        # when an accelerator is visible (committed arrays steer jit)
        self._device = None
        if config.device == "cpu" and jax.default_backend() != "cpu":
            try:
                self._device = jax.devices("cpu")[0]
            except RuntimeError:
                self._device = None
        if config._layer is not None:
            self._init_from_layer(config)
        elif config._prefix is not None:
            self._init_from_bundle(config)
        else:
            raise ValueError("Config has neither a model path nor a layer")
        if self._device is not None:
            self._params = jax.device_put(self._params, self._device)

    # -- init paths ----------------------------------------------------------
    def _init_from_bundle(self, config: Config) -> None:
        from paddle_tpu.jit.save_load import load

        bundle = load(config._prefix)
        if bundle._exported is None:
            raise RuntimeError(
                f"{config._prefix}.pdmodel has no serialized program; re-save with "
                "jit.save(layer, path, input_spec=...)"
            )
        params = {k: t._data for k, t in bundle.state_dict().items()}
        # Precision conversion cannot be applied to an already-exported
        # program (dtypes are baked into the StableHLO signature) — that is a
        # save-time pass here (convert_to_mixed_precision), exactly like the
        # reference's offline convert_to_mixed_precision.cc tool. Requesting
        # one here must not silently serve the baked precision; only suppress
        # the warning when the request matches what the bundle bakes.
        float_dtypes = {
            str(v.dtype) for v in params.values() if jnp.issubdtype(v.dtype, jnp.floating)
        }
        if any(k.endswith("@int8") for k in params):
            baked = PrecisionType.Int8
        elif float_dtypes == {"bfloat16"}:
            baked = PrecisionType.Bfloat16
        elif float_dtypes == {"float16"}:
            baked = PrecisionType.Half
        else:
            baked = PrecisionType.Float32
        request_matches_bundle = config.precision in (PrecisionType.Float32, baked)
        if not request_matches_bundle:
            import warnings

            warnings.warn(
                f"Config precision={config.precision!r} is ignored for a "
                "serialized bundle (dtypes are baked at save time); convert "
                "offline with inference.convert_to_mixed_precision, or build "
                "the predictor with Config.from_layer",
                stacklevel=3,
            )
        exported = bundle._exported
        call = exported.call
        n_in = len(bundle.input_spec)
        donate = config.memory_optim and config.device != "cpu" and jax.default_backend() != "cpu"
        self._fn = jax.jit(
            lambda params_, *xs: call(params_, *xs),
            donate_argnums=tuple(range(1, 1 + n_in)) if donate else (),
        )
        self._params = params
        self._inputs = [
            PredictorTensor(s["name"], s["shape"], s["dtype"]) for s in bundle.input_spec
        ]
        self._outputs = [
            PredictorTensor(s["name"], s["shape"], s["dtype"]) for s in bundle.output_spec
        ]

    def _init_from_layer(self, config: Config) -> None:
        from paddle_tpu.core import autograd as _ag
        from paddle_tpu.jit.save_load import _pure_forward, specs_from_input_spec

        from paddle_tpu.jit.save_load import decommit_from_mesh

        layer = config._layer
        layer.eval()
        # mesh-sharded training weights must not bake an N-device calling
        # convention into the serving program
        params = decommit_from_mesh({k: v._data for k, v in layer.state_dict().items()})
        tgt = None
        int8 = config.precision == PrecisionType.Int8
        if config.precision in (PrecisionType.Bfloat16, PrecisionType.Half) or int8:
            tgt = jnp.float16 if config.precision == PrecisionType.Half else jnp.bfloat16
            if int8:
                # weight-only int8: int8 weights resident in HBM (half the
                # bf16 footprint), dequant fused into consumers, bf16 compute
                params = _quantize_weight_only_int8(params)
            else:
                params = {
                    k: v.astype(tgt) if jnp.issubdtype(v.dtype, jnp.floating) else v
                    for k, v in params.items()
                }
        base_pure = _pure_forward(layer)
        pure = (
            (lambda p, *xs: base_pure(_dequantize_params(p), *xs)) if int8 else base_pure
        )
        # inputs follow the param cast dtype (f16 params get f16 inputs —
        # mixing f16 x bf16 would silently promote every matmul to fp32)
        specs = specs_from_input_spec(config._input_spec, float_dtype=tgt)
        self._inputs = [
            PredictorTensor(getattr(s, "name", None) or f"x{i}", spec.shape, str(spec.dtype))
            for i, (s, spec) in enumerate(zip(config._input_spec, specs))
        ]
        n_in = len(specs)

        def fn(params_, *xs):
            with _ag.set_grad_enabled(False):
                return pure(params_, *xs)

        donate = config.memory_optim and config.device != "cpu" and jax.default_backend() != "cpu"
        self._fn = jax.jit(
            fn,
            donate_argnums=tuple(range(1, 1 + n_in)) if donate else (),
        )
        out_avals = jax.eval_shape(fn, params, *specs)
        flat, _ = jax.tree_util.tree_flatten(out_avals)
        self._outputs = [
            PredictorTensor(f"fetch{i}", a.shape, str(a.dtype)) for i, a in enumerate(flat)
        ]
        self._params = params

    # -- reference predictor surface -----------------------------------------
    def get_input_names(self) -> List[str]:
        return [h.name for h in self._inputs]

    def get_output_names(self) -> List[str]:
        return [h.name for h in self._outputs]

    def get_input_handle(self, name: str) -> PredictorTensor:
        for h in self._inputs:
            if h.name == name:
                return h
        raise KeyError(f"no input named {name!r}; have {self.get_input_names()}")

    def get_output_handle(self, name: str) -> PredictorTensor:
        for h in self._outputs:
            if h.name == name:
                return h
        raise KeyError(f"no output named {name!r}; have {self.get_output_names()}")

    def run(self, inputs: Optional[Sequence[np.ndarray]] = None) -> Optional[List[np.ndarray]]:
        """Execute one inference. Two forms:

        - handle style (reference ZeroCopyRun): stage inputs with
          ``copy_from_cpu`` on the handles, call ``run()``, read outputs with
          ``copy_to_cpu``;
        - direct style: ``outs = predictor.run([arr, ...])`` returns numpy.
        """
        if inputs is not None:
            if len(inputs) != len(self._inputs):
                raise ValueError(
                    f"Predictor.run expects {len(self._inputs)} inputs "
                    f"({self.get_input_names()}), got {len(inputs)}"
                )
            for h, a in zip(self._inputs, inputs):
                h.copy_from_cpu(a)
        arrays = []
        for h in self._inputs:
            if h._host is None:
                raise RuntimeError(f"input '{h.name}' was never fed (copy_from_cpu)")
            arr = jnp.asarray(h._host)
            want = jnp.dtype(h._dtype)
            if arr.dtype != want and jnp.issubdtype(want, jnp.floating):
                arr = arr.astype(want)
            if self._device is not None:
                arr = jax.device_put(arr, self._device)
            arrays.append(arr)
        out = self._fn(self._params, *arrays)
        flat, _ = jax.tree_util.tree_flatten(out)
        for h, a in zip(self._outputs, flat):
            h._device = a
        if inputs is not None:
            return [np.asarray(a) for a in flat]
        return None

    # reference alias
    def zero_copy_run(self) -> None:
        self.run()

    ZeroCopyRun = zero_copy_run


def create_predictor(config: Config) -> Predictor:
    """Reference ``paddle_infer.create_predictor`` parity."""
    return Predictor(config)


def convert_to_mixed_precision(
    layer_or_path: Any,
    save_path: str,
    input_spec: Optional[Sequence[Any]] = None,
    mixed_precision: str = PrecisionType.Bfloat16,
    backend: str = "tpu",
    black_list: Optional[Sequence[str]] = None,
) -> None:
    """Offline mixed-precision conversion (reference
    ``convert_to_mixed_precision.cc``): cast a model's float params to the
    target dtype and re-export the bundle with a low-precision program.

    Accepts a live Layer (+ input_spec). dtype conversion happens *before*
    export because StableHLO bakes dtypes into the program signature.
    """
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.jit import save as jit_save
    from paddle_tpu.nn.layer.layers import Layer

    if not isinstance(layer_or_path, Layer):
        raise TypeError(
            "convert_to_mixed_precision needs a live Layer on this backend "
            "(exported programs have baked dtypes)"
        )
    from paddle_tpu.jit.save_load import specs_from_input_spec

    layer = layer_or_path
    black = set(black_list or ())
    if mixed_precision == PrecisionType.Int8:
        _export_weight_only_int8(layer, save_path, input_spec or [], black)
        return
    tgt = jnp.bfloat16 if mixed_precision != PrecisionType.Half else jnp.float16
    # cast for the export only — the caller's live (training) weights are
    # restored afterwards, like the reference's offline converter working on
    # a separate saved model
    saved = []
    for name, p in layer.named_parameters():
        if name in black:
            continue
        if jnp.issubdtype(jnp.dtype(p.dtype), jnp.floating):
            saved.append((p, p._data))
            p._data = p._data.astype(tgt)
    try:
        specs = specs_from_input_spec(input_spec or [], float_dtype=tgt)
        jit_save(layer, save_path, input_spec=specs)
    finally:
        for p, d in saved:
            p._data = d


def _export_weight_only_int8(layer: Any, save_path: str, input_spec: Sequence[Any],
                             black: Any) -> None:
    """Offline WINT8 export: serialize a program whose parameter inputs ARE
    the int8 weights + scales (dequant lives inside the StableHLO), so the
    saved bundle and the served HBM copy are both half-size. The Predictor's
    bundle loader needs no special casing — the flat ``@int8``/``@scale``
    keys ride the normal state-dict path, and the on-disk format lives in
    one place (``save_load.write_bundle``)."""
    from paddle_tpu.jit.save_load import (
        _pure_forward,
        decommit_from_mesh,
        export_fn,
        specs_from_input_spec,
        write_bundle,
    )

    was_training = bool(getattr(layer, "training", False))
    layer.eval()
    try:
        params = decommit_from_mesh({k: v._data for k, v in layer.state_dict().items()})
        qparams = _quantize_weight_only_int8(params, black=black)
        pure = _pure_forward(layer)

        def qfn(qp, *xs):
            return pure(_dequantize_params(qp), *xs)

        specs = specs_from_input_spec(input_spec, float_dtype=jnp.bfloat16)
        exported = export_fn(qfn, qparams, specs)
        write_bundle(
            save_path, exported, qparams, input_spec, specs=specs,
            extra_spec={"precision": "int8-weight-only"},
        )
    finally:
        if was_training:
            layer.train()
