"""Content-addressed prefix cache over the paged KV block pool.

At production scale most requests share long prefixes (system prompts,
few-shot templates), yet a plain paged engine recomputes every prompt from
token zero. This module is the layer between the scheduler and the pool that
makes shared prefixes *computed once, mapped by all*:

- **Chain nodes.** A token stream is chunked into block-aligned segments;
  each full block is keyed by a rolling content hash
  ``digest = H(parent_digest, token_ids)``, so a node identifies not just
  its own tokens but the entire prefix that produced its KV — two blocks
  with identical tokens under different histories never alias. Prompt
  blocks register IN-FLIGHT (the moment their prefill chunk returns);
  blocks of GENERATED tokens register at request finish only — a live tail
  can still be rewound by speculative decoding, so the engine hashes
  generated content exclusively after the last commit
  (``engine._register_finished_chain``), which is what makes registration
  rewind-safe: only committed, verified tokens ever enter the chain. A
  multi-turn conversation's second turn thereby maps its first turn's KV.
- **Match + map.** On admission the longest chain of cached nodes matching
  the prompt is mapped straight into the request's block table with
  refcounts bumped — those tokens are never recomputed. Matching is capped
  at ``prompt_len - 1``: the engine always computes at least one prompt
  position, because the first generated token comes from the last prompt
  position's logits.
- **Copy-on-write.** When the first divergent block is a *prefix* of some
  cached child block (a ragged prompt tail, or the one token held back by
  the cap), that child's physical block is forked: the engine copies the
  block device-side in its next step and the request continues writing into
  its private copy — the shared block is never written. The source node
  holds a reference until the fork's copy has executed.
- **Refcounts + eviction.** A node's block returns to the free list only
  when no request maps it, no child chains under it, AND the LRU decides to
  evict it; until then a finished request's prompt blocks stay warm for the
  next match. Eviction walks zero-reference chain tails only — a live
  request can never lose a block.

Thread safety: the cache has one internal lock ordered strictly above the
pool's (cache -> pool, never the reverse); the serving front end's pump
thread and intake threads may race engine introspection against admissions.

Fault sites ``prefix_cache.match`` and ``prefix_cache.cow`` let the fault
campaign force cache-miss and CoW-failure paths deterministically; both
degrade to recompute, never to a failed request.

**Hierarchical KV**: with a :class:`~paddle_tpu.inference.kv_tier
.HostKVTier` attached (``FLAGS_kv_host_tier_bytes`` > 0), an evicted chain
block is captured D2H and spilled into the host tier instead of dropped,
and the match walk continues ACROSS the tier boundary: when the device walk
runs out of resident nodes, the same rolling-digest recurrence keeps
walking spilled nodes (returned pinned in ``MatchResult.host_nodes`` for
the engine to prefetch H2D), and the partial arm consults spilled children
too (``MatchResult.host_partial``). Spill and prefetch failures degrade to
the pre-tier behavior — drop, and recompute, respectively.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from paddle_tpu.inference.kv_tier import HostKVTier, HostNode, leading_run
from paddle_tpu.observability import flight_recorder as _flight
from paddle_tpu.observability import metrics as _obs
from paddle_tpu.testing.faults import InjectedFault, fault_point

__all__ = ["ChainNode", "MatchResult", "PrefixCache", "chain_digest"]

_ROOT_DIGEST = b"prefix-cache-root"


def chain_digest(
    prompt: np.ndarray, block_size: int, max_blocks: Optional[int] = None
) -> bytes:
    """Rolling content digest of ``prompt``'s block-aligned prefix chain —
    the SAME ``H(parent_digest, token_bytes)`` recurrence :meth:`PrefixCache
    .match` walks, so two prompts that would map the same cached chain nodes
    produce the same digest. This is the cluster router's affinity key:
    routing by it lands requests sharing a prefix on the replica already
    holding that prefix's KV chains.

    ``max_blocks`` caps the walk (a router keys on the first few blocks — the
    shared system prompt — so divergent user tails do not scatter a tenant's
    traffic). A prompt shorter than one block hashes its raw tokens under the
    root, so short prompts still spread across replicas."""
    prompt = np.asarray(prompt, np.int32).reshape(-1)
    bs = int(block_size)
    n_full = prompt.size // bs
    if max_blocks is not None:
        n_full = min(n_full, int(max_blocks))
    digest = _ROOT_DIGEST
    if n_full == 0:
        return PrefixCache._digest(digest, prompt.tobytes())
    for i in range(n_full):
        digest = PrefixCache._digest(digest, prompt[i * bs : (i + 1) * bs].tobytes())
    return digest


def _cache_metrics() -> Dict[str, Any]:
    """Get-or-create the prefix-cache metric families (process-global, like
    the engine's). Recording is a no-op behind the registry's cached-bool
    gate when ``FLAGS_enable_metrics`` is off."""
    reg = _obs.GLOBAL_METRICS
    return {
        "hits": reg.counter(
            "prefix_cache_hits_total",
            "Admissions that mapped at least one cached prefix token, by the "
            "deepest tier the match reached (hbm = device-resident chain "
            "only; host = the walk crossed into the host spill tier).",
            labelnames=("tier",),
        ),
        "misses": reg.counter(
            "prefix_cache_misses_total",
            "Admissions that found no reusable prefix (cold compute).",
        ),
        "evictions": reg.counter(
            "prefix_cache_evictions_total",
            "Cached blocks evicted (LRU over zero-reference chain tails).",
        ),
        "shared": reg.gauge(
            "prefix_cache_blocks_shared",
            "Cache-owned blocks currently mapped by two or more requests.",
        ),
        "saved": reg.gauge(
            "prefix_cache_bytes_saved",
            "Cumulative KV bytes served from cache instead of recomputed.",
        ),
    }


class ChainNode:
    """One cached full block of prompt KV: a link in a content-hash chain.

    ``req_refs`` counts live request mappings (including a pending CoW fork
    reading from this block); ``child_refs`` counts cached child nodes. The
    node is evictable only when both are zero."""

    __slots__ = (
        "key", "digest", "block", "parent", "token_bytes",
        "req_refs", "child_refs",
    )

    def __init__(
        self,
        key: Tuple[bytes, bytes],
        digest: bytes,
        block: int,
        parent: Optional["ChainNode"],
        token_bytes: bytes,
    ) -> None:
        self.key = key
        self.digest = digest
        self.block = block
        self.parent = parent
        self.token_bytes = token_bytes
        self.req_refs = 0
        self.child_refs = 0


class MatchResult:
    """Outcome of :meth:`PrefixCache.match` — already reference-held.

    ``nodes`` are the matched full-block chain (refs taken); ``cached_tokens``
    counts every token served from DEVICE-resident cache including the CoW
    partial (host-tier reuse is added by the engine only once its prefetch
    actually lands); ``cow`` is ``(src_node, dst_block, partial_len)`` when
    the first divergent block was forked (refs taken on ``src_node`` until
    :meth:`PrefixCache.release_cow_source`). ``host_nodes`` continue the
    chain walk into the host spill tier (full blocks, pinned against LRU
    drop until the engine issues or abandons their H2D prefetch) and
    ``host_partial`` is the spilled divergent-block arm ``(host_node,
    matched_tokens)`` (also pinned)."""

    __slots__ = ("nodes", "cached_tokens", "cow", "host_nodes", "host_partial")

    def __init__(
        self,
        nodes: List[ChainNode],
        cached_tokens: int,
        cow: Optional[Tuple[ChainNode, int, int]],
        host_nodes: Optional[List[HostNode]] = None,
        host_partial: Optional[Tuple[HostNode, int]] = None,
    ) -> None:
        self.nodes = nodes
        self.cached_tokens = cached_tokens
        self.cow = cow
        self.host_nodes = host_nodes or []
        self.host_partial = host_partial


class PrefixCache:
    """Content-addressed, reference-counted block cache over a
    :class:`~paddle_tpu.incubate.nn.functional.BlockKVCache` pool.

    ``bytes_per_token`` sizes the bytes-saved gauge: KV bytes across all
    layers for one token (2 x layers x kv_heads x head_dim x itemsize).
    """

    def __init__(
        self,
        pool: Any,
        block_size: int,
        bytes_per_token: int = 0,
        host_tier: Optional[HostKVTier] = None,
        capture_kv: Optional[Callable[[int], np.ndarray]] = None,
    ) -> None:
        self._pool = pool
        self.block_size = int(block_size)
        self.bytes_per_token = int(bytes_per_token)
        # hierarchical KV: the host spill tier plus the engine-provided D2H
        # capture of one physical block's KV across all layers; both None =
        # single-tier (evicted chains die, the pre-tier behavior)
        self._tier = host_tier
        self._capture_kv = capture_kv
        self._lock = threading.Lock()
        self._nodes: Dict[Tuple[bytes, bytes], ChainNode] = {}
        # parent digest -> insertion-ordered child keys (partial-match scan)
        self._children: Dict[bytes, List[Tuple[bytes, bytes]]] = {}
        # zero-ref chain TAILS in LRU order (oldest first) — the eviction
        # walk order; interior dead nodes are reached by parent cascade
        self._evictable: "OrderedDict[Tuple[bytes, bytes], ChainNode]" = OrderedDict()
        # O(1) reclaim/sharing accounting. Invariant: a request that maps a
        # node maps (and holds) its whole ancestor chain, so req_refs == 0
        # implies every descendant is dead too — ALL dead nodes are
        # eventually evictable via the leaf-first cascade, and the dead
        # count IS the reclaimable-headroom count admission may use.
        self._dead = 0  # nodes with req_refs == 0
        self._shared = 0  # nodes with req_refs >= 2
        # host-side counters (always on — introspection must not depend on
        # the metrics flag); the metric families mirror them when enabled
        self._hits = 0
        self._host_hits = 0  # hits whose walk crossed into the host tier
        self._misses = 0
        self._evictions = 0
        self._tokens_reused = 0
        self._spilled = 0  # evicted blocks saved into the host tier
        self._cow_forks = 0
        self._metrics = _cache_metrics()
        self._flight = _flight.GLOBAL_FLIGHT_RECORDER

    def set_replica_scope(self, scope: Any, flight: Any) -> None:
        """Re-bind metrics/flight events to a replica scope (see the
        engine's ``set_replica_scope``); resolved once, per-record cost
        unchanged."""
        self._metrics = scope.bind_all(_cache_metrics())
        self._flight = flight

    # -- hashing -------------------------------------------------------------
    @staticmethod
    def _digest(parent_digest: bytes, token_bytes: bytes) -> bytes:
        h = hashlib.blake2b(digest_size=16)
        h.update(parent_digest)
        h.update(token_bytes)
        return h.digest()

    # -- introspection -------------------------------------------------------
    @property
    def evictable_blocks(self) -> int:
        """Blocks the cache retains but would surrender under pressure:
        EVERY node with zero request references (the leaf-first eviction
        cascade reaches interior dead nodes too) — admission may count all
        of them as reclaimable headroom."""
        with self._lock:
            return self._dead

    @property
    def node_count(self) -> int:
        with self._lock:
            return len(self._nodes)

    def shared_block_count(self) -> int:
        """Cache-owned blocks currently mapped by >= 2 requests."""
        with self._lock:
            return self._shared

    def stats_snapshot(self) -> Dict[str, Any]:
        """Cheap health view for the serving layer and bench records."""
        with self._lock:
            lookups = self._hits + self._misses
            # counters only — this runs on every serving pump tick, so it
            # must never scan the node table under the lock
            return {
                "hits": self._hits,
                "host_hits": self._host_hits,
                "misses": self._misses,
                "hit_rate": (self._hits / lookups) if lookups else 0.0,
                "tokens_reused": self._tokens_reused,
                "bytes_saved": self._tokens_reused * self.bytes_per_token,
                "cow_forks": self._cow_forks,
                "evictions": self._evictions,
                "spilled": self._spilled,
                "nodes": len(self._nodes),
                "evictable_blocks": self._dead,
                "blocks_shared": self._shared,
            }

    def peek_cached_blocks(self, prompt: np.ndarray) -> Tuple[int, int]:
        """``(matched, matched_evictable)``: the full blocks a :meth:`match`
        of ``prompt`` would map, WITHOUT taking references — the admission
        reservation uses this to count only non-shared blocks against the
        pool. ``matched_evictable`` counts matched blocks currently DEAD
        (zero request refs): pinning those consumes reclaimable headroom the
        caller may otherwise have counted as free."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        cap = prompt.size - 1
        bs = self.block_size
        with self._lock:
            parent_digest = _ROOT_DIGEST
            pos = 0
            n = 0
            n_evictable = 0
            while pos + bs <= cap:
                key = (parent_digest, prompt[pos : pos + bs].tobytes())
                node = self._nodes.get(key)
                if node is None:
                    break
                n += 1
                if node.req_refs == 0:
                    n_evictable += 1
                pos += bs
                parent_digest = node.digest
            return n, n_evictable

    # -- match / acquire -----------------------------------------------------
    def match(self, prompt: np.ndarray) -> MatchResult:
        """Map the longest cached prefix chain of ``prompt``; references are
        taken atomically under the cache lock (matched nodes can never be
        evicted between match and use). The fault site at the top models a
        corrupted/unavailable index — callers degrade to a cold miss.

        The walk is the SAME rolling-digest recurrence across both tiers:
        device-resident nodes first, then (host tier attached) spilled nodes
        continuing from the last resident digest — a chain whose tail was
        evicted to host RAM still matches end to end, and every full cached
        block before the first divergent block maps regardless of which
        tier holds it. The partial arm then reuses the leading run of the
        divergent block from a resident child (copy-on-write) or, failing
        that, from a spilled child (prefetch-on-write)."""
        fault_point("prefix_cache.match")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        cap = prompt.size - 1  # >= 1 token must be computed for logits
        bs = self.block_size
        with self._lock:
            nodes: List[ChainNode] = []
            parent_digest = _ROOT_DIGEST
            pos = 0
            while pos + bs <= cap:
                key = (parent_digest, prompt[pos : pos + bs].tobytes())
                node = self._nodes.get(key)
                if node is None:
                    break
                nodes.append(node)
                pos += bs
                parent_digest = node.digest
            # cross-tier continuation: keep walking the same recurrence over
            # spilled nodes (a spilled node never has a resident child, so
            # the walk never needs to come back to this tier)
            host_nodes: List[HostNode] = []
            host_pos = pos
            if self._tier is not None:
                while host_pos + bs <= cap:
                    hn = self._tier.lookup_pin(
                        parent_digest, prompt[host_pos : host_pos + bs].tobytes()
                    )
                    if hn is None:
                        break
                    host_nodes.append(hn)
                    host_pos += bs
                    parent_digest = hn.digest
            # the divergent-block arm: resident children fork copy-on-write;
            # past a host continuation (or with no resident candidate) a
            # spilled child serves the same partial via prefetch instead
            cow = None
            host_partial = None
            if not host_nodes:
                cow = self._match_partial_locked(prompt, pos, cap, parent_digest)
            if cow is None and self._tier is not None:
                remaining = prompt[host_pos : min(cap, host_pos + bs)]
                host_partial = self._tier.best_partial(parent_digest, remaining)
            for node in nodes:
                self._acquire_locked(node)
            cached = pos + (cow[2] if cow is not None else 0)
            host_hit = bool(host_nodes or host_partial)
            if cached > 0 or host_hit:
                self._hits += 1
                if host_hit:
                    self._host_hits += 1
                self._tokens_reused += cached
                self._metrics["hits"].labels(
                    tier="host" if host_hit else "hbm"
                ).inc()
                self._metrics["saved"].set(
                    self._tokens_reused * self.bytes_per_token
                )
            else:
                self._misses += 1
                self._metrics["misses"].inc()
            return MatchResult(nodes, cached, cow, host_nodes, host_partial)

    def record_host_reuse(self, tokens: int) -> None:
        """Fold successfully prefetched host-tier tokens into the reuse
        accounting (the engine calls this only once the H2D copies are
        issued — a degraded prefetch never inflates the savings)."""
        with self._lock:
            self._tokens_reused += int(tokens)
            self._metrics["saved"].set(
                self._tokens_reused * self.bytes_per_token
            )

    def release_host_pins(self, result: MatchResult) -> None:
        """Drop the prefetch pins a :meth:`match` took on host-tier nodes
        (after the engine issued the copies, or on any degrade path)."""
        if self._tier is None:
            return
        pinned = list(result.host_nodes)
        if result.host_partial is not None:
            pinned.append(result.host_partial[0])
        if pinned:
            self._tier.unpin(pinned)

    def _match_partial_locked(
        self,
        prompt: np.ndarray,
        pos: int,
        cap: int,
        parent_digest: bytes,
    ) -> Optional[Tuple[ChainNode, int, int]]:
        """The copy-on-write arm: the FIRST DIVERGENT block. The remaining
        prompt (a ragged tail, the one token held back by the cap, or a
        mid-block divergence) may share a leading run of tokens with some
        cached child block. Fork the child with the longest common prefix
        into a private copy so that cached KV is reused without
        recomputation and the divergent writes never touch the shared block.
        Returns ``(src_node, dst_block, partial_len)``."""
        remaining = prompt[pos : min(cap, pos + self.block_size)]
        if remaining.size < 1:
            return None
        src: Optional[ChainNode] = None
        best = 0
        for key in self._children.get(parent_digest, ()):
            node = self._nodes.get(key)
            if node is None:
                continue
            k = leading_run(np.frombuffer(node.token_bytes, np.int32), remaining)
            if k > best:
                best, src = k, node
        if src is None:
            return None
        remaining = remaining[:best]
        try:
            fault_point("prefix_cache.cow")
            dst = self._alloc_block_locked()
        except (InjectedFault, MemoryError) as exc:
            # CoW failure degrades to recompute — never to a failed request
            self._flight.record(
                "cow_fork_failed", error=f"{type(exc).__name__}: {exc}"[:120]
            )
            return None
        self._acquire_locked(src)  # pin the source until the copy executes
        self._cow_forks += 1
        return (src, dst, int(remaining.size))

    def _acquire_locked(self, node: ChainNode) -> None:
        if node.req_refs == 0:
            self._dead -= 1
        elif node.req_refs == 1:
            self._shared += 1
        node.req_refs += 1
        self._pool.incref(node.block)
        self._evictable.pop(node.key, None)

    def acquire(self, nodes: List[ChainNode]) -> None:
        """Re-take request references on an already-matched chain (recovery
        replay re-maps a live slot's chain through the same accounting)."""
        with self._lock:
            for node in nodes:
                self._acquire_locked(node)

    # -- insert (in-flight registration) -------------------------------------
    def insert(
        self,
        parent: Optional[ChainNode],
        tokens: np.ndarray,
        block: int,
    ) -> Optional[ChainNode]:
        """Register a request's freshly COMPUTED full block as a chain node
        (prompt blocks in-flight — later admissions match them immediately;
        generated-token blocks at request finish, after the last speculative
        commit, so only verified content is ever hashed). The cache becomes
        a co-owner of the physical block (pool incref); the request keeps
        its own reference. Returns None when the key already exists — two
        requests computed the same block concurrently; the caller keeps its
        copy private and the cache keeps the first."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        if tokens.size != self.block_size:
            raise ValueError(
                f"only full blocks are cacheable: got {tokens.size} tokens, "
                f"block_size={self.block_size}"
            )
        token_bytes = tokens.tobytes()
        parent_digest = parent.digest if parent is not None else _ROOT_DIGEST
        key = (parent_digest, token_bytes)
        with self._lock:
            if key in self._nodes:
                return None
            node = ChainNode(
                key, self._digest(parent_digest, token_bytes), int(block),
                parent, token_bytes,
            )
            node.req_refs = 1
            self._pool.incref(block)  # cache co-ownership
            if parent is not None:
                parent.child_refs += 1
                self._evictable.pop(parent.key, None)
            self._nodes[key] = node
            self._children.setdefault(parent_digest, []).append(key)
            return node

    # -- release / evict -----------------------------------------------------
    def release(self, nodes: List[ChainNode]) -> None:
        """Drop one request reference per node (finished/cancelled request).
        Blocks are NOT freed — zero-ref chain tails enter the LRU and stay
        warm until pressure evicts them."""
        with self._lock:
            for node in reversed(nodes):
                self._release_locked(node)

    def release_cow_source(self, node: ChainNode) -> None:
        """Drop the pin taken on a CoW fork's source once the device copy
        has executed."""
        with self._lock:
            self._release_locked(node)

    def _release_locked(self, node: ChainNode) -> None:
        if node.req_refs <= 0:
            raise RuntimeError(
                f"refcount underflow on cached block {node.block}"
            )
        node.req_refs -= 1
        if node.req_refs == 0:
            self._dead += 1
        elif node.req_refs == 1:
            self._shared -= 1
        self._pool.decref(node.block)
        if node.req_refs == 0 and node.child_refs == 0:
            self._evictable[node.key] = node  # most-recent end

    def evict_blocks(self, n: int) -> int:
        """Evict up to ``n`` zero-reference nodes, LRU-first, returning
        their physical blocks to the pool; cascades availability to parents
        whose last child left. Returns the number evicted."""
        with self._lock:
            return self._evict_locked(n)

    def _evict_locked(self, n: int) -> int:
        done = 0
        while done < n and self._evictable:
            _key, node = self._evictable.popitem(last=False)  # oldest
            self._drop_node_locked(node)
            done += 1
        if done:
            self._evictions += done
            self._metrics["evictions"].inc(done)
            self._flight.record("prefix_evict", blocks=done)
        return done

    def _try_spill_locked(self, node: ChainNode) -> None:
        """Spill an about-to-drop node's KV D2H into the host tier (runs
        BEFORE the pool reference is dropped, so the block cannot be
        reallocated and overwritten under the capture). Any failure —
        including an injected ``kv_tier.spill`` fault — degrades to the
        pre-tier behavior: the chain simply dies.

        The capture is a synchronous device read under the cache lock —
        deliberate: eviction happens mid-allocation (`_alloc_block_locked`
        pressure), and the freed block can be handed to a NEW owner inside
        the same critical section, whose writes would race a deferred
        capture. The cost is one small D2H per evicted block, serialized
        against intake-thread match()/stats calls only (the engine itself
        is driven by one pump thread)."""
        if self._tier is None or self._capture_kv is None:
            return
        try:
            ok = self._tier.put(
                node.key[0], node.digest, node.token_bytes,
                self._capture_kv(node.block),
            )
        except Exception as exc:  # noqa: BLE001 - spill failure = plain drop
            self._flight.record(
                "kv_spill_failed", block=node.block,
                error=f"{type(exc).__name__}: {exc}"[:120],
            )
            return
        if ok:
            self._spilled += 1
            self._flight.record("kv_spill", block=node.block)

    def _drop_node_locked(self, node: ChainNode) -> None:
        self._dead -= 1  # only dead nodes ever reach the eviction walk
        self._try_spill_locked(node)
        del self._nodes[node.key]
        siblings = self._children.get(node.key[0])
        if siblings is not None:
            siblings.remove(node.key)
            if not siblings:
                del self._children[node.key[0]]
        self._pool.decref(node.block)  # cache ownership drop; frees at zero
        parent = node.parent
        if parent is not None:
            parent.child_refs -= 1
            if parent.child_refs == 0 and parent.req_refs == 0:
                # the parent was pinned only by this child; it is OLDER than
                # anything in the LRU, so it goes to the eviction head
                self._evictable[parent.key] = parent
                self._evictable.move_to_end(parent.key, last=False)

    def _alloc_block_locked(self) -> int:
        """One private block for the CoW fork, evicting under pressure."""
        try:
            return self._pool.acquire_block()
        except MemoryError:
            if self._evict_locked(1) == 0:
                raise
            return self._pool.acquire_block()

    def alloc_private_block(self) -> int:
        """Allocate one request-private block, evicting zero-ref cached
        chains LRU-first under pressure — the engine's single allocation
        seam, so cache retention can never starve live requests."""
        with self._lock:
            return self._alloc_block_locked()

    def alloc_landing_blocks(self, n: int) -> List[int]:
        """Reserve ``n`` pool slots for prefetched host-tier blocks to land
        in, all-or-nothing: zero-ref cached chains are evicted (spilling in
        turn) until the pool can hand out all ``n`` atomically, and a
        shortfall raises MemoryError with NOTHING allocated — the prefetch
        degrade path never has partial state to unwind."""
        n = int(n)
        with self._lock:
            while self._pool.free_blocks < n:
                if self._evict_locked(1) == 0:
                    raise MemoryError(
                        f"cannot reserve {n} landing blocks: pool has "
                        f"{self._pool.free_blocks} free and nothing evictable"
                    )
            return self._pool.acquire_blocks(n)

    def update_shared_gauge(self) -> None:
        """Refresh the blocks-shared gauge (cheap; engine calls it at
        admit/release boundaries behind the metrics gate)."""
        if not _obs.metrics_enabled():
            return
        self._metrics["shared"].set(self.shared_block_count())
