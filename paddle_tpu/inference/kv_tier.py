"""Host-RAM KV spill tier under the prefix cache (hierarchical KV).

At millions-of-users scale the shared-prefix working set dwarfs one chip's
HBM: an LRU-evicted zero-reference chain in ``inference/prefix_cache.py``
used to simply die, and the next request paying for that prompt recomputed
it from token zero. This module is the second tier: a bounded host-memory
block store keyed by the SAME rolling ``(parent_digest, token_bytes)`` chain
keys the device cache uses, so chain digests span tiers seamlessly.

- **Spill.** When the device cache drops a zero-ref chain node under
  pressure, the engine captures that block's KV D2H and :meth:`HostKVTier
  .put`\\ s it here instead of discarding it. The tier has its own LRU over
  its own byte budget (``FLAGS_kv_host_tier_bytes``; 0 = tier off = the old
  drop-on-evict behavior). Entries are immutable once stored.
- **Match.** :meth:`PrefixCache.match`'s rolling-digest walk continues into
  this tier when the device walk runs out of resident nodes
  (:meth:`lookup_pin`), and the copy-on-write partial arm consults spilled
  children too (:meth:`best_partial`) — a prompt whose divergent block's
  source chain was spilled still reuses every token it can.
- **Prefetch.** Matched host blocks are copied H2D asynchronously into
  freshly reserved pool slots by the engine, overlapped with the mixed
  ragged step computing other slots' work; the scheduler gates the slot
  until the copies land. A prefetch that faults degrades to recompute with
  zero correctness impact (the tier entry is untouched).
- **Drop.** The tier's LRU evicts oldest-first under budget pressure and
  cascade-drops in-tier descendants of a dropped node (a child whose parent
  digest left the tier is unreachable by any future walk). Pinned entries
  (a prefetch in flight between match and copy-issue) are never dropped.

Both-tier residency is legal and common — a prefetched chain lives in HBM
*and* here — because contents are immutable and content-addressed: the same
digest always names the same KV bytes (pinned by the churn property test).

Fault sites ``kv_tier.spill`` (top of :meth:`put`) and ``kv_tier.prefetch``
(the engine's prefetch seam) make both failure paths deterministic: an
injected spill failure drops the chain (old behavior), an injected prefetch
failure degrades that request to recompute. Both are zero-cost when no
fault plan is installed.

Thread safety: one internal lock, ordered strictly BELOW the prefix cache's
(cache -> tier, never the reverse); the tier never calls back into the
cache or the pool.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from paddle_tpu.observability import metrics as _obs
from paddle_tpu.testing.faults import fault_point

__all__ = ["HostKVTier", "HostNode", "leading_run"]


def leading_run(cand: np.ndarray, remaining: np.ndarray) -> int:
    """Length of the leading token run ``remaining`` shares with candidate
    block ``cand`` — THE partial-match rule, shared by the device cache's
    copy-on-write arm and the host tier's prefetch-on-write arm so the two
    tiers can never disagree about how much of a divergent block is
    reusable."""
    cand = cand[: remaining.size]
    neq = np.nonzero(cand != remaining)[0]
    return int(neq[0]) if neq.size else int(remaining.size)


def _tier_metrics() -> Dict[str, Any]:
    """Get-or-create the host-tier metric families (process-global, like the
    prefix cache's). Recording is a no-op behind the registry's cached-bool
    gate when ``FLAGS_enable_metrics`` is off."""
    reg = _obs.GLOBAL_METRICS
    return {
        "spilled": reg.counter(
            "kv_tier_spilled_blocks_total",
            "Evicted chain blocks spilled D2H into the host tier instead of "
            "dropped.",
        ),
        "prefetched": reg.counter(
            "kv_tier_prefetched_blocks_total",
            "Host-tier blocks prefetched H2D into freshly reserved pool "
            "slots on a prefix match.",
        ),
        "dropped": reg.counter(
            "kv_tier_dropped_blocks_total",
            "Host-tier blocks dropped by its LRU (budget pressure, "
            "unreachable-descendant cascade, or an explicit drop).",
        ),
        "host_bytes": reg.gauge(
            "kv_tier_host_bytes",
            "Bytes of KV currently resident in the host tier.",
        ),
        "spilled_bytes": reg.counter(
            "kv_tier_spilled_bytes_total",
            "Bytes spilled D2H into the host tier (block count x the true "
            "per-block cost — a quantized pool spills packed int8+scale "
            "blocks at roughly half the bf16 bytes).",
        ),
        "prefetched_bytes": reg.counter(
            "kv_tier_prefetched_bytes_total",
            "Bytes prefetched H2D out of the host tier on prefix matches.",
        ),
    }


class HostNode:
    """One spilled full block of chain KV, resident in host RAM.

    ``key`` is the SAME ``(parent_digest, token_bytes)`` pair the device
    cache keys its chain nodes by, and ``digest`` the same rolling hash —
    a match walk crosses the tier boundary without re-deriving anything.
    ``kv`` is the captured ``[layers, 2, kv_heads, block_size, head_dim]``
    host array; it is IMMUTABLE once stored (prefetch H2D reads it, the
    LRU drops the reference — nothing ever writes it, which is what makes
    both-tier residency safe). ``pins`` guards the window between a match
    returning this node and the engine issuing its H2D copy."""

    __slots__ = ("key", "digest", "token_bytes", "kv", "pins")

    def __init__(
        self,
        key: Tuple[bytes, bytes],
        digest: bytes,
        token_bytes: bytes,
        kv: np.ndarray,
    ) -> None:
        self.key = key
        self.digest = digest
        self.token_bytes = token_bytes
        self.kv = kv
        self.pins = 0

    def tokens(self) -> np.ndarray:
        return np.frombuffer(self.token_bytes, np.int32)


class HostKVTier:
    """Bounded host-RAM store of spilled prefix-chain blocks.

    ``budget_bytes`` is the hard cap on resident KV bytes (the flag);
    ``block_nbytes`` the cost of one block across all layers
    (``2 * layers * kv_heads * block_size * head_dim * itemsize``)."""

    def __init__(self, budget_bytes: int, block_nbytes: int) -> None:
        self.budget_bytes = int(budget_bytes)
        self.block_nbytes = int(block_nbytes)
        self._lock = threading.Lock()
        # LRU: oldest first; prefetch hits touch to the MRU end
        self._entries: "OrderedDict[Tuple[bytes, bytes], HostNode]" = OrderedDict()
        # parent digest -> child keys, for the partial scan + drop cascade
        self._children: Dict[bytes, List[Tuple[bytes, bytes]]] = {}
        self._bytes = 0
        # host-side counters (always on — introspection must not depend on
        # the metrics flag); the metric families mirror them when enabled
        self._spilled = 0
        self._prefetched = 0
        self._dropped = 0
        self._refused = 0
        self._metrics = _tier_metrics()

    def set_replica_scope(self, scope: Any) -> None:
        """Re-bind the ``kv_tier_*`` families to a replica scope (see the
        engine's ``set_replica_scope``); resolved once."""
        self._metrics = scope.bind_all(_tier_metrics())

    # -- introspection -------------------------------------------------------
    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key: Tuple[bytes, bytes]) -> bool:
        with self._lock:
            return key in self._entries

    def stats_snapshot(self) -> Dict[str, Any]:
        """Cheap health view for /healthz and bench records (counters only —
        this runs on every serving pump tick)."""
        with self._lock:
            return {
                "budget_bytes": self.budget_bytes,
                "host_bytes": self._bytes,
                "blocks": len(self._entries),
                "spilled_blocks": self._spilled,
                "prefetched_blocks": self._prefetched,
                "dropped_blocks": self._dropped,
                "refused_spills": self._refused,
                "block_nbytes": self.block_nbytes,
                "spilled_bytes": self._spilled * self.block_nbytes,
                "prefetched_bytes": self._prefetched * self.block_nbytes,
            }

    # -- spill ---------------------------------------------------------------
    def put(
        self,
        parent_digest: bytes,
        digest: bytes,
        token_bytes: bytes,
        kv: np.ndarray,
    ) -> bool:
        """Store one evicted block's captured KV. Returns False when the
        block cannot fit (budget smaller than one block, or every resident
        entry is pinned) — the caller then drops the chain, exactly the
        pre-tier behavior. The fault site at the top models a failed D2H /
        allocation; an injected fault propagates to the caller's degrade
        path (chain dies, nothing half-stored)."""
        fault_point("kv_tier.spill")
        kv = np.asarray(kv)
        with self._lock:
            key = (parent_digest, token_bytes)
            node = self._entries.get(key)
            if node is not None:
                # same digest == same bytes (content-addressed, deterministic
                # recompute): the resident copy is already correct — touch it
                self._entries.move_to_end(key)
                return True
            if self.block_nbytes > self.budget_bytes:
                self._refused += 1
                return False
            while self._bytes + self.block_nbytes > self.budget_bytes:
                if not self._evict_one_locked():
                    self._refused += 1
                    return False
            self._entries[key] = HostNode(key, digest, token_bytes, kv)
            self._children.setdefault(parent_digest, []).append(key)
            self._bytes += self.block_nbytes
            self._spilled += 1
            self._metrics["spilled"].inc()
            self._metrics["spilled_bytes"].inc(float(self.block_nbytes))
            self._metrics["host_bytes"].set(self._bytes)
            return True

    # -- match ---------------------------------------------------------------
    def lookup_pin(
        self, parent_digest: bytes, token_bytes: bytes
    ) -> Optional[HostNode]:
        """One step of the cross-tier chain walk: the spilled child of
        ``parent_digest`` holding exactly ``token_bytes``, pinned against
        LRU drop until the engine issues (or abandons) its prefetch."""
        with self._lock:
            node = self._entries.get((parent_digest, token_bytes))
            if node is not None:
                node.pins += 1
                self._entries.move_to_end(node.key)
            return node

    def best_partial(
        self, parent_digest: bytes, remaining: np.ndarray
    ) -> Optional[Tuple[HostNode, int]]:
        """The spilled arm of partial-block suffix reuse: among the tier's
        children of ``parent_digest``, the one sharing the longest leading
        token run with ``remaining`` (the prompt's first divergent window).
        Returns ``(node, matched_tokens)`` with the node pinned, or None.
        This is what keeps the full-cached-blocks-before-the-divergence +
        partial-of-the-divergent-block match length intact even when the
        divergent block's source chain was spilled."""
        remaining = np.asarray(remaining, np.int32).reshape(-1)
        if remaining.size < 1:
            return None
        with self._lock:
            best_node: Optional[HostNode] = None
            best = 0
            for key in self._children.get(parent_digest, ()):
                node = self._entries.get(key)
                if node is None:
                    continue
                k = leading_run(node.tokens(), remaining)
                if k > best:
                    best, best_node = k, node
            if best_node is None:
                return None
            best_node.pins += 1
            self._entries.move_to_end(best_node.key)
            return best_node, best

    def unpin(self, nodes: List[HostNode]) -> None:
        """Release prefetch pins (issue completed, degraded, or abandoned)."""
        with self._lock:
            for node in nodes:
                if node.pins <= 0:
                    raise RuntimeError("host-tier pin underflow")
                node.pins -= 1

    def mark_prefetched(self, n_blocks: int) -> None:
        """Count ``n_blocks`` H2D prefetch copies issued by the engine."""
        with self._lock:
            self._prefetched += int(n_blocks)
        self._metrics["prefetched"].inc(int(n_blocks))
        self._metrics["prefetched_bytes"].inc(
            float(int(n_blocks) * self.block_nbytes)
        )

    # -- drop ----------------------------------------------------------------
    def drop_lru(self, n: int) -> int:
        """Explicitly drop up to ``n`` LRU entries (tests / external
        pressure ops); returns how many left, cascades included."""
        done = 0
        with self._lock:
            for _ in range(int(n)):
                before = len(self._entries)
                if not self._evict_one_locked():
                    break
                done += before - len(self._entries)
        return done

    def _evict_one_locked(self) -> bool:
        """Drop the oldest unpinned entry whose in-tier subtree is also
        unpinned, cascading its descendants (they become unreachable the
        moment their parent digest leaves the walk). Returns False when
        nothing is droppable (everything pinned or empty)."""
        for key in list(self._entries):
            node = self._entries[key]
            subtree = self._subtree_keys_locked(node)
            if any(self._entries[k].pins for k in subtree):
                continue
            for k in reversed(subtree):  # leaves first: children lists stay sane
                self._drop_locked(self._entries[k])
            return True
        return False

    def _subtree_keys_locked(
        self, node: HostNode
    ) -> List[Tuple[bytes, bytes]]:
        """``node`` plus every in-tier descendant, parents before children."""
        out = [node.key]
        i = 0
        while i < len(out):
            digest = self._entries[out[i]].digest
            out.extend(
                k for k in self._children.get(digest, ()) if k in self._entries
            )
            i += 1
        return out

    def _drop_locked(self, node: HostNode) -> None:
        del self._entries[node.key]
        siblings = self._children.get(node.key[0])
        if siblings is not None:
            siblings.remove(node.key)
            if not siblings:
                del self._children[node.key[0]]
        self._bytes -= self.block_nbytes
        self._dropped += 1
        self._metrics["dropped"].inc()
        self._metrics["host_bytes"].set(self._bytes)
