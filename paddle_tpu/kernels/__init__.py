"""Pallas TPU kernels — the hot ops where hand-scheduling beats XLA fusion
(SURVEY §7 stage 8): flash attention + FlashMask sparse-mask variant, fused
rms_norm and rotary embedding, and the fused linear+cross-entropy loss head.

Every kernel has an ``interpret=`` flag so numerics are testable on the CPU
backend; production selection happens in the ``paddle_tpu.nn.functional`` /
``paddle_tpu.incubate`` wrappers via ``FLAGS_use_pallas_attention`` /
``FLAGS_use_fused_loss``.
"""

from paddle_tpu.kernels.flash_attention import flash_attention_pallas  # noqa: F401
from paddle_tpu.kernels.flashmask import flashmask_attention_pallas  # noqa: F401
from paddle_tpu.kernels.fused import fused_rms_norm_pallas, fused_rope_pallas  # noqa: F401
from paddle_tpu.kernels.fused_loss import fused_linear_cross_entropy  # noqa: F401
