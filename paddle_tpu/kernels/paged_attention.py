"""Pallas TPU paged-attention decode kernel.

Replaces the dense-gather XLA path of
``incubate/nn/functional/block_attention.py`` (reference CUDA kernel:
``paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu``) with a
block-table-aware flash-decode kernel: each grid cell walks ONE sequence's
logical blocks, the scalar-prefetched block table steers the BlockSpec index
map so only that sequence's physical KV blocks are streamed HBM -> VMEM
(never the dense ``[B, MBS*BS, H, D]`` gather), and an online softmax
accumulates in fp32 VMEM scratch. Grouped-query attention keeps the G query
heads of one KV head together as the kernel's row dimension.

Quantized KV (``FLAGS_kv_cache_dtype=int8``): every kernel accepts optional
``k_scale``/``v_scale`` planes (``[NB, HKV, BS]`` fp32 — per block, per head,
per token slot, addressed by the SAME block ids the KV planes use), streamed
through the identical block-table-steered index map. The dequant epilogue
lives inside the block walk: int8 loads, one fp32 multiply per (BS, D) tile,
fp32 accumulate — no dequantized copy of the cache ever materializes. The
dequant composition (``x.astype(f32) * scale``) is the byte-for-byte op
sequence the XLA gather fallback applies, keeping the two paths in lockstep.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.export  # noqa: F401  (jax 0.4.x: not re-exported by `import jax`)
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

from paddle_tpu.kernels.select import _CompilerParams


def _dequant_tile(k_ref, v_ref, ks_ref, vs_ref):
    """The in-walk dequant epilogue shared by every paged kernel: one fp32
    multiply per (BS, D) tile against this block's per-token scale rows. The
    scale planes ride as [NB, HKV, BS, 1] (the trailing 1 keeps the (1, 1,
    bs, 1) block legal under the TPU last-two-dims tiling rule), so the
    [BS, 1] tile broadcasts over D. With no scale refs this is the plain
    fp32 upcast — the bf16 path's op sequence, untouched."""
    k = k_ref[0, 0].astype(jnp.float32)  # [BS, D]
    v = v_ref[0, 0].astype(jnp.float32)
    if ks_ref is not None:
        k = k * ks_ref[0, 0].astype(jnp.float32)  # [BS, 1] broadcast over D
        v = v * vs_ref[0, 0].astype(jnp.float32)
    return k, v


def _decode_kernel(
    tables_ref,  # scalar prefetch: [B, MBS] int32
    lens_ref,  # scalar prefetch: [B] int32 (length INCLUDING current token)
    q_ref,  # [1, 1, G, D]
    k_ref,  # [1, 1, BS, D] this logical block's physical KV (one head)
    v_ref,
    *rest,  # quantized: ks_ref, vs_ref [1, 1, BS] then outputs/scratch
    scale: float,
    block_size: int,
    num_blocks: int,
    quantized: bool = False,
):
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
        ks_ref = vs_ref = None
    bi = pl.program_id(0)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # ragged skip: a block whose first position is already past this
    # sequence's length contributes nothing (its p would be masked to 0), so
    # the MXU work is predicated away entirely. A fully-padded slot
    # (len == 0) never takes this branch at all — the engine's inactive batch
    # slots cost no compute, only the final zero-write below.
    @pl.when(i * block_size < lens_ref[bi])
    def _attend():
        q = q_ref[0, 0].astype(jnp.float32) * scale  # [G, D]
        k, v = _dequant_tile(k_ref, v_ref, ks_ref, vs_ref)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [G, BS]
        pos = i * block_size + jax.lax.broadcasted_iota(jnp.int32, (1, block_size), 1)
        valid = pos < lens_ref[bi]
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_ref[...]  # [G, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        # the explicit valid multiply keeps fully-masked rows at p == 0: with
        # every position masked, m_new == NEG_INF and exp(s - m_new) would be
        # 1 everywhere — silent garbage for zero-length sequences
        p = jnp.exp(s - m_new) * valid.astype(jnp.float32)  # [G, BS]
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[...] = m_new

    @pl.when(i == num_blocks - 1)
    def _finish():
        denom = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / denom).astype(o_ref.dtype)


@functools.lru_cache(maxsize=64)
def lowering_supported(b: int, hq: int, hkv: int, d: int, nb: int, bs: int, mbs: int,
                       dtype: str, kv_dtype: str = "") -> bool:
    """Static Mosaic-lowering probe, cached per geometry. A lowering error
    inside a captured (jitted) decode step is uncatchable at run time — this
    check runs host-side at TRACE time so the caller can route to the XLA
    path instead (same rule as the bench preflight). ``kv_dtype`` names the
    cache storage dtype when it differs from ``dtype`` (the quantized path);
    empty = cache stores ``dtype``, the historical geometry."""
    import numpy as np

    q = jax.ShapeDtypeStruct((b, hq, d), np.dtype(dtype))
    kc = jax.ShapeDtypeStruct((nb, hkv, bs, d), np.dtype(kv_dtype or dtype))
    tb = jax.ShapeDtypeStruct((b, mbs), np.int32)
    ln = jax.ShapeDtypeStruct((b,), np.int32)
    try:
        if kv_dtype:
            sc = jax.ShapeDtypeStruct((nb, hkv, bs), np.float32)
            jax.export.export(
                jax.jit(lambda q, kc, vc, ks, vs, t, l: paged_flash_decode(
                    q, kc, vc, t, l, k_scale=ks, v_scale=vs)),
                platforms=["tpu"],
            )(q, kc, kc, sc, sc, tb, ln)
        else:
            jax.export.export(
                jax.jit(lambda q, kc, vc, t, l: paged_flash_decode(q, kc, vc, t, l)),
                platforms=["tpu"],
            )(q, kc, kc, tb, ln)
        return True
    except Exception:  # noqa: BLE001 - any lowering failure means "don't"
        return False


def paged_flash_decode(
    q: jax.Array,  # [B, HQ, D]
    key_cache: jax.Array,  # [NB, HKV, BS, D]
    value_cache: jax.Array,
    block_tables: jax.Array,  # [B, MBS] int32
    seq_lens: jax.Array,  # [B] length INCLUDING the current token
    scale: Optional[float] = None,
    interpret: bool = False,
    k_scale: Optional[jax.Array] = None,  # [NB, HKV, BS] fp32 (int8 cache)
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Flash decode over the paged cache. Returns ``[B, HQ, D]``."""
    b, hq, d = q.shape
    nb, hkv, bs, _ = key_cache.shape
    mbs = block_tables.shape[1]
    if hq % hkv != 0:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    g = hq // hkv
    if scale is None:
        scale = 1.0 / (d**0.5)
    qg = q.reshape(b, hkv, g, d)
    quantized = k_scale is not None

    grid = (b, hkv, mbs)
    kernel = functools.partial(
        _decode_kernel, scale=float(scale), block_size=bs, num_blocks=mbs,
        quantized=quantized,
    )

    def _kv_index(bi, hi, i, tables, lens):
        # the block table steers which PHYSICAL block is streamed in; block
        # (1, 1, BS, D) tiles the (BS, D) plane of one head. Logical blocks
        # past the sequence's last in-use block are clamped onto that last
        # block: the pipeline sees the same physical index as the previous
        # grid step and skips the HBM->VMEM copy, so ragged tails (and fully
        # padded slots, which clamp to block-table entry 0) cost no DMA
        # traffic — the matching compute skip is the pl.when in the kernel.
        last = jnp.maximum((lens[bi] + bs - 1) // bs - 1, 0)
        return (tables[bi, jnp.minimum(i, last)], hi, 0, 0)

    def _scale_index(bi, hi, i, tables, lens):
        # the scale plane is addressed by the SAME physical block id
        last = jnp.maximum((lens[bi] + bs - 1) // bs - 1, 0)
        return (tables[bi, jnp.minimum(i, last)], hi, 0, 0)

    in_specs = [
        pl.BlockSpec((1, 1, g, d), lambda bi, hi, i, tables, lens: (bi, hi, 0, 0)),
        pl.BlockSpec((1, 1, bs, d), _kv_index),
        pl.BlockSpec((1, 1, bs, d), _kv_index),
    ]
    operands = [qg, key_cache, value_cache]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1, bs, 1), _scale_index),
            pl.BlockSpec((1, 1, bs, 1), _scale_index),
        ]
        operands += [k_scale[..., None], v_scale[..., None]]

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, 1, g, d), lambda bi, hi, i, tables, lens: (bi, hi, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((g, 1), jnp.float32),
                pltpu.VMEM((g, 1), jnp.float32),
                pltpu.VMEM((g, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
        # batch and kv-head cells are independent; the block walk accumulates
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), seq_lens.astype(jnp.int32), *operands)
    return out.reshape(b, hq, d)


# ---------------------------------------------------------------------------
# Ragged MIXED prefill/decode kernel (chunked prefill)
# ---------------------------------------------------------------------------
#
# One grid cell serves every new token of one sequence at once: the row
# dimension packs the chunk's C token positions x the G grouped query heads
# of one KV head, so a decode row (1 valid token) and a prompt-chunk row
# (up to C tokens) are the SAME kernel — the engine's single compiled
# signature. Each packed row carries its own causal limit
# (``seq_lens + j + 1`` for chunk token j), which is what makes the batch
# ragged rather than rectangular ("Ragged Paged Attention", arxiv
# 2604.15464).


def _chunk_kernel(
    tables_ref,  # scalar prefetch: [B, MBS] int32
    lens_ref,  # scalar prefetch: [B] int32 tokens cached BEFORE the chunk
    qlens_ref,  # scalar prefetch: [B] int32 valid new tokens (0 = skip row)
    q_ref,  # [1, 1, C*G, D] chunk-major packed rows (row = j*G + g)
    k_ref,  # [1, 1, BS, D] this logical block's physical KV (one head)
    v_ref,
    *rest,  # quantized: ks_ref, vs_ref [1, 1, BS] then outputs/scratch
    scale: float,
    block_size: int,
    num_blocks: int,
    group: int,
    quantized: bool = False,
):
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
        ks_ref = vs_ref = None
    bi = pl.program_id(0)
    i = pl.program_id(2)
    rows = q_ref.shape[2]

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # ragged skip: the LAST position any of this sequence's rows may see is
    # lens + q_lens - 1 (the chunk's final token attending to itself); blocks
    # wholly past it are predicated away — a decode row costs the same blocks
    # it did under the decode-only kernel, and an inactive slot (q_lens == 0)
    # never takes this branch at all.
    @pl.when(i * block_size < lens_ref[bi] + qlens_ref[bi])
    def _attend():
        q = q_ref[0, 0].astype(jnp.float32) * scale  # [C*G, D]
        k, v = _dequant_tile(k_ref, v_ref, ks_ref, vs_ref)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [C*G, BS]
        pos = i * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (rows, block_size), 1
        )
        # per-row causal limit: packed row r serves chunk token j = r // G at
        # absolute position lens + j, so it may see pos <= lens + j
        row_j = jax.lax.broadcasted_iota(jnp.int32, (rows, block_size), 0) // group
        valid = (pos < lens_ref[bi] + row_j + 1) & (row_j < qlens_ref[bi])
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_ref[...]  # [C*G, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        # the explicit valid multiply keeps fully-masked rows at p == 0 (a
        # row past q_lens has every position masked: exp(s - NEG_INF) would
        # otherwise be 1 everywhere — silent garbage)
        p = jnp.exp(s - m_new) * valid.astype(jnp.float32)  # [C*G, BS]
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[...] = m_new

    @pl.when(i == num_blocks - 1)
    def _finish():
        denom = jnp.maximum(l_ref[...], 1e-30)
        out = acc_ref[...] / denom  # [C*G, D]
        # rows past q_lens emitted exact zeros (their l stayed 0 -> out is
        # 0/1e-30 = 0 already via the masked p), but force it explicitly so
        # the contract does not hinge on the epsilon
        row_j = jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0) // group
        out = jnp.where(row_j < qlens_ref[bi], out, 0.0)
        o_ref[0, 0] = out.astype(o_ref.dtype)


@functools.lru_cache(maxsize=64)
def chunk_lowering_supported(b: int, c: int, hq: int, hkv: int, d: int, nb: int,
                             bs: int, mbs: int, dtype: str,
                             kv_dtype: str = "") -> bool:
    """Static Mosaic-lowering probe for the mixed prefill/decode kernel,
    cached per geometry (same rule as :func:`lowering_supported`)."""
    import numpy as np

    q = jax.ShapeDtypeStruct((b, c, hq, d), np.dtype(dtype))
    kc = jax.ShapeDtypeStruct((nb, hkv, bs, d), np.dtype(kv_dtype or dtype))
    tb = jax.ShapeDtypeStruct((b, mbs), np.int32)
    ln = jax.ShapeDtypeStruct((b,), np.int32)
    try:
        if kv_dtype:
            sc = jax.ShapeDtypeStruct((nb, hkv, bs), np.float32)
            jax.export.export(
                jax.jit(lambda q, kc, vc, ks, vs, t, l, ql: paged_flash_chunk(
                    q, kc, vc, t, l, ql, k_scale=ks, v_scale=vs)),
                platforms=["tpu"],
            )(q, kc, kc, sc, sc, tb, ln, ln)
        else:
            jax.export.export(
                jax.jit(
                    lambda q, kc, vc, t, l, ql: paged_flash_chunk(q, kc, vc, t, l, ql)
                ),
                platforms=["tpu"],
            )(q, kc, kc, tb, ln, ln)
        return True
    except Exception:  # noqa: BLE001 - any lowering failure means "don't"
        return False


def paged_flash_chunk(
    q: jax.Array,  # [B, C, HQ, D] ragged chunk (row j valid iff j < q_lens)
    key_cache: jax.Array,  # [NB, HKV, BS, D] chunk KV ALREADY appended
    value_cache: jax.Array,
    block_tables: jax.Array,  # [B, MBS] int32
    seq_lens: jax.Array,  # [B] tokens cached BEFORE the chunk
    q_lens: jax.Array,  # [B] valid new tokens (0 = inactive slot)
    scale: Optional[float] = None,
    interpret: bool = False,
    k_scale: Optional[jax.Array] = None,  # [NB, HKV, BS] fp32 (int8 cache)
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Flash attention for one mixed prefill/decode step over the paged
    cache. Returns ``[B, C, HQ, D]`` with rows past ``q_lens`` exactly 0."""
    b, c, hq, d = q.shape
    nb, hkv, bs, _ = key_cache.shape
    mbs = block_tables.shape[1]
    if hq % hkv != 0:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    g = hq // hkv
    if scale is None:
        scale = 1.0 / (d**0.5)
    # pack rows chunk-major per KV head: [B, C, HKV, G, D] -> [B, HKV, C*G, D]
    qg = q.reshape(b, c, hkv, g, d).transpose(0, 2, 1, 3, 4).reshape(b, hkv, c * g, d)
    quantized = k_scale is not None

    grid = (b, hkv, mbs)
    kernel = functools.partial(
        _chunk_kernel, scale=float(scale), block_size=bs, num_blocks=mbs,
        group=g, quantized=quantized,
    )

    def _kv_index(bi, hi, i, tables, lens, qlens):
        # logical blocks past the LAST in-use block (which now includes the
        # freshly appended chunk) clamp onto it: the pipeline sees the same
        # physical index as the previous grid step and skips the HBM->VMEM
        # copy, so ragged tails cost no DMA (the matching compute skip is the
        # pl.when in the kernel)
        last = jnp.maximum((lens[bi] + qlens[bi] + bs - 1) // bs - 1, 0)
        return (tables[bi, jnp.minimum(i, last)], hi, 0, 0)

    def _scale_index(bi, hi, i, tables, lens, qlens):
        # the scale plane is addressed by the SAME physical block id
        last = jnp.maximum((lens[bi] + qlens[bi] + bs - 1) // bs - 1, 0)
        return (tables[bi, jnp.minimum(i, last)], hi, 0, 0)

    in_specs = [
        pl.BlockSpec(
            (1, 1, c * g, d),
            lambda bi, hi, i, tables, lens, qlens: (bi, hi, 0, 0),
        ),
        pl.BlockSpec((1, 1, bs, d), _kv_index),
        pl.BlockSpec((1, 1, bs, d), _kv_index),
    ]
    operands = [qg, key_cache, value_cache]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1, bs, 1), _scale_index),
            pl.BlockSpec((1, 1, bs, 1), _scale_index),
        ]
        operands += [k_scale[..., None], v_scale[..., None]]

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, 1, c * g, d),
                lambda bi, hi, i, tables, lens, qlens: (bi, hi, 0, 0),
            ),
            scratch_shapes=[
                pltpu.VMEM((c * g, 1), jnp.float32),
                pltpu.VMEM((c * g, 1), jnp.float32),
                pltpu.VMEM((c * g, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, hkv, c * g, d), q.dtype),
        # batch and kv-head cells are independent; the block walk accumulates
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32),
        seq_lens.astype(jnp.int32),
        q_lens.astype(jnp.int32),
        *operands,
    )
    # [B, HKV, C*G, D] -> [B, C, HQ, D]
    return out.reshape(b, hkv, c, g, d).transpose(0, 2, 1, 3, 4).reshape(b, c, hq, d)


# ---------------------------------------------------------------------------
# Fused-epilogue variants: q-RoPE folded into the block walk
# ---------------------------------------------------------------------------
#
# The decode step's unfused path ropes q in a separate XLA elementwise pass —
# one extra HBM round-trip over [B, C, HQ, D] per layer just to feed the
# attention kernel. The *_fused kernels take the per-slot cos/sin rows
# (already offset-gathered, the per-batch tables the XLA path uses) as two
# extra VMEM inputs and apply the rotation to the q block in-register before
# the first dot. Numerics are LOCKSTEP with the unfused TPU path: the
# rotation is computed in q's dtype (exactly ``_rope_apply_xla`` with
# tables cast to x.dtype) and only THEN cast fp32 and scaled — so fused
# on/off stay byte-identical. KV is roped before the cache append (cache
# holds roped keys) in both modes; only q's rope moves into the kernel.


def _rope_rows(q, c, s, half):
    # neox rotate-half in q.dtype: q*cos + concat(-q2, q1)*sin
    q1 = q[..., :half]
    q2 = q[..., half:]
    rot = jnp.concatenate([-q2, q1], axis=-1)
    return q * c + rot * s


def _decode_fused_kernel(
    tables_ref,  # scalar prefetch: [B, MBS] int32
    lens_ref,  # scalar prefetch: [B] int32 (length INCLUDING current token)
    q_ref,  # [1, 1, G, D] pre-rope q
    cos_ref,  # [1, 1, D] this slot's rope row
    sin_ref,
    k_ref,  # [1, 1, BS, D]
    v_ref,
    *rest,  # quantized: ks_ref, vs_ref [1, 1, BS] then outputs/scratch
    scale: float,
    block_size: int,
    num_blocks: int,
    quantized: bool = False,
):
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
        ks_ref = vs_ref = None
    bi = pl.program_id(0)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(i * block_size < lens_ref[bi])
    def _attend():
        d = q_ref.shape[-1]
        g_rows = q_ref.shape[2]
        # materialize the [G, D] rope rows BEFORE the arithmetic — the same
        # op order the chunk kernel and the XLA rope composition lower to
        # (a [1, D] broadcast operand contracts differently and costs bitwise
        # parity with the unfused path)
        c = jnp.broadcast_to(cos_ref[0], (g_rows, d)).astype(q_ref.dtype)
        s_t = jnp.broadcast_to(sin_ref[0], (g_rows, d)).astype(q_ref.dtype)
        q = _rope_rows(q_ref[0, 0], c, s_t, d // 2)  # [G, D] in q.dtype
        q = q.astype(jnp.float32) * scale
        k, v = _dequant_tile(k_ref, v_ref, ks_ref, vs_ref)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        pos = i * block_size + jax.lax.broadcasted_iota(jnp.int32, (1, block_size), 1)
        valid = pos < lens_ref[bi]
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_ref[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new) * valid.astype(jnp.float32)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[...] = m_new

    @pl.when(i == num_blocks - 1)
    def _finish():
        denom = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / denom).astype(o_ref.dtype)


@functools.lru_cache(maxsize=64)
def decode_fused_lowering_supported(b: int, hq: int, hkv: int, d: int, nb: int,
                                    bs: int, mbs: int, dtype: str,
                                    kv_dtype: str = "") -> bool:
    """Static Mosaic-lowering probe for the rope-fused decode kernel (the
    lane-dim concat split can fail lowering for some D — same routing rule
    as :func:`lowering_supported`)."""
    import numpy as np

    q = jax.ShapeDtypeStruct((b, hq, d), np.dtype(dtype))
    cs = jax.ShapeDtypeStruct((b, 1, d), np.dtype(dtype))
    kc = jax.ShapeDtypeStruct((nb, hkv, bs, d), np.dtype(kv_dtype or dtype))
    tb = jax.ShapeDtypeStruct((b, mbs), np.int32)
    ln = jax.ShapeDtypeStruct((b,), np.int32)
    try:
        if kv_dtype:
            sc = jax.ShapeDtypeStruct((nb, hkv, bs), np.float32)
            jax.export.export(
                jax.jit(lambda q, c, s, kc, vc, ks, vs, t, l:
                        paged_flash_decode_fused(
                            q, c, s, kc, vc, t, l, k_scale=ks, v_scale=vs)),
                platforms=["tpu"],
            )(q, cs, cs, kc, kc, sc, sc, tb, ln)
        else:
            jax.export.export(
                jax.jit(
                    lambda q, c, s, kc, vc, t, l: paged_flash_decode_fused(
                        q, c, s, kc, vc, t, l
                    )
                ),
                platforms=["tpu"],
            )(q, cs, cs, kc, kc, tb, ln)
        return True
    except Exception:  # noqa: BLE001 - any lowering failure means "don't"
        return False


def paged_flash_decode_fused(
    q: jax.Array,  # [B, HQ, D] PRE-rope queries
    cos: jax.Array,  # [B, 1, D] offset-gathered rope rows
    sin: jax.Array,
    key_cache: jax.Array,  # [NB, HKV, BS, D] (keys already roped on append)
    value_cache: jax.Array,
    block_tables: jax.Array,
    seq_lens: jax.Array,
    scale: Optional[float] = None,
    interpret: bool = False,
    k_scale: Optional[jax.Array] = None,  # [NB, HKV, BS] fp32 (int8 cache)
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """:func:`paged_flash_decode` with q-RoPE folded into the block walk —
    one dispatch replaces the rope pass + attention pair."""
    b, hq, d = q.shape
    nb, hkv, bs, _ = key_cache.shape
    mbs = block_tables.shape[1]
    if hq % hkv != 0:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    g = hq // hkv
    if scale is None:
        scale = 1.0 / (d**0.5)
    qg = q.reshape(b, hkv, g, d)
    quantized = k_scale is not None

    kernel = functools.partial(
        _decode_fused_kernel, scale=float(scale), block_size=bs, num_blocks=mbs,
        quantized=quantized,
    )

    def _kv_index(bi, hi, i, tables, lens):
        last = jnp.maximum((lens[bi] + bs - 1) // bs - 1, 0)
        return (tables[bi, jnp.minimum(i, last)], hi, 0, 0)

    def _scale_index(bi, hi, i, tables, lens):
        last = jnp.maximum((lens[bi] + bs - 1) // bs - 1, 0)
        return (tables[bi, jnp.minimum(i, last)], hi, 0, 0)

    in_specs = [
        pl.BlockSpec((1, 1, g, d), lambda bi, hi, i, tables, lens: (bi, hi, 0, 0)),
        pl.BlockSpec((1, 1, d), lambda bi, hi, i, tables, lens: (bi, 0, 0)),
        pl.BlockSpec((1, 1, d), lambda bi, hi, i, tables, lens: (bi, 0, 0)),
        pl.BlockSpec((1, 1, bs, d), _kv_index),
        pl.BlockSpec((1, 1, bs, d), _kv_index),
    ]
    operands = [qg, cos, sin, key_cache, value_cache]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1, bs, 1), _scale_index),
            pl.BlockSpec((1, 1, bs, 1), _scale_index),
        ]
        operands += [k_scale[..., None], v_scale[..., None]]

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, hkv, mbs),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, 1, g, d), lambda bi, hi, i, tables, lens: (bi, hi, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((g, 1), jnp.float32),
                pltpu.VMEM((g, 1), jnp.float32),
                pltpu.VMEM((g, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32),
        seq_lens.astype(jnp.int32),
        *operands,
    )
    return out.reshape(b, hq, d)


def _chunk_fused_kernel(
    tables_ref,  # scalar prefetch: [B, MBS] int32
    lens_ref,  # scalar prefetch: [B] int32 tokens cached BEFORE the chunk
    qlens_ref,  # scalar prefetch: [B] int32 valid new tokens
    q_ref,  # [1, 1, C*G, D] chunk-major packed PRE-rope rows
    cos_ref,  # [1, C, D] this slot's offset-gathered rope rows
    sin_ref,
    k_ref,
    v_ref,
    *rest,  # quantized: ks_ref, vs_ref [1, 1, BS] then outputs/scratch
    scale: float,
    block_size: int,
    num_blocks: int,
    group: int,
    quantized: bool = False,
):
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
        ks_ref = vs_ref = None
    bi = pl.program_id(0)
    i = pl.program_id(2)
    rows = q_ref.shape[2]

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(i * block_size < lens_ref[bi] + qlens_ref[bi])
    def _attend():
        d = q_ref.shape[-1]
        c_dim = rows // group
        # expand [C, D] rope rows to the packed [C*G, D] row layout (row =
        # j*G + g shares token j's rotation across its G query heads)
        c = jnp.broadcast_to(
            cos_ref[0][:, None, :], (c_dim, group, d)
        ).reshape(rows, d).astype(q_ref.dtype)
        s_t = jnp.broadcast_to(
            sin_ref[0][:, None, :], (c_dim, group, d)
        ).reshape(rows, d).astype(q_ref.dtype)
        q = _rope_rows(q_ref[0, 0], c, s_t, d // 2)  # [C*G, D] in q.dtype
        q = q.astype(jnp.float32) * scale
        k, v = _dequant_tile(k_ref, v_ref, ks_ref, vs_ref)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        pos = i * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (rows, block_size), 1
        )
        row_j = jax.lax.broadcasted_iota(jnp.int32, (rows, block_size), 0) // group
        valid = (pos < lens_ref[bi] + row_j + 1) & (row_j < qlens_ref[bi])
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_ref[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new) * valid.astype(jnp.float32)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[...] = m_new

    @pl.when(i == num_blocks - 1)
    def _finish():
        denom = jnp.maximum(l_ref[...], 1e-30)
        out = acc_ref[...] / denom
        row_j = jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0) // group
        out = jnp.where(row_j < qlens_ref[bi], out, 0.0)
        o_ref[0, 0] = out.astype(o_ref.dtype)


@functools.lru_cache(maxsize=64)
def chunk_fused_lowering_supported(b: int, c: int, hq: int, hkv: int, d: int,
                                   nb: int, bs: int, mbs: int, dtype: str,
                                   kv_dtype: str = "") -> bool:
    """Static Mosaic-lowering probe for the rope-fused mixed kernel, cached
    per geometry (same rule as :func:`chunk_lowering_supported`)."""
    import numpy as np

    q = jax.ShapeDtypeStruct((b, c, hq, d), np.dtype(dtype))
    cs = jax.ShapeDtypeStruct((b, c, d), np.dtype(dtype))
    kc = jax.ShapeDtypeStruct((nb, hkv, bs, d), np.dtype(kv_dtype or dtype))
    tb = jax.ShapeDtypeStruct((b, mbs), np.int32)
    ln = jax.ShapeDtypeStruct((b,), np.int32)
    try:
        if kv_dtype:
            sc = jax.ShapeDtypeStruct((nb, hkv, bs), np.float32)
            jax.export.export(
                jax.jit(lambda q, c, s, kc, vc, ks, vs, t, l, ql:
                        paged_flash_chunk_fused(
                            q, c, s, kc, vc, t, l, ql, k_scale=ks, v_scale=vs)),
                platforms=["tpu"],
            )(q, cs, cs, kc, kc, sc, sc, tb, ln, ln)
        else:
            jax.export.export(
                jax.jit(
                    lambda q, c, s, kc, vc, t, l, ql: paged_flash_chunk_fused(
                        q, c, s, kc, vc, t, l, ql
                    )
                ),
                platforms=["tpu"],
            )(q, cs, cs, kc, kc, tb, ln, ln)
        return True
    except Exception:  # noqa: BLE001 - any lowering failure means "don't"
        return False


def paged_flash_chunk_fused(
    q: jax.Array,  # [B, C, HQ, D] PRE-rope ragged chunk
    cos: jax.Array,  # [B, C, D] offset-gathered rope rows per chunk token
    sin: jax.Array,
    key_cache: jax.Array,  # [NB, HKV, BS, D] (keys already roped on append)
    value_cache: jax.Array,
    block_tables: jax.Array,
    seq_lens: jax.Array,  # [B] tokens cached BEFORE the chunk
    q_lens: jax.Array,  # [B] valid new tokens (0 = inactive slot)
    scale: Optional[float] = None,
    interpret: bool = False,
    k_scale: Optional[jax.Array] = None,  # [NB, HKV, BS] fp32 (int8 cache)
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """:func:`paged_flash_chunk` with q-RoPE folded into the block walk —
    the decode layer's rope pass + attention collapse to ONE dispatch."""
    b, c, hq, d = q.shape
    nb, hkv, bs, _ = key_cache.shape
    mbs = block_tables.shape[1]
    if hq % hkv != 0:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    g = hq // hkv
    if scale is None:
        scale = 1.0 / (d**0.5)
    qg = q.reshape(b, c, hkv, g, d).transpose(0, 2, 1, 3, 4).reshape(b, hkv, c * g, d)
    quantized = k_scale is not None

    kernel = functools.partial(
        _chunk_fused_kernel, scale=float(scale), block_size=bs, num_blocks=mbs,
        group=g, quantized=quantized,
    )

    def _kv_index(bi, hi, i, tables, lens, qlens):
        last = jnp.maximum((lens[bi] + qlens[bi] + bs - 1) // bs - 1, 0)
        return (tables[bi, jnp.minimum(i, last)], hi, 0, 0)

    def _scale_index(bi, hi, i, tables, lens, qlens):
        last = jnp.maximum((lens[bi] + qlens[bi] + bs - 1) // bs - 1, 0)
        return (tables[bi, jnp.minimum(i, last)], hi, 0, 0)

    in_specs = [
        pl.BlockSpec(
            (1, 1, c * g, d),
            lambda bi, hi, i, tables, lens, qlens: (bi, hi, 0, 0),
        ),
        pl.BlockSpec(
            (1, c, d), lambda bi, hi, i, tables, lens, qlens: (bi, 0, 0)
        ),
        pl.BlockSpec(
            (1, c, d), lambda bi, hi, i, tables, lens, qlens: (bi, 0, 0)
        ),
        pl.BlockSpec((1, 1, bs, d), _kv_index),
        pl.BlockSpec((1, 1, bs, d), _kv_index),
    ]
    operands = [qg, cos, sin, key_cache, value_cache]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1, bs, 1), _scale_index),
            pl.BlockSpec((1, 1, bs, 1), _scale_index),
        ]
        operands += [k_scale[..., None], v_scale[..., None]]

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(b, hkv, mbs),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, 1, c * g, d),
                lambda bi, hi, i, tables, lens, qlens: (bi, hi, 0, 0),
            ),
            scratch_shapes=[
                pltpu.VMEM((c * g, 1), jnp.float32),
                pltpu.VMEM((c * g, 1), jnp.float32),
                pltpu.VMEM((c * g, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, hkv, c * g, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32),
        seq_lens.astype(jnp.int32),
        q_lens.astype(jnp.int32),
        *operands,
    )
    return out.reshape(b, hkv, c, g, d).transpose(0, 2, 1, 3, 4).reshape(b, c, hq, d)
