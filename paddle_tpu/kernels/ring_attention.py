"""Ring attention: context parallelism over a sequence-sharded mesh axis.

The reference snapshot has NO context-parallel attention (SURVEY §5.7: its
long-context strategy is FlashMask + Megatron-SP + a 'sep' axis whose
attention exchange is left to model code). This module goes beyond it: a
first-class blockwise ring attention — KV chunks rotate around the ICI ring
via ``lax.ppermute`` while each device accumulates online-softmax partial
results for its local Q chunk. Compute per step overlaps with the next
chunk's permute (XLA schedules the collective-permute concurrently), HBM
never holds more than the local chunk, and sequence length scales linearly
with the ring size.

Differentiable by construction: ``jax.grad`` through the scan + ppermute
yields the reversed ring for backward.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import shard_map

NEG_INF = -1e30

__all__ = ["ring_flash_attention"]


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Any,
    axis_name: str = "sep",
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """Ring attention over paddle layout ``[B, S, H, D]``.

    ``q``/``k``/``v`` are global-view arrays; the sequence dim is sharded over
    ``axis_name`` inside (inputs need not be pre-sharded — shard_map partitions
    them). Ring order IS sequence order: chunk c holds positions
    ``[c*S/N, (c+1)*S/N)``. Returns the global ``[B, S, H, D]`` output sharded
    the same way.
    """
    jmesh = mesh.jax_mesh() if hasattr(mesh, "jax_mesh") else mesh
    n = jmesh.shape[axis_name]
    b, s, h, d = q.shape
    hk = k.shape[2]
    if s % n != 0:
        raise ValueError(f"sequence length {s} not divisible by ring size {n}")
    if h % hk != 0:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hk}")
    if scale is None:
        scale = 1.0 / (d**0.5)
    if n == 1:
        from paddle_tpu.nn.functional.flash_attention import _xla_attention

        return _xla_attention(q, k, v, causal=causal, scale=scale)
    group = h // hk
    s_local = s // n
    perm = [(i, (i + 1) % n) for i in range(n)]

    spec = P(None, axis_name, None, None)

    def local_fn(q, k, v):
        # [B, S/N, H, D] → grouped [B, HK, G, S/N, D] fp32; KV stays at its
        # unrepeated head count so each ring hop moves only unique KV bytes
        qh = jnp.moveaxis(q, 2, 1).astype(jnp.float32) * scale
        qh = qh.reshape(b, hk, group, s_local, d)
        kh = jnp.moveaxis(k, 2, 1).astype(jnp.float32)  # [B, HK, S/N, D]
        vh = jnp.moveaxis(v, 2, 1).astype(jnp.float32)
        idx = jax.lax.axis_index(axis_name)
        rows = idx * s_local + jax.lax.broadcasted_iota(jnp.int32, (s_local, 1), 0)

        def partial_attn(carry, k_cur, v_cur, src):
            acc, m, l = carry
            logits = jnp.einsum("bhgqd,bhkd->bhgqk", qh, k_cur)
            if causal:
                cols = src * s_local + jax.lax.broadcasted_iota(
                    jnp.int32, (1, s_local), 1
                )
                logits = jnp.where(cols > rows, NEG_INF, logits)
            m_new = jnp.maximum(m, logits.max(axis=-1, keepdims=True))
            p = jnp.exp(logits - m_new)
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + p.sum(axis=-1, keepdims=True)
            acc_new = acc * alpha + jnp.einsum("bhgqk,bhkd->bhgqd", p, v_cur)
            return acc_new, m_new, l_new

        acc0 = jnp.zeros((b, hk, group, s_local, d), jnp.float32)
        m0 = jnp.full((b, hk, group, s_local, 1), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, hk, group, s_local, 1), jnp.float32)
        # tick 0: the local chunk, no communication
        carry0 = partial_attn((acc0, m0, l0), kh, vh, idx)

        def step(carry, t):
            k_cur, v_cur, acc, m, l = carry
            # rotate first: n-1 permutes total, none wasted
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
            src = (idx - t) % n  # whose chunk we hold this tick
            acc, m, l = partial_attn((acc, m, l), k_cur, v_cur, src)
            return (k_cur, v_cur, acc, m, l), None

        (_, _, acc, m, l), _ = jax.lax.scan(
            step, (kh, vh) + carry0, jnp.arange(1, n)
        )
        l = jnp.maximum(l, 1e-30)
        out = (acc / l).reshape(b, h, s_local, d).astype(q.dtype)
        return jnp.moveaxis(out, 1, 2)  # [B, S/N, H, D]

    return shard_map(
        local_fn,
        mesh=jmesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
