"""Fused elementwise Pallas kernels: rms_norm and rotary embedding.

Reference CUDA kernels: ``paddle/phi/kernels/gpu/rms_norm_kernel``,
``fused_rope_kernel.cu`` (``fused_ops.yaml:408``). XLA fuses these patterns
reasonably; the Pallas versions exist to pin the fusion (one HBM round-trip)
and as the base for bench-driven tuning. Both are differentiable: rms_norm
via custom VJP (recompute-rstd backward), rope via its jax-level composition
being linear in (x) and trig tables.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.kernels.select import _CompilerParams

__all__ = ["fused_rms_norm_pallas", "fused_rope_pallas", "rope_adjoint_pallas"]


def _rms_fwd_kernel(x_ref, w_ref, y_ref, rstd_ref, *, eps):
    x = x_ref[0].astype(jnp.float32)  # [blk_rows, H]
    w = w_ref[...].astype(jnp.float32)  # [H]
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    y_ref[0] = (x * rstd * w[None, :]).astype(y_ref.dtype)
    rstd_ref[0] = rstd[:, 0]


def _rms_bwd_kernel(x_ref, w_ref, rstd_ref, g_ref, dx_ref, dw_ref, *, eps):
    x = x_ref[0].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    g = g_ref[0].astype(jnp.float32)
    rstd = rstd_ref[0][:, None]
    xhat = x * rstd
    gw = g * w[None, :]
    # dx = rstd * (gw - xhat * mean(gw * xhat))
    dot = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx_ref[0] = (rstd * (gw - xhat * dot)).astype(dx_ref.dtype)
    # dw accumulates into ONE [1, h] block across the sequential TPU grid
    # (a per-block [nblk, h] partial would need an illegal (1, h) tile:
    # sublane 1 is neither 8-divisible nor equal to nblk)
    @pl.when(pl.program_id(0) == 0)
    def _init():
        dw_ref[0] = jnp.zeros_like(dw_ref[0])

    dw_ref[0] += jnp.sum(g * xhat, axis=0)


@functools.lru_cache(maxsize=None)
def _make_rms(rows, h, eps, blk_rows, interpret):
    grid = (rows // blk_rows,)

    def run_fwd(x, w):
        return pl.pallas_call(
            functools.partial(_rms_fwd_kernel, eps=eps),
            grid=grid,
            # independent row blocks: megacore-splittable
            compiler_params=_CompilerParams(dimension_semantics=("parallel",)),
            in_specs=[
                pl.BlockSpec((1, blk_rows, h), lambda i: (0, i, 0)),
                pl.BlockSpec((h,), lambda i: (0,)),
            ],
            out_specs=[
                pl.BlockSpec((1, blk_rows, h), lambda i: (0, i, 0)),
                pl.BlockSpec((1, blk_rows), lambda i: (0, i)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((1, rows, h), x.dtype),
                jax.ShapeDtypeStruct((1, rows), jnp.float32),
            ],
            interpret=interpret,
        )(x, w)

    @jax.custom_vjp
    def core(x, w):
        y, _ = run_fwd(x, w)
        return y

    def core_fwd(x, w):
        y, rstd = run_fwd(x, w)
        return y, (x, w, rstd)

    def core_bwd(res, g):
        x, w, rstd = res
        dx, dw = pl.pallas_call(
            functools.partial(_rms_bwd_kernel, eps=eps),
            grid=grid,
            # dw accumulates across the grid in one output block: the grid
            # MUST run sequentially ("arbitrary"), never be split
            compiler_params=_CompilerParams(dimension_semantics=("arbitrary",)),
            in_specs=[
                pl.BlockSpec((1, blk_rows, h), lambda i: (0, i, 0)),
                pl.BlockSpec((h,), lambda i: (0,)),
                pl.BlockSpec((1, blk_rows), lambda i: (0, i)),
                pl.BlockSpec((1, blk_rows, h), lambda i: (0, i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, blk_rows, h), lambda i: (0, i, 0)),
                pl.BlockSpec((1, h), lambda i: (0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((1, rows, h), x.dtype),
                jax.ShapeDtypeStruct((1, h), jnp.float32),
            ],
            interpret=interpret,
        )(x, w, rstd, g)
        return dx, dw[0].astype(w.dtype)

    core.defvjp(core_fwd, core_bwd)
    return core


def fused_rms_norm_pallas(
    x: jax.Array, weight: jax.Array, epsilon: float = 1e-6, interpret: bool = False
) -> jax.Array:
    """RMSNorm over the last axis; any leading shape."""
    h = x.shape[-1]
    lead = x.shape[:-1]
    rows = 1
    for s in lead:
        rows *= s
    blk = _autotune_rms_rows(rows, h, x.dtype, float(epsilon), bool(interpret))
    pad = (-rows) % blk
    x2 = x.reshape(1, rows, h)
    if pad:
        x2 = jnp.pad(x2, ((0, 0), (0, pad), (0, 0)))
    core = _make_rms(rows + pad, h, float(epsilon), blk, bool(interpret))
    y = core(x2, weight)
    return y[0, :rows].reshape(*lead, h)


def _autotune_rms_rows(rows: int, h: int, dtype, eps: float, interpret: bool) -> int:
    """Benchmark-pick the row-block for rms_norm at this shape (reference
    ``auto_tune_base.h:48``); 128 when tuning is off."""
    from paddle_tpu.kernels.autotune import autotune

    key = (rows, h, str(dtype))

    def build(blk):
        pad = (-rows) % blk
        xz = jnp.zeros((1, rows + pad, h), dtype)
        wz = jnp.zeros((h,), dtype)
        core = _make_rms(rows + pad, h, eps, blk, interpret)
        return lambda: core(xz, wz)

    picked = autotune("fused_rms_norm", key, (128, 256, 512, 1024), build, default=128)
    return int(picked)


def _rope_kernel(x_ref, cos_ref, sin_ref, y_ref):
    x = x_ref[0, 0].astype(jnp.float32)  # [S, D]
    cos = cos_ref[0].astype(jnp.float32)  # [S, D]
    sin = sin_ref[0].astype(jnp.float32)
    d = x.shape[-1]
    x1 = x[:, : d // 2]
    x2 = x[:, d // 2 :]
    rot = jnp.concatenate([-x2, x1], axis=-1)
    y_ref[0, 0] = (x * cos + rot * sin).astype(y_ref.dtype)


def _rope_bwd_kernel(g_ref, cos_ref, sin_ref, dx_ref):
    # y = x⊙cos + rot(x)⊙sin with rot([x1,x2]) = [-x2, x1]. The adjoint of
    # rot is unrot([v1,v2]) = [v2, -v1], so dx = g⊙cos + unrot(g⊙sin):
    #   dx1 = g1·cos1 + g2·sin2 ; dx2 = g2·cos2 − g1·sin1
    # (exact even when the two sin halves differ — no table-symmetry
    # assumption). Reference: fused_rope_grad_kernel.cu (fused_ops.yaml:408).
    g = g_ref[0, 0].astype(jnp.float32)  # [S, D]
    cos = cos_ref[0].astype(jnp.float32)
    sin = sin_ref[0].astype(jnp.float32)
    d = g.shape[-1]
    gs = g * sin
    v1 = gs[:, : d // 2]
    v2 = gs[:, d // 2 :]
    unrot = jnp.concatenate([v2, -v1], axis=-1)
    dx_ref[0, 0] = (g * cos + unrot).astype(dx_ref.dtype)


@functools.lru_cache(maxsize=None)
def _make_rope_runner(bh, s, d, interpret):
    """One (batch*head)-gridded rope-shaped pallas_call launcher, shared by
    the forward and the adjoint kernels (identical specs, different body)."""
    grid = (bh,)
    in_specs = [
        pl.BlockSpec((1, 1, s, d), lambda i: (i, 0, 0, 0)),
        pl.BlockSpec((1, s, d), lambda i: (0, 0, 0)),
        pl.BlockSpec((1, s, d), lambda i: (0, 0, 0)),
    ]
    out_spec = pl.BlockSpec((1, 1, s, d), lambda i: (i, 0, 0, 0))

    def run(kernel, xh, cos2, sin2):
        return pl.pallas_call(
            kernel,
            grid=grid,
            # independent (batch*head) cells
            compiler_params=_CompilerParams(dimension_semantics=("parallel",)),
            in_specs=in_specs,
            out_specs=out_spec,
            out_shape=jax.ShapeDtypeStruct((bh, 1, s, d), xh.dtype),
            interpret=interpret,
        )(xh, cos2, sin2)

    return run


@functools.lru_cache(maxsize=None)
def _make_rope(bh, s, d, interpret):
    run = _make_rope_runner(bh, s, d, interpret)

    @jax.custom_vjp
    def core(xh, cos2, sin2):
        return run(_rope_kernel, xh, cos2, sin2)

    def core_fwd(xh, cos2, sin2):
        return run(_rope_kernel, xh, cos2, sin2), (xh, cos2, sin2)

    def core_bwd(res, g):
        xh, cos2, sin2 = res
        dx = run(_rope_bwd_kernel, g, cos2, sin2)
        # Table cotangents: trig tables are constants in every real model, so
        # XLA dead-code-eliminates these sums; computed exactly for parity.
        gf = g.astype(jnp.float32)
        xf = xh.astype(jnp.float32)
        dcos = jnp.sum(gf * xf, axis=0)  # [1, S, D]
        x1 = xf[..., : d // 2]
        x2 = xf[..., d // 2 :]
        rot = jnp.concatenate([-x2, x1], axis=-1)
        dsin = jnp.sum(gf * rot, axis=0)
        return dx, dcos.astype(cos2.dtype), dsin.astype(sin2.dtype)

    core.defvjp(core_fwd, core_bwd)
    return core


def fused_rope_pallas(
    x: jax.Array, cos: jax.Array, sin: jax.Array, interpret: bool = False
) -> jax.Array:
    """Rotate-half rotary embedding. ``x`` [B, S, H, D]; cos/sin [S, D].

    Differentiable: custom VJP with a Pallas backward kernel (the bwd is a
    rope with the rotation adjoint applied to g⊙sin).
    """
    b, s, h, d = x.shape
    xh = jnp.moveaxis(x, 2, 1).reshape(b * h, 1, s, d)  # grid over B*H
    cos2 = cos.reshape(1, s, d)
    sin2 = sin.reshape(1, s, d)
    core = _make_rope(b * h, s, d, bool(interpret))
    y = core(xh, cos2, sin2)
    return jnp.moveaxis(y.reshape(b, h, s, d), 1, 2)


def rope_adjoint_pallas(
    g: jax.Array, cos: jax.Array, sin: jax.Array, interpret: bool = False
) -> jax.Array:
    """Adjoint of :func:`fused_rope_pallas` w.r.t. ``x`` as ONE standalone
    Pallas kernel: ``dx = g⊙cos + unrot(g⊙sin)``. The framework tape's rope
    op calls this directly in its backward (no jax-level differentiation of
    any ``pallas_call`` ever happens on the train path — the fix for the r03
    "Linearization failed" fallback), so it must stay callable outside any
    AD transform. ``g`` [B, S, H, D]; cos/sin [S, D]."""
    b, s, h, d = g.shape
    gh = jnp.moveaxis(g, 2, 1).reshape(b * h, 1, s, d)
    cos2 = cos.reshape(1, s, d)
    sin2 = sin.reshape(1, s, d)
    run = _make_rope_runner(b * h, s, d, bool(interpret))
    dx = run(_rope_bwd_kernel, gh, cos2, sin2)
    return jnp.moveaxis(dx.reshape(b, h, s, d), 1, 2)
