"""Fused elementwise Pallas kernels: rms_norm and rotary embedding.

Reference CUDA kernels: ``paddle/phi/kernels/gpu/rms_norm_kernel``,
``fused_rope_kernel.cu`` (``fused_ops.yaml:408``). XLA fuses these patterns
reasonably; the Pallas versions exist to pin the fusion (one HBM round-trip)
and as the base for bench-driven tuning. Both are differentiable: rms_norm
via custom VJP (recompute-rstd backward), rope via its jax-level composition
being linear in (x) and trig tables.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.kernels.select import _CompilerParams

__all__ = [
    "fused_rms_norm_pallas",
    "fused_rope_pallas",
    "rope_adjoint_pallas",
    "fused_rms_norm_residual_pallas",
    "rms_norm_residual_adjoint_pallas",
    "fused_layer_norm_residual_pallas",
    "layer_norm_residual_adjoint_pallas",
    "fused_embed_rms_norm_pallas",
    "arm_dispatch_probe",
    "disarm_dispatch_probe",
    "count_dispatch",
]


# ---------------------------------------------------------------------------
# Trace-time dispatch probe
# ---------------------------------------------------------------------------
#
# The fused-decode-layer work exists to cut dispatches per layer per step, so
# the win must be observable: model code calls ``count_dispatch(site)`` at
# every kernel-dispatch site of the paged serving path (both the fused and
# the unfused variants). The calls run at TRACE time only — the Python body
# of a jitted step executes once per compile, the same property the engine's
# ``step_traces`` counter rides — so an armed probe records exactly one count
# per dispatch site per compiled program, and a disarmed probe costs one
# ``is None`` check. Tests and bench.py arm it around an engine's first step.

_DISPATCH_PROBE: Optional[dict] = None


def arm_dispatch_probe() -> None:
    """Start recording dispatch sites (clears any previous counts)."""
    global _DISPATCH_PROBE
    _DISPATCH_PROBE = {}


def disarm_dispatch_probe() -> dict:
    """Stop recording; returns {site: count} seen since arming."""
    global _DISPATCH_PROBE
    out = _DISPATCH_PROBE or {}
    _DISPATCH_PROBE = None
    return out


def count_dispatch(site: str) -> None:
    """Record one dispatch-site hit (no-op unless the probe is armed)."""
    if _DISPATCH_PROBE is not None:
        _DISPATCH_PROBE[site] = _DISPATCH_PROBE.get(site, 0) + 1


def _rms_fwd_kernel(x_ref, w_ref, y_ref, rstd_ref, *, eps):
    x = x_ref[0].astype(jnp.float32)  # [blk_rows, H]
    w = w_ref[...].astype(jnp.float32)  # [H]
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    y_ref[0] = (x * rstd * w[None, :]).astype(y_ref.dtype)
    rstd_ref[0] = rstd[:, 0]


def _rms_bwd_kernel(x_ref, w_ref, rstd_ref, g_ref, dx_ref, dw_ref, *, eps):
    x = x_ref[0].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    g = g_ref[0].astype(jnp.float32)
    rstd = rstd_ref[0][:, None]
    xhat = x * rstd
    gw = g * w[None, :]
    # dx = rstd * (gw - xhat * mean(gw * xhat))
    dot = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx_ref[0] = (rstd * (gw - xhat * dot)).astype(dx_ref.dtype)
    # dw accumulates into ONE [1, h] block across the sequential TPU grid
    # (a per-block [nblk, h] partial would need an illegal (1, h) tile:
    # sublane 1 is neither 8-divisible nor equal to nblk)
    @pl.when(pl.program_id(0) == 0)
    def _init():
        dw_ref[0] = jnp.zeros_like(dw_ref[0])

    dw_ref[0] += jnp.sum(g * xhat, axis=0)


@functools.lru_cache(maxsize=None)
def _make_rms(rows, h, eps, blk_rows, interpret):
    grid = (rows // blk_rows,)

    def run_fwd(x, w):
        return pl.pallas_call(
            functools.partial(_rms_fwd_kernel, eps=eps),
            grid=grid,
            # independent row blocks: megacore-splittable
            compiler_params=_CompilerParams(dimension_semantics=("parallel",)),
            in_specs=[
                pl.BlockSpec((1, blk_rows, h), lambda i: (0, i, 0)),
                pl.BlockSpec((h,), lambda i: (0,)),
            ],
            out_specs=[
                pl.BlockSpec((1, blk_rows, h), lambda i: (0, i, 0)),
                pl.BlockSpec((1, blk_rows), lambda i: (0, i)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((1, rows, h), x.dtype),
                jax.ShapeDtypeStruct((1, rows), jnp.float32),
            ],
            interpret=interpret,
        )(x, w)

    @jax.custom_vjp
    def core(x, w):
        y, _ = run_fwd(x, w)
        return y

    def core_fwd(x, w):
        y, rstd = run_fwd(x, w)
        return y, (x, w, rstd)

    def core_bwd(res, g):
        x, w, rstd = res
        dx, dw = pl.pallas_call(
            functools.partial(_rms_bwd_kernel, eps=eps),
            grid=grid,
            # dw accumulates across the grid in one output block: the grid
            # MUST run sequentially ("arbitrary"), never be split
            compiler_params=_CompilerParams(dimension_semantics=("arbitrary",)),
            in_specs=[
                pl.BlockSpec((1, blk_rows, h), lambda i: (0, i, 0)),
                pl.BlockSpec((h,), lambda i: (0,)),
                pl.BlockSpec((1, blk_rows), lambda i: (0, i)),
                pl.BlockSpec((1, blk_rows, h), lambda i: (0, i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, blk_rows, h), lambda i: (0, i, 0)),
                pl.BlockSpec((1, h), lambda i: (0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((1, rows, h), x.dtype),
                jax.ShapeDtypeStruct((1, h), jnp.float32),
            ],
            interpret=interpret,
        )(x, w, rstd, g)
        return dx, dw[0].astype(w.dtype)

    core.defvjp(core_fwd, core_bwd)
    return core


def fused_rms_norm_pallas(
    x: jax.Array, weight: jax.Array, epsilon: float = 1e-6, interpret: bool = False
) -> jax.Array:
    """RMSNorm over the last axis; any leading shape."""
    h = x.shape[-1]
    lead = x.shape[:-1]
    rows = 1
    for s in lead:
        rows *= s
    blk = _autotune_rms_rows(rows, h, x.dtype, float(epsilon), bool(interpret))
    pad = (-rows) % blk
    x2 = x.reshape(1, rows, h)
    if pad:
        x2 = jnp.pad(x2, ((0, 0), (0, pad), (0, 0)))
    core = _make_rms(rows + pad, h, float(epsilon), blk, bool(interpret))
    y = core(x2, weight)
    return y[0, :rows].reshape(*lead, h)


def _autotune_rms_rows(rows: int, h: int, dtype, eps: float, interpret: bool) -> int:
    """Benchmark-pick the row-block for rms_norm at this shape (reference
    ``auto_tune_base.h:48``); 128 when tuning is off."""
    from paddle_tpu.kernels.autotune import autotune

    key = (rows, h, str(dtype))

    def build(blk):
        pad = (-rows) % blk
        xz = jnp.zeros((1, rows + pad, h), dtype)
        wz = jnp.zeros((h,), dtype)
        core = _make_rms(rows + pad, h, eps, blk, interpret)
        return lambda: core(xz, wz)

    picked = autotune("fused_rms_norm", key, (128, 256, 512, 1024), build, default=128)
    return int(picked)


def _rope_kernel(x_ref, cos_ref, sin_ref, y_ref):
    x = x_ref[0, 0].astype(jnp.float32)  # [S, D]
    cos = cos_ref[0].astype(jnp.float32)  # [S, D]
    sin = sin_ref[0].astype(jnp.float32)
    d = x.shape[-1]
    x1 = x[:, : d // 2]
    x2 = x[:, d // 2 :]
    rot = jnp.concatenate([-x2, x1], axis=-1)
    y_ref[0, 0] = (x * cos + rot * sin).astype(y_ref.dtype)


def _rope_bwd_kernel(g_ref, cos_ref, sin_ref, dx_ref):
    # y = x⊙cos + rot(x)⊙sin with rot([x1,x2]) = [-x2, x1]. The adjoint of
    # rot is unrot([v1,v2]) = [v2, -v1], so dx = g⊙cos + unrot(g⊙sin):
    #   dx1 = g1·cos1 + g2·sin2 ; dx2 = g2·cos2 − g1·sin1
    # (exact even when the two sin halves differ — no table-symmetry
    # assumption). Reference: fused_rope_grad_kernel.cu (fused_ops.yaml:408).
    g = g_ref[0, 0].astype(jnp.float32)  # [S, D]
    cos = cos_ref[0].astype(jnp.float32)
    sin = sin_ref[0].astype(jnp.float32)
    d = g.shape[-1]
    gs = g * sin
    v1 = gs[:, : d // 2]
    v2 = gs[:, d // 2 :]
    unrot = jnp.concatenate([v2, -v1], axis=-1)
    dx_ref[0, 0] = (g * cos + unrot).astype(dx_ref.dtype)


@functools.lru_cache(maxsize=None)
def _make_rope_runner(bh, s, d, interpret):
    """One (batch*head)-gridded rope-shaped pallas_call launcher, shared by
    the forward and the adjoint kernels (identical specs, different body)."""
    grid = (bh,)
    in_specs = [
        pl.BlockSpec((1, 1, s, d), lambda i: (i, 0, 0, 0)),
        pl.BlockSpec((1, s, d), lambda i: (0, 0, 0)),
        pl.BlockSpec((1, s, d), lambda i: (0, 0, 0)),
    ]
    out_spec = pl.BlockSpec((1, 1, s, d), lambda i: (i, 0, 0, 0))

    def run(kernel, xh, cos2, sin2):
        return pl.pallas_call(
            kernel,
            grid=grid,
            # independent (batch*head) cells
            compiler_params=_CompilerParams(dimension_semantics=("parallel",)),
            in_specs=in_specs,
            out_specs=out_spec,
            out_shape=jax.ShapeDtypeStruct((bh, 1, s, d), xh.dtype),
            interpret=interpret,
        )(xh, cos2, sin2)

    return run


@functools.lru_cache(maxsize=None)
def _make_rope(bh, s, d, interpret):
    run = _make_rope_runner(bh, s, d, interpret)

    @jax.custom_vjp
    def core(xh, cos2, sin2):
        return run(_rope_kernel, xh, cos2, sin2)

    def core_fwd(xh, cos2, sin2):
        return run(_rope_kernel, xh, cos2, sin2), (xh, cos2, sin2)

    def core_bwd(res, g):
        xh, cos2, sin2 = res
        dx = run(_rope_bwd_kernel, g, cos2, sin2)
        # Table cotangents: trig tables are constants in every real model, so
        # XLA dead-code-eliminates these sums; computed exactly for parity.
        gf = g.astype(jnp.float32)
        xf = xh.astype(jnp.float32)
        dcos = jnp.sum(gf * xf, axis=0)  # [1, S, D]
        x1 = xf[..., : d // 2]
        x2 = xf[..., d // 2 :]
        rot = jnp.concatenate([-x2, x1], axis=-1)
        dsin = jnp.sum(gf * rot, axis=0)
        return dx, dcos.astype(cos2.dtype), dsin.astype(sin2.dtype)

    core.defvjp(core_fwd, core_bwd)
    return core


def fused_rope_pallas(
    x: jax.Array, cos: jax.Array, sin: jax.Array, interpret: bool = False
) -> jax.Array:
    """Rotate-half rotary embedding. ``x`` [B, S, H, D]; cos/sin [S, D].

    Differentiable: custom VJP with a Pallas backward kernel (the bwd is a
    rope with the rotation adjoint applied to g⊙sin).
    """
    b, s, h, d = x.shape
    xh = jnp.moveaxis(x, 2, 1).reshape(b * h, 1, s, d)  # grid over B*H
    cos2 = cos.reshape(1, s, d)
    sin2 = sin.reshape(1, s, d)
    core = _make_rope(b * h, s, d, bool(interpret))
    y = core(xh, cos2, sin2)
    return jnp.moveaxis(y.reshape(b, h, s, d), 1, 2)


def rope_adjoint_pallas(
    g: jax.Array, cos: jax.Array, sin: jax.Array, interpret: bool = False
) -> jax.Array:
    """Adjoint of :func:`fused_rope_pallas` w.r.t. ``x`` as ONE standalone
    Pallas kernel: ``dx = g⊙cos + unrot(g⊙sin)``. The framework tape's rope
    op calls this directly in its backward (no jax-level differentiation of
    any ``pallas_call`` ever happens on the train path — the fix for the r03
    "Linearization failed" fallback), so it must stay callable outside any
    AD transform. ``g`` [B, S, H, D]; cos/sin [S, D]."""
    b, s, h, d = g.shape
    gh = jnp.moveaxis(g, 2, 1).reshape(b * h, 1, s, d)
    cos2 = cos.reshape(1, s, d)
    sin2 = sin.reshape(1, s, d)
    run = _make_rope_runner(b * h, s, d, bool(interpret))
    dx = run(_rope_bwd_kernel, gh, cos2, sin2)
    return jnp.moveaxis(dx.reshape(b, h, s, d), 1, 2)


# ---------------------------------------------------------------------------
# Fused residual-add + norm epilogues (decode-layer fusion)
# ---------------------------------------------------------------------------
#
# The decode step's per-layer epilogue is `r = x + residual; y = norm(r)` —
# two bandwidth-bound HBM round-trips that these kernels collapse into one
# (read x/residual once, write y and the new residual stream once). Numerics
# are LOCKSTEP with the XLA composition the flag-off path runs: the residual
# add happens in the IO dtype, rms_norm accumulates fp32 and multiplies by
# the weight AFTER the downcast (exactly ``nn.functional.common.rms_norm``'s
# order). The backward is a STANDALONE adjoint kernel (rstd/mean recomputed
# from the saved residual stream) that the incubate entries' explicit tape
# GradNode calls directly — no jax AD ever sees these pallas_calls.


def _rms_res_fwd_kernel(x_ref, res_ref, w_ref, y_ref, r_ref, *, eps):
    r = x_ref[0] + res_ref[0]  # residual add in the IO dtype (XLA lockstep)
    r_ref[0] = r
    xf = r.astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    # fp32 weight multiply BEFORE the downcast — the same order as
    # _rms_fwd_kernel, so fused on/off stay bitwise-matched on TPU where the
    # unfused path runs that kernel
    y_ref[0] = (xf * rstd * w[None, :]).astype(y_ref.dtype)


def _rms_res_bwd_kernel(r_ref, w_ref, g_ref, dx_ref, dw_ref, *, eps):
    r = r_ref[0].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    g = g_ref[0].astype(jnp.float32)
    ms = jnp.mean(r * r, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    xhat = r * rstd
    gw = g * w[None, :]
    dot = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx_ref[0] = (rstd * (gw - xhat * dot)).astype(dx_ref.dtype)

    # dw accumulates into ONE [1, h] block across the sequential grid (the
    # same rule as _rms_bwd_kernel: a per-block partial would need an
    # illegal (1, h) sublane tile)
    @pl.when(pl.program_id(0) == 0)
    def _init():
        dw_ref[0] = jnp.zeros_like(dw_ref[0])

    dw_ref[0] += jnp.sum(g * xhat, axis=0)


def _ln_res_fwd_kernel(x_ref, res_ref, w_ref, b_ref, y_ref, r_ref, *, eps):
    r = x_ref[0] + res_ref[0]
    r_ref[0] = r
    xf = r.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    y = y * w_ref[...].astype(jnp.float32)[None, :] + b_ref[...].astype(jnp.float32)[None, :]
    y_ref[0] = y.astype(y_ref.dtype)


def _ln_res_bwd_kernel(r_ref, w_ref, g_ref, dx_ref, dw_ref, db_ref, *, eps):
    r = r_ref[0].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    g = g_ref[0].astype(jnp.float32)
    mu = jnp.mean(r, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(r - mu), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (r - mu) * rstd
    gw = g * w[None, :]
    m1 = jnp.mean(gw, axis=-1, keepdims=True)
    m2 = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx_ref[0] = (rstd * (gw - m1 - xhat * m2)).astype(dx_ref.dtype)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        dw_ref[0] = jnp.zeros_like(dw_ref[0])
        db_ref[0] = jnp.zeros_like(db_ref[0])

    dw_ref[0] += jnp.sum(g * xhat, axis=0)
    db_ref[0] += jnp.sum(g, axis=0)


def _row_block(kernel: str, rows: int, h: int, dtype) -> int:
    """Benchmark-pick the row block for a residual+norm kernel at this shape
    (same candidate set the plain rms_norm tune sweeps); 128 when tuning is
    off. Registered per kernel name so the fwd and adjoint shapes tune
    independently of the plain fused_rms_norm entry."""
    from paddle_tpu.kernels.autotune import autotune

    key = (rows, h, str(dtype))

    def build(blk):
        pad = (-rows) % blk
        if kernel.endswith("_bwd"):
            def run():
                g = jnp.zeros((1, rows + pad, h), dtype)
                r = jnp.zeros((1, rows + pad, h), dtype)
                w = jnp.zeros((h,), dtype)
                if kernel.startswith("fused_rms"):
                    return _rms_res_adjoint_call(g, r, w, 1e-6, blk, False)
                return _ln_res_adjoint_call(g, r, w, 1e-6, blk, False)
            return run

        def run():
            x = jnp.zeros((1, rows + pad, h), dtype)
            w = jnp.zeros((h,), dtype)
            if kernel.startswith("fused_rms"):
                return _rms_res_fwd_call(x, x, w, 1e-6, blk, False)
            return _ln_res_fwd_call(x, x, w, jnp.zeros((h,), dtype), 1e-6, blk, False)
        return run

    return int(autotune(kernel, key, (128, 256, 512, 1024), build, default=128))


def _rms_res_fwd_call(x2, res2, w, eps, blk, interpret):
    rows, h = x2.shape[1], x2.shape[2]
    spec = pl.BlockSpec((1, blk, h), lambda i: (0, i, 0))
    return pl.pallas_call(
        functools.partial(_rms_res_fwd_kernel, eps=eps),
        grid=(rows // blk,),
        compiler_params=_CompilerParams(dimension_semantics=("parallel",)),
        in_specs=[spec, spec, pl.BlockSpec((h,), lambda i: (0,))],
        out_specs=[spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct((1, rows, h), x2.dtype),
            jax.ShapeDtypeStruct((1, rows, h), x2.dtype),
        ],
        interpret=interpret,
    )(x2, res2, w)


def _rms_res_adjoint_call(g2, r2, w, eps, blk, interpret):
    rows, h = g2.shape[1], g2.shape[2]
    spec = pl.BlockSpec((1, blk, h), lambda i: (0, i, 0))
    return pl.pallas_call(
        functools.partial(_rms_res_bwd_kernel, eps=eps),
        grid=(rows // blk,),
        # dw accumulates across the grid: sequential, never megacore-split
        compiler_params=_CompilerParams(dimension_semantics=("arbitrary",)),
        in_specs=[spec, pl.BlockSpec((h,), lambda i: (0,)), spec],
        out_specs=[spec, pl.BlockSpec((1, h), lambda i: (0, 0))],
        out_shape=[
            jax.ShapeDtypeStruct((1, rows, h), g2.dtype),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
        ],
        interpret=interpret,
    )(r2, w, g2)


def _ln_res_fwd_call(x2, res2, w, b, eps, blk, interpret):
    rows, h = x2.shape[1], x2.shape[2]
    spec = pl.BlockSpec((1, blk, h), lambda i: (0, i, 0))
    wspec = pl.BlockSpec((h,), lambda i: (0,))
    return pl.pallas_call(
        functools.partial(_ln_res_fwd_kernel, eps=eps),
        grid=(rows // blk,),
        compiler_params=_CompilerParams(dimension_semantics=("parallel",)),
        in_specs=[spec, spec, wspec, wspec],
        out_specs=[spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct((1, rows, h), x2.dtype),
            jax.ShapeDtypeStruct((1, rows, h), x2.dtype),
        ],
        interpret=interpret,
    )(x2, res2, w, b)


def _ln_res_adjoint_call(g2, r2, w, eps, blk, interpret):
    rows, h = g2.shape[1], g2.shape[2]
    spec = pl.BlockSpec((1, blk, h), lambda i: (0, i, 0))
    return pl.pallas_call(
        functools.partial(_ln_res_bwd_kernel, eps=eps),
        grid=(rows // blk,),
        compiler_params=_CompilerParams(dimension_semantics=("arbitrary",)),
        in_specs=[spec, pl.BlockSpec((h,), lambda i: (0,)), spec],
        out_specs=[
            spec,
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, rows, h), g2.dtype),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
        ],
        interpret=interpret,
    )(r2, w, g2)


def _pad_rows(x, rows, pad):
    x2 = x.reshape(1, rows, x.shape[-1])
    if pad:
        x2 = jnp.pad(x2, ((0, 0), (0, pad), (0, 0)))
    return x2


def fused_rms_norm_residual_pallas(
    x: jax.Array, residual: jax.Array, weight: jax.Array,
    epsilon: float = 1e-6, interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """``r = x + residual; y = rms_norm(r, weight)`` in ONE kernel.
    Returns ``(y, r)``; any leading shape, norm over the last axis."""
    h = x.shape[-1]
    lead = x.shape[:-1]
    rows = 1
    for s in lead:
        rows *= s
    blk = _row_block("fused_rms_norm_residual", rows, h, x.dtype)
    pad = (-rows) % blk
    y, r = _rms_res_fwd_call(
        _pad_rows(x, rows, pad), _pad_rows(residual, rows, pad), weight,
        float(epsilon), blk, bool(interpret),
    )
    return y[0, :rows].reshape(*lead, h), r[0, :rows].reshape(*lead, h)


def rms_norm_residual_adjoint_pallas(
    g: jax.Array, r: jax.Array, weight: jax.Array,
    epsilon: float = 1e-6, interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Adjoint of the norm half of :func:`fused_rms_norm_residual_pallas`
    w.r.t. its pre-norm input ``r`` (the saved residual stream) as ONE
    standalone kernel: ``(d_r, d_weight)`` given the y-cotangent ``g``.
    The residual add's adjoint is the identity, so the caller's tape node
    forwards ``d_r`` (plus any residual-stream cotangent) to both x and
    residual. rstd is recomputed from ``r`` — nothing but forward outputs is
    saved, and no jax AD transform ever touches the pallas_call."""
    h = g.shape[-1]
    lead = g.shape[:-1]
    rows = 1
    for s in lead:
        rows *= s
    blk = _row_block("fused_rms_norm_residual_bwd", rows, h, g.dtype)
    pad = (-rows) % blk
    dx, dw = _rms_res_adjoint_call(
        _pad_rows(g, rows, pad), _pad_rows(r, rows, pad), weight,
        float(epsilon), blk, bool(interpret),
    )
    return dx[0, :rows].reshape(*lead, h), dw[0].astype(weight.dtype)


def fused_layer_norm_residual_pallas(
    x: jax.Array, residual: jax.Array, weight: jax.Array,
    bias: Optional[jax.Array] = None, epsilon: float = 1e-5,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """``r = x + residual; y = layer_norm(r, weight, bias)`` in ONE kernel
    (fp32 accumulation). Returns ``(y, r)``."""
    h = x.shape[-1]
    lead = x.shape[:-1]
    rows = 1
    for s in lead:
        rows *= s
    if bias is None:
        bias = jnp.zeros((h,), x.dtype)
    blk = _row_block("fused_layer_norm_residual", rows, h, x.dtype)
    pad = (-rows) % blk
    y, r = _ln_res_fwd_call(
        _pad_rows(x, rows, pad), _pad_rows(residual, rows, pad), weight, bias,
        float(epsilon), blk, bool(interpret),
    )
    return y[0, :rows].reshape(*lead, h), r[0, :rows].reshape(*lead, h)


def layer_norm_residual_adjoint_pallas(
    g: jax.Array, r: jax.Array, weight: jax.Array,
    epsilon: float = 1e-5, interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Standalone adjoint of :func:`fused_layer_norm_residual_pallas`'s norm
    half: ``(d_r, d_weight, d_bias)`` given the y-cotangent (mean/var
    recomputed from the saved residual stream; same tape contract as
    :func:`rms_norm_residual_adjoint_pallas`)."""
    h = g.shape[-1]
    lead = g.shape[:-1]
    rows = 1
    for s in lead:
        rows *= s
    blk = _row_block("fused_layer_norm_residual_bwd", rows, h, g.dtype)
    pad = (-rows) % blk
    dx, dw, db = _ln_res_adjoint_call(
        _pad_rows(g, rows, pad), _pad_rows(r, rows, pad), weight,
        float(epsilon), blk, bool(interpret),
    )
    return (
        dx[0, :rows].reshape(*lead, h),
        dw[0].astype(weight.dtype),
        db[0].astype(weight.dtype),
    )


# ---------------------------------------------------------------------------
# Fused token-gather + embedding lookup + first-layer norm (chunk-step entry)
# ---------------------------------------------------------------------------


def _embed_rms_kernel(ids_ref, row_ref, w_ref, emb_ref, y_ref, *, eps):
    # ids_ref is the scalar-prefetched token vector that already steered this
    # grid cell's row_ref block onto the right embedding row — the gather IS
    # the BlockSpec index map, so the dense [N, V] one-hot / XLA gather
    # round-trip never materializes. One cell = one token row.
    row = row_ref[...]  # [1, H] embedding row, table dtype
    emb_ref[...] = row.astype(emb_ref.dtype)
    xf = row.astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    # same op order as _rms_fwd_kernel (bitwise-matched vs the unfused path)
    y_ref[...] = (xf * jax.lax.rsqrt(ms + eps) * w[None, :]).astype(y_ref.dtype)


def fused_embed_rms_norm_pallas(
    ids: jax.Array,  # [B, C] int32 token ids
    table: jax.Array,  # [V, H] embedding table
    weight: jax.Array,  # [H] first-layer rms_norm weight
    epsilon: float = 1e-6,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Chunk-step entry fusion: token-id gather + embedding row load + the
    first decoder layer's pre-attention RMSNorm in ONE dispatch. The
    scalar-prefetched ids steer the BlockSpec index map (the same trick the
    paged-attention block table plays), so each grid cell streams exactly its
    token's [1, H] row HBM -> VMEM and writes the raw embedding (the layer
    loop's residual stream) plus its normed form. Returns ``(emb, y)``, both
    ``[B, C, H]`` in the table dtype. Inference-only (the serving step) —
    there is no backward; training embeds through the regular op."""
    b, c = ids.shape
    v, h = table.shape
    n = b * c
    flat = jnp.clip(ids.reshape(n).astype(jnp.int32), 0, v - 1)
    row_spec = pl.BlockSpec((1, h), lambda i, ids: (ids[i], 0))
    out_spec = pl.BlockSpec((1, h), lambda i, ids: (i, 0))
    emb, y = pl.pallas_call(
        functools.partial(_embed_rms_kernel, eps=float(epsilon)),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n,),
            in_specs=[row_spec, pl.BlockSpec((h,), lambda i, ids: (0,))],
            out_specs=[out_spec, out_spec],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((n, h), table.dtype),
            jax.ShapeDtypeStruct((n, h), table.dtype),
        ],
        # token cells are independent: megacore-splittable
        compiler_params=_CompilerParams(dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(flat, table, weight)
    return emb.reshape(b, c, h), y.reshape(b, c, h)
