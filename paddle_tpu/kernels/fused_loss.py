"""Fused linear + softmax-cross-entropy loss head, vocab-chunked.

The training loss head is the single largest bandwidth sink in a causal-LM
step: ``lm_head`` materializes ``[B·S, V]`` logits, the fp32 upcast copies
them, and ``log_softmax`` allocates a third buffer — at the bench config
(16k tokens, 32k vocab) that is ~2 GB of pure HBM traffic per copy, dwarfing
any single matmul. This module computes ``cross_entropy(x @ Wᵀ, labels)``
without ever materializing ``[N, V]`` in any dtype, in the style of flash
attention's online softmax:

- **forward** streams vocab blocks of ``x @ W_blockᵀ`` through VMEM keeping a
  per-token online max/sum (fp32) plus the target-class logit (gathered per
  block; ``ignore_index`` rows simply never match), then finishes with
  ``loss = logsumexp - target_logit`` reduced exactly like
  ``F.cross_entropy`` (mean over non-ignored tokens, ``max(count, 1)``);
- **backward** recomputes each block's logits from the saved logsumexp and
  emits ``(softmax - onehot) * dloss`` block-wise, accumulating ``dX`` (row
  blocks) and ``dW`` (vocab blocks) in two Pallas kernels — the flash-attn-2
  dq/dkv split, so each output is only ever revisited on consecutive grid
  steps;
- a ``lax.scan``-over-vocab-chunks reference with the SAME custom-VJP
  decomposition (pure jnp) runs on CPU / in tier-1 / as the fallback, so the
  numerics are pinned off-TPU. (Differentiating *through* a scan would stash
  every chunk's logits — exactly the ``[N, V]`` buffer this kernel exists to
  avoid — hence the custom VJP on both paths.)

Weight layouts: ``vocab_major=False`` is ``nn.Linear`` 's ``[H, V]``
(untied lm_head); ``vocab_major=True`` is the embedding's ``[V, H]``
(tied lm_head, the ``matmul(out, embed.weight, transpose_y=True)`` branch).
Both fuse without a transpose — only BlockSpec index maps and dot dims
change.

Selection: ``FLAGS_use_fused_loss`` + TPU backend picks the Pallas kernels
(vocab/row block sizes autotuned per shape, ``kernels/autotune.py``); any
Pallas failure falls back to the scan reference through
``kernels.select.warn_fallback`` (counted in
``paddle_tpu_kernel_fallbacks_total``).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from paddle_tpu.kernels.select import _CompilerParams, pallas_enabled, warn_fallback

__all__ = ["fused_linear_cross_entropy"]

NEG_INF = -1e30
_REF_BLOCK = 512  # scan-reference vocab chunk; any value works, numerics-pinning only


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


# --------------------------------------------------------------------------
# shared custom-VJP shell: epilogue (reduction) + per-row grad coefficient
# --------------------------------------------------------------------------


def _build_core(engine_fwd, engine_bwd, ignore_index, reduction):
    """Wrap a (fwd, bwd) engine pair in the custom VJP both paths share.

    Engine contract (all row-count-N arrays are 1-D f32 unless noted):
    ``engine_fwd(x2, wp, lab) -> (lse, target_logit)`` and
    ``engine_bwd(x2, wp, lab, lse, gcoef) -> (dx, dw)`` with ``dx`` in
    ``x2.dtype`` ``[N, H]`` and ``dw`` in ``wp``'s dtype and layout. The
    shell owns the reduction semantics (identical to ``F.cross_entropy``)
    and the ``ignore_index`` masking, so the Pallas and scan paths cannot
    drift apart on them.
    """

    def _loss(per, valid):
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
            return jnp.sum(per) / denom
        if reduction == "sum":
            return jnp.sum(per)
        return per

    @jax.custom_vjp
    def core(x2, wp, lab):
        lse, tl = engine_fwd(x2, wp, lab)
        valid = lab != ignore_index
        return _loss(jnp.where(valid, lse - tl, 0.0), valid)

    def core_fwd(x2, wp, lab):
        lse, tl = engine_fwd(x2, wp, lab)
        valid = lab != ignore_index
        loss = _loss(jnp.where(valid, lse - tl, 0.0), valid)
        # residuals: inputs + the [N] logsumexp only — never [N, V]
        return loss, (x2, wp, lab, lse)

    def core_bwd(res, g):
        x2, wp, lab, lse = res
        valid = lab != ignore_index
        g = g.astype(jnp.float32)
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
            g_row = (g / denom) * jnp.ones_like(lse)
        elif reduction == "sum":
            g_row = g * jnp.ones_like(lse)
        else:
            g_row = g  # [N] cotangent for reduction="none"
        gcoef = jnp.where(valid, g_row, 0.0)
        dx, dw = engine_bwd(x2, wp, lab, lse, gcoef)
        # integer labels carry no gradient (float0 cotangent)
        return dx, dw, np.zeros(lab.shape, jax.dtypes.float0)

    core.defvjp(core_fwd, core_bwd)
    return core


# --------------------------------------------------------------------------
# lax.scan reference engine (CPU / tier-1 / fallback)
# --------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _make_ref_core(v, h, blk, ignore_index, reduction):
    """Pure-jnp engines over vocab-major padded weights ``[nv*blk, H]``."""
    nv = (v + blk - 1) // blk

    def engine_fwd(x2, wp, lab):
        wb = wp.reshape(nv, blk, h)
        cols0 = jnp.arange(blk)
        n = x2.shape[0]

        def step(carry, inp):
            m, l, tl = carry
            wj, j = inp
            logits = jax.lax.dot_general(
                x2, wj, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )  # [N, blk]
            cols = j * blk + cols0
            logits = jnp.where((cols < v)[None, :], logits, NEG_INF)
            m_new = jnp.maximum(m, logits.max(axis=-1))
            l_new = l * jnp.exp(m - m_new) + jnp.exp(logits - m_new[:, None]).sum(axis=-1)
            tl_new = tl + jnp.where(cols[None, :] == lab[:, None], logits, 0.0).sum(axis=-1)
            return (m_new, l_new, tl_new), None

        init = (
            jnp.full((n,), NEG_INF, jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32),
        )
        (m, l, tl), _ = jax.lax.scan(step, init, (wb, jnp.arange(nv)))
        return m + jnp.log(l), tl

    def engine_bwd(x2, wp, lab, lse, gcoef):
        wb = wp.reshape(nv, blk, h)
        cols0 = jnp.arange(blk)

        def step(dx, inp):
            wj, j = inp
            logits = jax.lax.dot_general(
                x2, wj, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            cols = j * blk + cols0
            p = jnp.exp(logits - lse[:, None])
            p = jnp.where((cols < v)[None, :], p, 0.0)  # zero-padded W rows: kill exp(-lse)
            onehot = (cols[None, :] == lab[:, None]).astype(jnp.float32)
            d = ((p - onehot) * gcoef[:, None]).astype(x2.dtype)
            dx = dx + jax.lax.dot_general(
                d, wj, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
            )
            dwj = jax.lax.dot_general(
                d, x2, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
            )
            return dx, dwj.astype(wp.dtype)

        dx, dwb = jax.lax.scan(
            step, jnp.zeros((x2.shape[0], h), jnp.float32), (wb, jnp.arange(nv))
        )
        return dx.astype(x2.dtype), dwb.reshape(nv * blk, h)

    return _build_core(engine_fwd, engine_bwd, ignore_index, reduction)


def _reference_path(x2, w, lab, *, v, h, ignore_index, reduction, vocab_major):
    # canonicalize to vocab-major [V, H] + zero-pad the ragged tail; both ops
    # sit OUTSIDE the custom VJP so their transposes run in reverse for dW
    wc = w if vocab_major else jnp.swapaxes(w, 0, 1)
    vp = _round_up(v, _REF_BLOCK)
    wp = jnp.pad(wc, ((0, vp - v), (0, 0))) if vp > v else wc
    core = _make_ref_core(v, h, _REF_BLOCK, ignore_index, reduction)
    return core(x2, wp, lab)


# --------------------------------------------------------------------------
# weight-only int8 lm-head variant (inference-only: no VJP)
# --------------------------------------------------------------------------


def _quant_epilogue(lse, tl, lab, ignore_index, reduction):
    """Same reduction semantics as ``_build_core``'s shell — duplicated here
    because the quantized walk is forward-only (weight-only int8 is an
    inference feature; nothing differentiates through an int8 weight)."""
    valid = lab != ignore_index
    per = jnp.where(valid, lse - tl, 0.0)
    if reduction == "mean":
        denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
        return jnp.sum(per) / denom
    if reduction == "sum":
        return jnp.sum(per)
    return per


def _reference_quant_path(x2, w, scale, lab, *, v, h, ignore_index, reduction, vocab_major):
    """Scan walk over int8 vocab chunks, dequantizing each chunk's LOGITS
    (``(x @ w8ᵀ) * scale_col`` — the per-output-channel scale factors out of
    the contraction, same canonical composition as ``kernels.quant``). The
    dequantized weight is never materialized."""
    wc = w if vocab_major else jnp.swapaxes(w, 0, 1)  # [V, H] int8
    vp = _round_up(v, _REF_BLOCK)
    sp = scale.astype(jnp.float32)
    if vp > v:
        wc = jnp.pad(wc, ((0, vp - v), (0, 0)))
        sp = jnp.pad(sp, (0, vp - v))
    nv = vp // _REF_BLOCK
    wb = wc.reshape(nv, _REF_BLOCK, h)
    sb = sp.reshape(nv, _REF_BLOCK)
    cols0 = jnp.arange(_REF_BLOCK)
    n = x2.shape[0]
    xf = x2.astype(jnp.float32)

    def step(carry, inp):
        m, l, tl = carry
        wj, sj, j = inp
        logits = jax.lax.dot_general(
            xf, wj.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sj[None, :]
        cols = j * _REF_BLOCK + cols0
        logits = jnp.where((cols < v)[None, :], logits, NEG_INF)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        l_new = l * jnp.exp(m - m_new) + jnp.exp(logits - m_new[:, None]).sum(axis=-1)
        tl_new = tl + jnp.where(cols[None, :] == lab[:, None], logits, 0.0).sum(axis=-1)
        return (m_new, l_new, tl_new), None

    init = (
        jnp.full((n,), NEG_INF, jnp.float32),
        jnp.zeros((n,), jnp.float32),
        jnp.zeros((n,), jnp.float32),
    )
    (m, l, tl), _ = jax.lax.scan(step, init, (wb, sb, jnp.arange(nv)))
    return _quant_epilogue(m + jnp.log(l), tl, lab, ignore_index, reduction)


# --------------------------------------------------------------------------
# Pallas kernels
# --------------------------------------------------------------------------


def _flxent_fwd_kernel(x_ref, w_ref, lab_ref, *rest, v, blk_v, vocab_major, quantized=False):
    if quantized:
        s_ref, m_ref, l_ref, tl_ref = rest
    else:
        (m_ref, l_ref, tl_ref), s_ref = rest, None
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref[...], NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref[...])
        tl_ref[...] = jnp.zeros_like(tl_ref[...])

    x = x_ref[...]  # [blk_rows, H] native dtype — bf16 MXU, fp32 accumulation
    w = w_ref[...]
    if quantized:  # int8 weight block: upcast for the dot, scale the logits
        x = x.astype(jnp.float32)
        w = w.astype(jnp.float32)
    if vocab_major:  # w [blk_v, H]
        logits = jax.lax.dot_general(
            x, w, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
    else:  # w [H, blk_v]
        logits = jax.lax.dot_general(
            x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
    if s_ref is not None:
        # per-output-channel dequant factors out of the contraction: scaling
        # the logits column equals dequantizing the whole weight column
        logits = logits * s_ref[...].astype(jnp.float32)  # [1, blk_v] broadcast
    cols = j * blk_v + jax.lax.broadcasted_iota(jnp.int32, (1, blk_v), 1)
    logits = jnp.where(cols < v, logits, NEG_INF)
    m = m_ref[...]  # [blk_rows, 1]
    m_new = jnp.maximum(m, logits.max(axis=-1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    l_ref[...] = l_ref[...] * alpha + jnp.sum(jnp.exp(logits - m_new), axis=-1, keepdims=True)
    m_ref[...] = m_new
    # target-class logit: ignore_index (< 0) never matches a column
    tl_ref[...] += jnp.sum(jnp.where(cols == lab_ref[...], logits, 0.0), axis=-1, keepdims=True)


def _flxent_block_d(x_ref, w_ref, lab_ref, lse_ref, gc_ref, j, *, v, blk_v, vocab_major):
    """Recompute one block's ``(softmax - onehot) * gcoef`` from the saved
    logsumexp — shared by the dX and dW kernels."""
    x = x_ref[...]
    w = w_ref[...]
    if vocab_major:
        logits = jax.lax.dot_general(
            x, w, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
    else:
        logits = jax.lax.dot_general(
            x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
    cols = j * blk_v + jax.lax.broadcasted_iota(jnp.int32, (1, blk_v), 1)
    p = jnp.exp(logits - lse_ref[...])
    p = jnp.where(cols < v, p, 0.0)  # zero-padded W rows: kill exp(-lse)
    onehot = (cols == lab_ref[...]).astype(jnp.float32)
    return ((p - onehot) * gc_ref[...]).astype(x.dtype)


def _flxent_dx_kernel(x_ref, w_ref, lab_ref, lse_ref, gc_ref, dx_ref, *, v, blk_v, vocab_major):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        dx_ref[...] = jnp.zeros_like(dx_ref[...])

    d = _flxent_block_d(
        x_ref, w_ref, lab_ref, lse_ref, gc_ref, j, v=v, blk_v=blk_v, vocab_major=vocab_major
    )
    w = w_ref[...]
    if vocab_major:  # d [br, bv] @ w [bv, H]
        dx_ref[...] += jax.lax.dot_general(
            d, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
    else:  # d [br, bv] @ w [H, bv]ᵀ
        dx_ref[...] += jax.lax.dot_general(
            d, w, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )


def _flxent_dw_kernel(x_ref, w_ref, lab_ref, lse_ref, gc_ref, dw_ref, *, v, blk_v, vocab_major):
    j = pl.program_id(0)  # vocab block (outer, parallel)
    i = pl.program_id(1)  # row block (inner, sequential accumulation)

    @pl.when(i == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref[...])

    d = _flxent_block_d(
        x_ref, w_ref, lab_ref, lse_ref, gc_ref, j, v=v, blk_v=blk_v, vocab_major=vocab_major
    )
    x = x_ref[...]
    if vocab_major:  # dᵀ [bv, br] @ x [br, H]
        dw_ref[...] += jax.lax.dot_general(
            d, x, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
    else:  # xᵀ [H, br] @ d [br, bv]
        dw_ref[...] += jax.lax.dot_general(
            x, d, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )


@functools.lru_cache(maxsize=None)
def _make_pallas_core(
    n_pad, v, vp, h, blk_rows, blk_v, vocab_major, interpret, ignore_index, reduction
):
    nr = n_pad // blk_rows
    nv = vp // blk_v
    row_spec = pl.BlockSpec((blk_rows, h), lambda i, j: (i, 0))
    col_spec = pl.BlockSpec((blk_rows, 1), lambda i, j: (i, 0))  # lab/lse/gc/m/l/tl
    if vocab_major:
        w_spec = pl.BlockSpec((blk_v, h), lambda i, j: (j, 0))
    else:
        w_spec = pl.BlockSpec((h, blk_v), lambda i, j: (0, j))

    def engine_fwd(x2, wp, lab):
        m, l, tl = pl.pallas_call(
            functools.partial(
                _flxent_fwd_kernel, v=v, blk_v=blk_v, vocab_major=vocab_major
            ),
            grid=(nr, nv),
            # row blocks are independent (megacore-splittable); the vocab dim
            # accumulates the online softmax state and MUST run sequentially
            compiler_params=_CompilerParams(dimension_semantics=("parallel", "arbitrary")),
            in_specs=[row_spec, w_spec, col_spec],
            out_specs=[col_spec, col_spec, col_spec],
            out_shape=[jax.ShapeDtypeStruct((n_pad, 1), jnp.float32)] * 3,
            interpret=interpret,
        )(x2, wp, lab.reshape(n_pad, 1))
        return (m + jnp.log(l))[:, 0], tl[:, 0]

    def engine_bwd(x2, wp, lab, lse, gcoef):
        lab2 = lab.reshape(n_pad, 1)
        lse2 = lse.reshape(n_pad, 1)
        gc2 = gcoef.reshape(n_pad, 1)
        kw = dict(v=v, blk_v=blk_v, vocab_major=vocab_major)
        dx = pl.pallas_call(
            functools.partial(_flxent_dx_kernel, **kw),
            grid=(nr, nv),
            compiler_params=_CompilerParams(dimension_semantics=("parallel", "arbitrary")),
            in_specs=[row_spec, w_spec, col_spec, col_spec, col_spec],
            out_specs=row_spec,
            out_shape=jax.ShapeDtypeStruct((n_pad, h), jnp.float32),
            interpret=interpret,
        )(x2, wp, lab2, lse2, gc2)
        # dW: transposed grid so its accumulation dim (rows) is innermost —
        # an output block may only be revisited on consecutive grid steps
        if vocab_major:
            dw_spec = pl.BlockSpec((blk_v, h), lambda j, i: (j, 0))
            dw_shape = jax.ShapeDtypeStruct((vp, h), jnp.float32)
        else:
            dw_spec = pl.BlockSpec((h, blk_v), lambda j, i: (0, j))
            dw_shape = jax.ShapeDtypeStruct((h, vp), jnp.float32)
        dw = pl.pallas_call(
            functools.partial(_flxent_dw_kernel, **kw),
            grid=(nv, nr),
            compiler_params=_CompilerParams(dimension_semantics=("parallel", "arbitrary")),
            in_specs=[
                pl.BlockSpec((blk_rows, h), lambda j, i: (i, 0)),
                pl.BlockSpec((blk_v, h), lambda j, i: (j, 0))
                if vocab_major
                else pl.BlockSpec((h, blk_v), lambda j, i: (0, j)),
                pl.BlockSpec((blk_rows, 1), lambda j, i: (i, 0)),
                pl.BlockSpec((blk_rows, 1), lambda j, i: (i, 0)),
                pl.BlockSpec((blk_rows, 1), lambda j, i: (i, 0)),
            ],
            out_specs=dw_spec,
            out_shape=dw_shape,
            interpret=interpret,
        )(x2, wp, lab2, lse2, gc2)
        return dx.astype(x2.dtype), dw.astype(wp.dtype)

    return _build_core(engine_fwd, engine_bwd, ignore_index, reduction)


def _pallas_path(x2, w, lab, *, v, h, ignore_index, reduction, vocab_major, interpret, block):
    n = x2.shape[0]
    blk_rows, blk_v = block
    blk_rows = min(blk_rows, _round_up(n, 16))  # small batches: one row block
    n_pad = _round_up(n, blk_rows)
    vp = _round_up(v, blk_v)
    # padding / layout prep sits OUTSIDE the custom VJP: its transpose rules
    # slice dX and dW back to the caller's shapes automatically
    x2p = jnp.pad(x2, ((0, n_pad - n), (0, 0))) if n_pad > n else x2
    labp = (
        jnp.pad(lab, (0, n_pad - n), constant_values=ignore_index) if n_pad > n else lab
    )
    if vp > v:
        wp = jnp.pad(w, ((0, vp - v), (0, 0)) if vocab_major else ((0, 0), (0, vp - v)))
    else:
        wp = w
    core = _make_pallas_core(
        n_pad, v, vp, h, blk_rows, blk_v, vocab_major, interpret, ignore_index, reduction
    )
    loss = core(x2p, wp, labp)
    if reduction == "none":
        loss = loss[:n]
    return loss


@functools.lru_cache(maxsize=None)
def _make_pallas_quant_fwd(n_pad, v, vp, h, blk_rows, blk_v, vocab_major, interpret):
    """Forward-only quantized engine: the fwd kernel with a scale input."""
    nr = n_pad // blk_rows
    nv = vp // blk_v
    row_spec = pl.BlockSpec((blk_rows, h), lambda i, j: (i, 0))
    col_spec = pl.BlockSpec((blk_rows, 1), lambda i, j: (i, 0))
    if vocab_major:
        w_spec = pl.BlockSpec((blk_v, h), lambda i, j: (j, 0))
    else:
        w_spec = pl.BlockSpec((h, blk_v), lambda i, j: (0, j))
    s_spec = pl.BlockSpec((1, blk_v), lambda i, j: (0, j))

    def engine_fwd(x2, wp, sp, lab):
        m, l, tl = pl.pallas_call(
            functools.partial(
                _flxent_fwd_kernel, v=v, blk_v=blk_v, vocab_major=vocab_major,
                quantized=True,
            ),
            grid=(nr, nv),
            compiler_params=_CompilerParams(dimension_semantics=("parallel", "arbitrary")),
            in_specs=[row_spec, w_spec, col_spec, s_spec],
            out_specs=[col_spec, col_spec, col_spec],
            out_shape=[jax.ShapeDtypeStruct((n_pad, 1), jnp.float32)] * 3,
            interpret=interpret,
        )(x2, wp, lab.reshape(n_pad, 1), sp.reshape(1, vp))
        return (m + jnp.log(l))[:, 0], tl[:, 0]

    return engine_fwd


def _pallas_quant_path(
    x2, w, scale, lab, *, v, h, ignore_index, reduction, vocab_major, interpret, block
):
    n = x2.shape[0]
    blk_rows, blk_v = block
    blk_rows = min(blk_rows, _round_up(n, 16))
    n_pad = _round_up(n, blk_rows)
    vp = _round_up(v, blk_v)
    x2p = jnp.pad(x2, ((0, n_pad - n), (0, 0))) if n_pad > n else x2
    labp = (
        jnp.pad(lab, (0, n_pad - n), constant_values=ignore_index) if n_pad > n else lab
    )
    sp = scale.astype(jnp.float32)
    if vp > v:
        w = jnp.pad(w, ((0, vp - v), (0, 0)) if vocab_major else ((0, 0), (0, vp - v)))
        sp = jnp.pad(sp, (0, vp - v))
    engine = _make_pallas_quant_fwd(
        n_pad, v, vp, h, blk_rows, blk_v, vocab_major, interpret
    )
    lse, tl = engine(x2p, w, sp, labp)
    loss = _quant_epilogue(lse, tl, labp, ignore_index, reduction)
    if reduction == "none":
        loss = loss[:n]
    return loss


# --------------------------------------------------------------------------
# block-size autotuning + public entry
# --------------------------------------------------------------------------


def _default_block(h: int, itemsize: int) -> Tuple[int, int]:
    # dW kernel VMEM budget: x + w blocks (native dtype) + fp32 dw block;
    # larger hidden sizes need smaller blocks — pick the largest tier that
    # fits the same budget the autotune candidate filter enforces
    for cfg in ((512, 512), (256, 256), (128, 128)):
        if _vmem_ok(cfg[0], cfg[1], h, itemsize):
            return cfg
    return (128, 128)


def _vmem_ok(blk_rows: int, blk_v: int, h: int, itemsize: int) -> bool:
    resident = (
        blk_rows * h * itemsize  # x block
        + blk_v * h * itemsize  # w block
        + blk_rows * blk_v * 4  # logits
        + blk_v * h * 4  # fp32 dw accumulator (the fattest kernel's extra)
    )
    return resident <= 12 * 1024 * 1024


def _autotune_fused_loss(n, v, h, dtype, vocab_major, interpret):
    """Benchmark-pick (row-block, vocab-block) for this loss-head shape
    (reference ``auto_tune_base.h:48``); defaults when tuning is off."""
    from paddle_tpu.kernels.autotune import autotune

    itemsize = jnp.dtype(dtype).itemsize
    key = (n, v, h, str(dtype), vocab_major)
    candidates = [
        (br, bv)
        for br in (256, 512, 1024)
        for bv in (256, 512, 1024)
        if _vmem_ok(br, bv, h, itemsize)
    ]

    def build(cfg):
        xz = jnp.zeros((n, h), dtype)
        wz = jnp.zeros((v, h) if vocab_major else (h, v), dtype)
        labz = jnp.zeros((n,), jnp.int32)

        def run():
            loss, vjp_fn = jax.vjp(
                lambda a, b: _pallas_path(
                    a, b, labz, v=v, h=h, ignore_index=-100, reduction="mean",
                    vocab_major=vocab_major, interpret=interpret, block=cfg,
                ),
                xz, wz,
            )
            return vjp_fn(jnp.ones_like(loss))  # fwd + bwd: the training cost

        return run

    return autotune(
        "fused_linear_xent", key, candidates, build, default=_default_block(h, itemsize)
    )


def fused_linear_cross_entropy(
    x: jax.Array,
    weight: jax.Array,
    labels: jax.Array,
    ignore_index: int = -100,
    reduction: str = "mean",
    vocab_major: bool = False,
    interpret: bool = False,
    block: Optional[Tuple[int, int]] = None,
    weight_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """``cross_entropy(x @ Wᵀ, labels)`` without materializing ``[N, V]``.

    ``x`` ``[..., H]``; ``weight`` ``[H, V]`` (``nn.Linear``) or ``[V, H]``
    with ``vocab_major=True`` (tied embedding); ``labels`` ``[...]`` int.
    Differentiable in ``x`` and ``weight`` (custom VJP; the backward
    recomputes block logits from the saved logsumexp). Loss is fp32;
    reduction semantics match ``F.cross_entropy`` (mean divides by
    ``max(#non-ignored, 1)``). ``interpret=True`` forces the Pallas path in
    interpreter mode (tests); ``block`` overrides the autotuned
    ``(row_block, vocab_block)``.

    ``weight_scale`` (``[V]`` fp32, with ``weight`` int8) switches to the
    weight-only int8 lm-head walk: each vocab chunk's logits are scaled by
    its per-channel factors inside the walk, so the dequantized weight never
    materializes. Inference-only — the quantized walk has no VJP.
    """
    if reduction not in ("mean", "sum", "none"):
        raise ValueError(f"unsupported reduction {reduction!r}")
    lead = x.shape[:-1]
    h = x.shape[-1]
    v = weight.shape[0] if vocab_major else weight.shape[1]
    n = 1
    for s in lead:
        n *= int(s)
    x2 = x.reshape(n, h)
    lab = labels.reshape(n).astype(jnp.int32)

    if weight_scale is not None:
        loss = None
        if bool(interpret) or (pallas_enabled("use_fused_loss") and h % 128 == 0):
            blk = tuple(block) if block is not None else _default_block(h, 1)
            try:
                loss = _pallas_quant_path(
                    x2, weight, weight_scale, lab, v=v, h=h,
                    ignore_index=int(ignore_index), reduction=reduction,
                    vocab_major=bool(vocab_major), interpret=bool(interpret),
                    block=blk,
                )
            except Exception as exc:  # noqa: BLE001 - scan fallback below
                warn_fallback("fused_linear_xent_quant", exc)
        if loss is None:
            loss = _reference_quant_path(
                x2, weight, weight_scale, lab, v=v, h=h,
                ignore_index=int(ignore_index), reduction=reduction,
                vocab_major=bool(vocab_major),
            )
        if reduction == "none":
            return loss.reshape(lead)
        return loss

    loss = None
    # pre-trace applicability: lane-aligned hidden (see kernels/select.py)
    if bool(interpret) or (pallas_enabled("use_fused_loss") and h % 128 == 0):
        blk = tuple(block) if block is not None else _autotune_fused_loss(
            n, v, h, x.dtype, vocab_major, bool(interpret)
        )
        try:
            loss = _pallas_path(
                x2, weight, lab, v=v, h=h, ignore_index=int(ignore_index),
                reduction=reduction, vocab_major=bool(vocab_major),
                interpret=bool(interpret), block=blk,
            )
        except Exception as exc:  # Mosaic lowering / unsupported shape: XLA path covers it
            warn_fallback("fused_linear_cross_entropy", exc)
    if loss is None:
        loss = _reference_path(
            x2, weight, lab, v=v, h=h, ignore_index=int(ignore_index),
            reduction=reduction, vocab_major=bool(vocab_major),
        )
    if reduction == "none":
        return loss.reshape(lead)
    return loss
