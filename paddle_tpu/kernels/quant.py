"""Weight-only int8 projection kernel (inference serving).

Reference capability: the fork's weight-only quantization surface
(``paddle/phi/kernels/fusion/gpu/fused_weight_only_linear_pass``-adjacent
AMP/quantization layer) — lm-head and MLP projection weights stored int8
with per-output-channel fp32 scales, dequantized on the fly inside the
matmul so no bf16 copy of the weight ever materializes in HBM.

TPU-native shape: a Pallas tiled matmul over grid (M/bm, N/bn, K/bk) — int8
weight tiles stream HBM -> VMEM at half the bytes of bf16, upcast in
VMEM, fp32 MXU accumulate (``preferred_element_type``), and the scale row
multiplies once at the K-walk's end. The XLA fallback is the same op
composition (``(x_f32 @ w8_f32) * scale``) — the canonical semantics both
paths implement; CPU CI always takes it (inference-only: no tape, no
GradNode — the engine's decode step never differentiates through it).

Dispatch follows the repo's kernel discipline (PG905): host-side lowering
probe at trace time, ``warn_fallback``-counted degradation, autotune entry
for the block geometry.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.export  # noqa: F401  (jax 0.4.x: not re-exported by `import jax`)
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.kernels.select import _CompilerParams

__all__ = [
    "quantize_weight_int8",
    "quantize_module_weights",
    "int8_weight_matmul",
    "wo_lowering_supported",
]

# Model leaf names whose nn.Linear weights the engine quantizes under
# FLAGS_weight_only_int8: the MLP projections and the lm-head — attention
# projections and (tied) embeddings are excluded (an embedding weight also
# feeds the token gather, which must stay full-precision).
WEIGHT_ONLY_LEAVES = ("gate_proj", "up_proj", "down_proj", "fc1", "fc2", "lm_head")


def quantize_weight_int8(w: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-output-channel absmax quantization of a ``[K, N]``
    projection weight: returns ``(w8 [K, N] int8, scale [N] fp32)`` with
    ``w ≈ w8 * scale`` column-wise. Per-COLUMN scales are exact under both
    the K-contraction and tensor-parallel K-sharding (the scale factors out
    of the sum), which is why the row dim never gets its own scale."""
    wf = jnp.asarray(w).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=0)  # [N]
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    w8 = jnp.clip(jnp.round(wf / scale[None, :]), -127, 127).astype(jnp.int8)
    return w8, scale


def quantize_module_weights(model) -> list:
    """In-place weight-only int8 quantization of a model's projection
    weights (engine-applied under ``FLAGS_weight_only_int8``).

    Walks the sublayer tree, and for every layer whose attribute leaf name
    is in :data:`WEIGHT_ONLY_LEAVES` replaces ``weight._data`` with the
    int8 array and hangs the per-output-channel scales off the Parameter as
    ``_quant_scale`` — the hook ``nn.Linear.forward`` dispatches on.
    Parameters shared with any non-target layer (tied embeddings) are left
    untouched: the other consumer needs the full-precision array. Idempotent;
    returns the list of Parameters quantized (order = sublayer walk order),
    which the engine threads as extra step operands so the scales stay part
    of the ONE compiled step signature."""
    # ownership map built from the raw per-layer parameter dicts — NOT
    # named_parameters(), which dedups by id and would hide sharing
    owners: dict = {}
    for lname, layer in model.named_sublayers(include_self=True):
        leaf = lname.split(".")[-1] if lname else ""
        for p in getattr(layer, "_parameters", {}).values():
            if p is not None:
                owners.setdefault(id(p), set()).add(leaf)
    quantized = []
    for lname, layer in model.named_sublayers(include_self=True):
        leaf = lname.split(".")[-1] if lname else ""
        if leaf not in WEIGHT_ONLY_LEAVES:
            continue
        w = getattr(layer, "weight", None)
        if w is None or getattr(w, "_quant_scale", None) is not None:
            continue
        data = getattr(w, "_data", None)
        if (
            data is None
            or data.ndim != 2
            or not jnp.issubdtype(data.dtype, jnp.floating)
        ):
            continue
        if any(o not in WEIGHT_ONLY_LEAVES for o in owners.get(id(w), set())):
            continue
        w8, scale = quantize_weight_int8(data)
        w._data = w8
        w._quant_scale = scale
        quantized.append(w)
    return quantized


def _wo_matmul_kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, n_k: int):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jax.lax.dot_general(
        x_ref[...].astype(jnp.float32),
        w_ref[...].astype(jnp.float32),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(ki == n_k - 1)
    def _finish():
        # one scale multiply per output tile, AFTER the K walk: dequant
        # factors out of the contraction, so this equals dequantizing the
        # whole weight first — without ever materializing it
        o_ref[...] = (acc_ref[...] * s_ref[...].astype(jnp.float32)).astype(
            o_ref.dtype
        )


def _wo_matmul_pallas(
    x: jax.Array,  # [M, K] activations (bf16/f32)
    w8: jax.Array,  # [K, N] int8
    scale: jax.Array,  # [N] fp32
    block: Tuple[int, int, int],
    interpret: bool = False,
) -> jax.Array:
    m, k = x.shape
    n = w8.shape[1]
    bm, bn, bk = block
    bm, bn, bk = min(bm, m), min(bn, n), min(bk, k)
    if m % bm or n % bn or k % bk:
        raise ValueError(f"geometry ({m},{k},{n}) not divisible by {block}")
    n_k = k // bk
    kernel = functools.partial(_wo_matmul_kernel, n_k=n_k)
    return pl.pallas_call(
        kernel,
        grid=(m // bm, n // bn, n_k),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda mi, ni, ki: (mi, ki)),
            pl.BlockSpec((bk, bn), lambda mi, ni, ki: (ki, ni)),
            pl.BlockSpec((1, bn), lambda mi, ni, ki: (0, ni)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda mi, ni, ki: (mi, ni)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(x, w8, scale.reshape(1, n))


@functools.lru_cache(maxsize=64)
def wo_lowering_supported(m: int, k: int, n: int, block: Tuple[int, int, int],
                          dtype: str) -> bool:
    """Static Mosaic-lowering probe for the weight-only matmul, cached per
    geometry — the same TRACE-time routing rule every paged kernel uses (a
    lowering error inside the engine's jitted step is uncatchable)."""
    import numpy as np

    xs = jax.ShapeDtypeStruct((m, k), np.dtype(dtype))
    ws = jax.ShapeDtypeStruct((k, n), np.int8)
    ss = jax.ShapeDtypeStruct((n,), np.float32)
    try:
        jax.export.export(
            jax.jit(lambda x, w, s: _wo_matmul_pallas(x, w, s, block)),
            platforms=["tpu"],
        )(xs, ws, ss)
        return True
    except Exception:  # noqa: BLE001 - any lowering failure means "don't"
        return False


def _default_block(m: int, k: int, n: int) -> Tuple[int, int, int]:
    # MXU-friendly 128-multiples, shrunk to the actual geometry
    return (min(256, m), min(256, n), min(512, k))


def _autotune_block(m: int, k: int, n: int, dtype: str) -> Tuple[int, int, int]:
    """Autotune entry for the weight-only matmul block geometry — disabled
    by default (FLAGS_use_kernel_autotune), TPU-only, cached per shape."""
    from paddle_tpu.kernels.autotune import autotune

    key = (m, k, n, dtype)
    candidates = [
        (bm, bn, bk)
        for bm in (128, 256, 512)
        for bn in (128, 256, 512)
        for bk in (256, 512)
        if m % bm == 0 and n % bn == 0 and k % bk == 0
    ]

    def build(cfg):
        if not wo_lowering_supported(m, k, n, cfg, dtype):
            return None
        xz = jnp.zeros((m, k), jnp.dtype(dtype))
        wz = jnp.zeros((k, n), jnp.int8)
        sz = jnp.ones((n,), jnp.float32)

        def run():
            return _wo_matmul_pallas(xz, wz, sz, cfg)

        return run

    return autotune(
        "int8_weight_matmul", key, candidates, build,
        default=_default_block(m, k, n),
    )


def int8_weight_matmul(
    x: jax.Array,  # [..., K] activations
    w8: jax.Array,  # [K, N] int8 quantized weight
    scale: jax.Array,  # [N] fp32 per-output-channel scales
    interpret: bool = False,
    block: Optional[Tuple[int, int, int]] = None,
) -> jax.Array:
    """``(x @ dequant(w8)) = (x @ w8) * scale`` without materializing the
    dequantized weight. Pallas on TPU when the geometry lowers (probed at
    trace time), XLA composition elsewhere — ``warn_fallback``-counted on
    kernel failure per the PG905 dispatch discipline."""
    from paddle_tpu.distributed.tp import current_tp_mesh
    from paddle_tpu.kernels.select import pallas_enabled, warn_fallback

    lead = x.shape[:-1]
    k = x.shape[-1]
    n = w8.shape[1]
    m = 1
    for s in lead:
        m *= int(s)
    x2 = x.reshape(m, k)

    # under an armed tp shard group this matmul is GSPMD-partitioned by the
    # surrounding trace; a bare pallas_call cannot be (it would need its own
    # shard_map) — route to the XLA composition, which GSPMD splits fine
    if (
        pallas_enabled("weight_only_int8") and not interpret
        and current_tp_mesh() is None
    ):
        blk = block or _autotune_block(m, k, n, str(x.dtype))
        blk = (min(blk[0], m), min(blk[1], n), min(blk[2], k))
        if (
            m % blk[0] == 0 and n % blk[1] == 0 and k % blk[2] == 0
            and wo_lowering_supported(m, k, n, blk, str(x.dtype))
        ):
            try:
                out = _wo_matmul_pallas(x2, w8, scale, blk)
                return out.reshape(*lead, n)
            except Exception as exc:  # noqa: BLE001 - XLA fallback below
                warn_fallback("int8_weight_matmul", exc)
        else:
            warn_fallback(
                "int8_weight_matmul",
                RuntimeError("Mosaic lowering unsupported for geometry"),
            )
    elif interpret:
        out = _wo_matmul_pallas(
            x2, w8, scale, block or _default_block(m, k, n), interpret=True
        )
        return out.reshape(*lead, n)
    # the canonical composition the kernel implements: fp32 matmul of the
    # int8 weight, one scale row multiply, cast back to the activation dtype
    out = (
        jnp.matmul(
            x2.astype(jnp.float32), w8.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        * scale[None, :]
    ).astype(x.dtype)
    return out.reshape(*lead, n)
