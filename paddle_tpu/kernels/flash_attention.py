"""Pallas TPU flash attention (forward + backward) with optional FlashMask
column-sparse masking.

Replaces the reference's CUDA flash-attention kernels
(``paddle/phi/kernels/gpu/flash_attn_kernel.cu:353`` + patched
``third_party/flashattn``) with a TPU kernel: online-softmax tiling over KV
blocks held in VMEM, fp32 accumulation on the MXU, and a custom-VJP backward
pair (dq kernel / dkv kernel) recomputing probabilities from the saved
logsumexp — the standard flash-attention-2 decomposition.

Layouts: public entry takes paddle's ``[B, S, H, D]``; kernels run
``[B, H, S, D]``. Grouped-query attention is handled by BlockSpec index maps
(kv head = q head // group), never materializing repeated KV.

The FlashMask encoding (``startend_row_indices [B, Hm, Sk, C]``, C ∈ {1,2,4})
is applied per KV block from an O(S) bounds tensor — mask memory stays linear
in sequence length, the fork's marquee property.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.kernels.select import _CompilerParams

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _cdiv(a: int, b: int) -> int:
    return (a + b - 1) // b


def _pad_to(x: jax.Array, axis: int, mult: int) -> jax.Array:
    size = x.shape[axis]
    rem = size % mult
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, mult - rem)
    return jnp.pad(x, pad)


def _mask_block(
    rows: jax.Array,  # [blk_q, 1] global query positions
    cols: jax.Array,  # [1, blk_k] global key positions
    sq: int,
    sk: int,
    causal: bool,
    bounds: Optional[jax.Array],  # [blk_k, C] startend_row_indices slice
) -> jax.Array:
    """True where the logit must be masked out."""
    masked = cols >= sk  # padding columns
    if causal:
        masked = masked | (cols > rows + (sk - sq))
    if bounds is not None:
        c = bounds.shape[-1]
        if c == 1:
            masked = masked | (rows >= bounds[:, 0][None, :])
        elif c == 2:
            start = bounds[:, 0][None, :]
            end = bounds[:, 1][None, :]
            masked = masked | ((rows >= start) & (rows < end))
        elif c == 4:
            lts = bounds[:, 0][None, :]
            lte = bounds[:, 1][None, :]
            uts = bounds[:, 2][None, :]
            ute = bounds[:, 3][None, :]
            masked = masked | ((rows >= lts) & (rows < lte)) | ((rows >= uts) & (rows < ute))
        else:
            raise ValueError(f"FlashMask C must be 1/2/4, got {c}")
    return masked


# --------------------------------------------------------------------------
# forward kernel
# --------------------------------------------------------------------------


def _fwd_kernel(
    q_ref, k_ref, v_ref, idx_ref, o_ref, lse_ref, *, sq, sk, scale, causal, blk_q, blk_k, num_kv_blocks
):
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale  # [blk_q, D]
    d = q.shape[-1]
    rows = qi * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, 1), 0)

    if causal:
        # only kv blocks touching or below the diagonal contribute
        hi = jnp.minimum(((qi + 1) * blk_q + (sk - sq) + blk_k - 1) // blk_k, num_kv_blocks)
        hi = jnp.maximum(hi, 0)
    else:
        hi = num_kv_blocks

    def body(ki, carry):
        acc, m, l = carry
        k = k_ref[0, 0, pl.dslice(ki * blk_k, blk_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.dslice(ki * blk_k, blk_k), :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [blk_q, blk_k]
        cols = ki * blk_k + jax.lax.broadcasted_iota(jnp.int32, (1, blk_k), 1)
        bounds = None
        if idx_ref is not None:
            bounds = idx_ref[0, 0, pl.dslice(ki * blk_k, blk_k), :]
        masked = _mask_block(rows, cols, sq, sk, causal, bounds)
        logits = jnp.where(masked, NEG_INF, logits)
        m_new = jnp.maximum(m, logits.max(axis=-1, keepdims=True))
        p = jnp.exp(logits - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc, m_new, l

    acc0 = jnp.zeros((blk_q, d), jnp.float32)
    m0 = jnp.full((blk_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((blk_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, hi, body, (acc0, m0, l0))
    l = jnp.maximum(l, 1e-30)  # fully-masked rows: avoid 0/0
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    # lse is carried as [B, H, Sq, 1]: a trailing unit lane dim keeps the
    # block (1, 1, blk_q, 1) Mosaic-legal (sublane blk_q % 8 == 0, lane == 1
    # equals the array dim) — a bare [B, H, Sq] layout would need an
    # (·, ·, blk_q) block whose head dim of 1 violates the (8, 128) rule
    lse_ref[0, 0] = m + jnp.log(l)


def _run_fwd(q, k, v, idx, *, sq, sk, scale, causal, blk_q, blk_k, interpret):
    b, h, sq_pad, d = q.shape
    hk = k.shape[1]
    sk_pad = k.shape[2]
    group = h // hk
    num_kv_blocks = sk_pad // blk_k
    grid = (b, h, sq_pad // blk_q)

    in_specs = [
        pl.BlockSpec((1, 1, blk_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, sk_pad, d), lambda bi, hi, qi: (bi, hi // group, 0, 0)),
        pl.BlockSpec((1, 1, sk_pad, d), lambda bi, hi, qi: (bi, hi // group, 0, 0)),
    ]
    args = [q, k, v]
    if idx is not None:
        hm = idx.shape[1]
        c = idx.shape[-1]
        in_specs.append(
            pl.BlockSpec(
                (1, 1, sk_pad, c),
                lambda bi, hi, qi: (bi, 0 if hm == 1 else hi, 0, 0),
            )
        )
        args.append(idx)
        kernel = functools.partial(
            _fwd_kernel, sq=sq, sk=sk, scale=scale, causal=causal,
            blk_q=blk_q, blk_k=blk_k, num_kv_blocks=num_kv_blocks,
        )
    else:
        kernel = functools.partial(
            lambda q_ref, k_ref, v_ref, o_ref, lse_ref, **kw: _fwd_kernel(
                q_ref, k_ref, v_ref, None, o_ref, lse_ref, **kw
            ),
            sq=sq, sk=sk, scale=scale, causal=causal,
            blk_q=blk_q, blk_k=blk_k, num_kv_blocks=num_kv_blocks,
        )

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        # every (batch, head, q-block) cell is independent — Mosaic may split
        # them across TensorCores (megacore on v4/v5p)
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel")
        ),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, blk_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, blk_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq_pad, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq_pad, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return out, lse


# --------------------------------------------------------------------------
# backward kernels
# --------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, idx_ref, g_ref, lse_ref, delta_ref, dq_ref,
    *, sq, sk, scale, causal, blk_q, blk_k, num_kv_blocks
):
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)  # [blk_q, D]
    g = g_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]  # [blk_q, 1]
    delta = delta_ref[0, 0]
    d = q.shape[-1]
    rows = qi * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, 1), 0)

    if causal:
        hi = jnp.minimum(((qi + 1) * blk_q + (sk - sq) + blk_k - 1) // blk_k, num_kv_blocks)
        hi = jnp.maximum(hi, 0)
    else:
        hi = num_kv_blocks

    def body(ki, dq):
        k = k_ref[0, 0, pl.dslice(ki * blk_k, blk_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.dslice(ki * blk_k, blk_k), :].astype(jnp.float32)
        logits = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        cols = ki * blk_k + jax.lax.broadcasted_iota(jnp.int32, (1, blk_k), 1)
        bounds = None
        if idx_ref is not None:
            bounds = idx_ref[0, 0, pl.dslice(ki * blk_k, blk_k), :]
        masked = _mask_block(rows, cols, sq, sk, causal, bounds)
        p = jnp.where(masked, 0.0, jnp.exp(logits - lse))  # [blk_q, blk_k]
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * scale
        dq = dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dq

    dq = jax.lax.fori_loop(0, hi, body, jnp.zeros((blk_q, d), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, idx_ref, g_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, sq, sk, scale, causal, blk_q, blk_k, num_q_blocks, group
):
    ki = pl.program_id(2)
    k = k_ref[0, 0].astype(jnp.float32)  # [blk_k, D]
    v = v_ref[0, 0].astype(jnp.float32)
    d = k.shape[-1]
    cols = ki * blk_k + jax.lax.broadcasted_iota(jnp.int32, (1, blk_k), 1)
    bounds = idx_ref[0, 0] if idx_ref is not None else None  # [blk_k, C]

    if causal:
        lo = jnp.maximum((ki * blk_k - (sk - sq)) // blk_q, 0)
    else:
        lo = 0

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.dslice(qi * blk_q, blk_q), :].astype(jnp.float32)
        g = g_ref[0, 0, pl.dslice(qi * blk_q, blk_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.dslice(qi * blk_q, blk_q), :]  # [blk_q, 1]
        delta = delta_ref[0, 0, pl.dslice(qi * blk_q, blk_q), :]
        rows = qi * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, 1), 0)
        logits = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [blk_q, blk_k]
        masked = _mask_block(rows, cols, sq, sk, causal, bounds)
        # padding rows (rows >= sq) contribute nothing: lse there is 0 and
        # exp(0-0)=1, so mask them explicitly
        masked = masked | (rows >= sq)
        p = jnp.where(masked, 0.0, jnp.exp(logits - lse))
        dv = dv + jax.lax.dot_general(
            p, g, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [blk_k, D]
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [blk_q, blk_k]
        ds = p * (dp - delta) * scale
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk, dv

    dk0 = jnp.zeros((blk_k, d), jnp.float32)
    dv0 = jnp.zeros((blk_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo, num_q_blocks, body, (dk0, dv0))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _run_bwd(q, k, v, idx, g, out, lse, *, sq, sk, scale, causal, blk_q, blk_k, interpret):
    b, h, sq_pad, d = q.shape
    hk = k.shape[1]
    sk_pad = k.shape[2]
    group = h // hk
    # [B, H, Sq, 1] — same trailing-unit-lane layout as lse (Mosaic tiling)
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True
    )

    common = dict(sq=sq, sk=sk, scale=scale, causal=causal, blk_q=blk_q, blk_k=blk_k)

    # dq: grid over q blocks
    dq_specs = [
        pl.BlockSpec((1, 1, blk_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),       # q
        pl.BlockSpec((1, 1, sk_pad, d), lambda bi, hi, qi: (bi, hi // group, 0, 0)),  # k
        pl.BlockSpec((1, 1, sk_pad, d), lambda bi, hi, qi: (bi, hi // group, 0, 0)),  # v
    ]
    dq_args = [q, k, v]
    if idx is not None:
        hm = idx.shape[1]
        c = idx.shape[-1]
        dq_specs.append(
            pl.BlockSpec((1, 1, sk_pad, c), lambda bi, hi, qi: (bi, 0 if hm == 1 else hi, 0, 0))
        )
        dq_args.append(idx)
        dq_kernel = functools.partial(_bwd_dq_kernel, **common, num_kv_blocks=sk_pad // blk_k)
    else:
        dq_kernel = functools.partial(
            lambda q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dq_ref, **kw: _bwd_dq_kernel(
                q_ref, k_ref, v_ref, None, g_ref, lse_ref, delta_ref, dq_ref, **kw
            ),
            **common,
            num_kv_blocks=sk_pad // blk_k,
        )
    dq_specs += [
        pl.BlockSpec((1, 1, blk_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),      # g
        pl.BlockSpec((1, 1, blk_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),      # lse
        pl.BlockSpec((1, 1, blk_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),      # delta
    ]
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, sq_pad // blk_q),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel")
        ),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, 1, blk_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, sq_pad, d), q.dtype),
        interpret=interpret,
    )(*dq_args, g, lse, delta)

    # dk/dv: grid over kv blocks, one q-head at a time (GQA: accumulate
    # outside over the group's q heads to avoid in-kernel atomics)
    dkv_specs = [
        pl.BlockSpec((1, 1, sq_pad, d), lambda bi, hi, ki: (bi, hi, 0, 0)),   # q
        pl.BlockSpec((1, 1, blk_k, d), lambda bi, hi, ki: (bi, hi // group, ki, 0)),  # k
        pl.BlockSpec((1, 1, blk_k, d), lambda bi, hi, ki: (bi, hi // group, ki, 0)),  # v
    ]
    dkv_args = [q, k, v]
    if idx is not None:
        hm = idx.shape[1]
        c = idx.shape[-1]
        dkv_specs.append(
            pl.BlockSpec((1, 1, blk_k, c), lambda bi, hi, ki: (bi, 0 if hm == 1 else hi, ki, 0))
        )
        dkv_args.append(idx)
        dkv_kernel = functools.partial(
            _bwd_dkv_kernel, **common, num_q_blocks=sq_pad // blk_q, group=group
        )
    else:
        dkv_kernel = functools.partial(
            lambda q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dk_ref, dv_ref, **kw: _bwd_dkv_kernel(
                q_ref, k_ref, v_ref, None, g_ref, lse_ref, delta_ref, dk_ref, dv_ref, **kw
            ),
            **common,
            num_q_blocks=sq_pad // blk_q,
            group=group,
        )
    dkv_specs += [
        pl.BlockSpec((1, 1, sq_pad, d), lambda bi, hi, ki: (bi, hi, 0, 0)),      # g
        pl.BlockSpec((1, 1, sq_pad, 1), lambda bi, hi, ki: (bi, hi, 0, 0)),      # lse
        pl.BlockSpec((1, 1, sq_pad, 1), lambda bi, hi, ki: (bi, hi, 0, 0)),      # delta
    ]
    # per-q-head partial dk/dv, summed over the group afterwards
    dk_h, dv_h = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, sk_pad // blk_k),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel")
        ),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, 1, blk_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, blk_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk_pad, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, sk_pad, d), jnp.float32),
        ],
        interpret=interpret,
    )(*dkv_args, g, lse, delta)
    if group > 1:
        dk = dk_h.reshape(b, hk, group, sk_pad, d).sum(axis=2)
        dv = dv_h.reshape(b, hk, group, sk_pad, d).sum(axis=2)
    else:
        dk, dv = dk_h, dv_h
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# --------------------------------------------------------------------------
# public entry (custom VJP, paddle [B, S, H, D] layout)
# --------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _make_flash_core(sq, sk, scale, causal, blk_q, blk_k, interpret):
    """Build the custom-VJP core for one static configuration. All static
    parameters live in this closure; the returned function takes only array
    arguments (q, k, v [B,H,S,D] and the optional FlashMask bounds)."""

    def fwd_res(q, k, v, idx):
        qp = _pad_to(q, 2, blk_q)
        kp = _pad_to(k, 2, blk_k)
        vp = _pad_to(v, 2, blk_k)
        idxp = _pad_to(idx, 2, blk_k) if idx is not None else None
        out, lse = _run_fwd(
            qp, kp, vp, idxp, sq=sq, sk=sk, scale=scale, causal=causal,
            blk_q=blk_q, blk_k=blk_k, interpret=interpret,
        )
        return out, lse, (qp, kp, vp, idxp)

    @jax.custom_vjp
    def core(q, k, v, idx):
        out, _, _ = fwd_res(q, k, v, idx)
        return out[:, :, :sq]

    def core_fwd(q, k, v, idx):
        out, lse, (qp, kp, vp, idxp) = fwd_res(q, k, v, idx)
        return out[:, :, :sq], (qp, kp, vp, idxp, out, lse)

    def core_bwd(res, g):
        import numpy as np

        qp, kp, vp, idxp, outp, lse = res
        gp = _pad_to(g, 2, blk_q)
        dq, dk, dv = _run_bwd(
            qp, kp, vp, idxp, gp, outp, lse,
            sq=sq, sk=sk, scale=scale, causal=causal,
            blk_q=blk_q, blk_k=blk_k, interpret=interpret,
        )
        didx = None
        if idxp is not None:
            # integer mask bounds carry no gradient (float0 cotangent)
            didx = np.zeros(idxp.shape[:2] + (sk,) + idxp.shape[3:], jax.dtypes.float0)
        return dq[:, :, :sq], dk[:, :, :sk], dv[:, :, :sk], didx

    core.defvjp(core_fwd, core_bwd)
    return core


def _autotune_blocks(q_shape, kv_heads, dtype, sq, sk, d, scale, causal, mask_c, interpret):
    """Benchmark-pick (blk_q, blk_k) for this attention shape (reference
    ``auto_tune_base.h:48``); returns the defaults when tuning is off."""
    from paddle_tpu.kernels.autotune import autotune

    b, h = q_shape[0], q_shape[2]
    key = (b, h, kv_heads, sq, sk, d, str(dtype), causal, mask_c)
    candidates = [
        (bq, bk)
        for bq in (128, 256, 512)
        for bk in (128, 256, 512)
        if bq <= max(sq, 128) and bk <= max(sk, 128) and bq * bk <= 512 * 256
    ]

    def build(cfg):
        bq, bk = cfg
        qz = jnp.zeros((b, h, sq, d), dtype)
        kz = jnp.zeros((b, kv_heads, sk, d), dtype)
        bounds = (
            jnp.zeros((b, 1, sk, mask_c), jnp.int32) if mask_c else None
        )
        core = _make_flash_core(
            sq, sk, float(scale), bool(causal),
            min(bq, max(_cdiv(sq, 8) * 8, 8)), min(bk, max(_cdiv(sk, 8) * 8, 8)),
            bool(interpret),
        )
        return lambda: core(qz, kz, kz, bounds)

    return autotune(
        "flash_attention", key, candidates, build,
        default=(DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K),
    )


def flash_attention_pallas(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    startend_row_indices: Optional[jax.Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: bool = False,
) -> jax.Array:
    """Flash attention over paddle layout ``[B, S, H, D]`` (optionally with a
    FlashMask bounds tensor ``[B, Hm, Sk, C]``). Differentiable.

    ``block_q``/``block_k`` default to the autotuner's pick for this shape
    when ``FLAGS_use_kernel_autotune`` is on, else (128, 128)."""
    sq, sk = q.shape[1], k.shape[1]
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d**0.5)
    if block_q is None or block_k is None:
        mask_c = 0 if startend_row_indices is None else int(startend_row_indices.shape[-1])
        tuned_q, tuned_k = _autotune_blocks(
            q.shape, k.shape[2], q.dtype, sq, sk, d, scale, causal, mask_c, interpret
        )
        block_q = block_q if block_q is not None else tuned_q
        block_k = block_k if block_k is not None else tuned_k
    blk_q = min(block_q, max(_cdiv(sq, 8) * 8, 8))
    blk_k = min(block_k, max(_cdiv(sk, 8) * 8, 8))
    qh = jnp.moveaxis(q, 2, 1)  # [B, H, S, D]
    kh = jnp.moveaxis(k, 2, 1)
    vh = jnp.moveaxis(v, 2, 1)
    core = _make_flash_core(
        sq, sk, float(scale), bool(causal), blk_q, blk_k, bool(interpret)
    )
    out = core(qh, kh, vh, startend_row_indices)
    return jnp.moveaxis(out, 1, 2)
