"""FlashMask attention Pallas kernel entry.

The fork's marquee feature (reference ``paddle/phi/ops/yaml/ops.yaml:1909``
``flashmask_attention``, kernel ``paddle/phi/kernels/gpu/
flash_attn_kernel.cu:353-460``): attention with a column-sparse mask encoded
as row bounds per key column (``startend_row_indices [B, Hm, Sk, C]``,
C ∈ {1,2,4}) — O(S) mask memory instead of O(S²) for causal, sliding-window,
document and global-token mask families.

On TPU the encoding maps naturally onto the flash-attention KV-block loop:
each KV block loads its ``[blk_k, C]`` bounds slice from VMEM and compares
against the query-row iota — the dense [Sq, Sk] mask never exists. The
reference's ``flashmask_maxmin`` block min/max precompute (used by the CUDA
kernel to skip fully-masked blocks) corresponds here to the causal block-range
bound already applied in the kernel loop; finer skipping is a scalar-prefetch
optimization layered on the same kernel.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.kernels.flash_attention import flash_attention_pallas

__all__ = ["flashmask_attention_pallas", "flashmask_maxmin"]


def flashmask_attention_pallas(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    startend_row_indices: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    interpret: bool = False,
) -> jax.Array:
    """FlashMask attention over paddle layout ``[B, S, H, D]``.

    ``startend_row_indices``: int32 ``[B, Hm, Sk, C]`` (Hm ∈ {1, H}):
      - C == 1 (causal): query rows ``[start_j, Sq)`` masked for column j.
      - C == 2 (causal): rows ``[start_j, end_j)`` masked.
      - C == 4: ``[LTS, LTE, UTS, UTE]`` lower/upper-triangle row bands.
    """
    if startend_row_indices.dtype not in (jnp.int32, jnp.int64):
        raise TypeError("startend_row_indices must be int32")
    return flash_attention_pallas(
        q,
        k,
        v,
        startend_row_indices=startend_row_indices.astype(jnp.int32),
        causal=causal,
        scale=scale,
        interpret=interpret,
    )


def flashmask_maxmin(startend_row_indices: jax.Array, block_size: int = 128):
    """Per-KV-block min/max of the mask bounds (reference
    ``flash_attn_kernel.cu:445`` ``flashmask_maxmin`` precompute). Returns
    (min, max) arrays ``[B, Hm, num_blocks, C]`` — the block-skip metadata a
    scalar-prefetch variant of the kernel consumes."""
    b, hm, sk, c = startend_row_indices.shape
    pad = (-sk) % block_size
    idx = jnp.pad(
        startend_row_indices, ((0, 0), (0, 0), (0, pad), (0, 0)), mode="edge"
    )
    blocks = idx.reshape(b, hm, -1, block_size, c)
    return blocks.min(axis=3), blocks.max(axis=3)
