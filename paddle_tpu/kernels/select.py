"""Kernel-path selection: one place deciding Pallas vs XLA-fallback.

The applicability checks run BEFORE tracing so a shape the Mosaic compiler
cannot lower never reaches jit (a lowering error inside a captured train step
cannot be caught by the eager try/except)."""

from __future__ import annotations

import logging
from typing import Any, Dict, List

import jax
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.flags import GLOBAL_FLAGS
from paddle_tpu.observability import get_registry

# named TPUCompilerParams before jax 0.5 — the one shared shim every kernel
# module imports (keep version dances out of the kernels themselves)
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

_logger = logging.getLogger("paddle_tpu.kernels")
_warned: set = set()
_fallbacks_total = get_registry().counter(
    "paddle_tpu_kernel_fallbacks_total",
    "Pallas kernel failures that degraded to the XLA fallback path, by kernel.",
    labelnames=("kernel",),
)

# per-flag cached bools kept fresh by on_change listeners: pallas_enabled
# runs on EVERY kernel-path dispatch (rope calls it once per q/k tensor), so
# it must not take the flag-registry lock per op (analyzer check CC704 — the
# same _NAN_CHECK discipline core/dispatch.py uses)
_flag_cache: Dict[str, List[bool]] = {}


def _cached_flag(flag: str) -> bool:
    cell = _flag_cache.get(flag)
    if cell is None:
        cell = _flag_cache.setdefault(flag, [False])

        def _refresh(value: Any, _cell: List[bool] = cell) -> None:
            _cell[0] = bool(value)

        GLOBAL_FLAGS.on_change(flag, _refresh)
        # analysis: disable=CC704 one-time cache seeding: runs once per flag lifetime (cell-miss branch), every later call reads the cached cell
        cell[0] = bool(GLOBAL_FLAGS.get(flag))  # seeds the FLAGS_ env var
    return cell[0]


def pallas_enabled(flag: str) -> bool:
    """Flag on AND running on a TPU backend."""
    if not _cached_flag(flag):
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # no backend initialised / plugin init failed: not a TPU
        return False


def warn_fallback(kernel: str, exc: Exception) -> None:
    """Counted (every occurrence) + warned (once) when a Pallas kernel fails
    and the XLA path is used — silent permanent degradation is worse than one
    log line, and the counter makes the degradation scrapeable."""
    _fallbacks_total.labels(kernel=kernel).inc()
    if kernel not in _warned:
        _warned.add(kernel)
        _logger.warning("Pallas kernel %s failed (%s); using XLA fallback", kernel, exc)
