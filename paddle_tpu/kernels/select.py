"""Kernel-path selection: one place deciding Pallas vs XLA-fallback.

The applicability checks run BEFORE tracing so a shape the Mosaic compiler
cannot lower never reaches jit (a lowering error inside a captured train step
cannot be caught by the eager try/except)."""

from __future__ import annotations

import logging
from typing import Any

import jax
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.flags import GLOBAL_FLAGS

# named TPUCompilerParams before jax 0.5 — the one shared shim every kernel
# module imports (keep version dances out of the kernels themselves)
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

_logger = logging.getLogger("paddle_tpu.kernels")
_warned: set = set()


def pallas_enabled(flag: str) -> bool:
    """Flag on AND running on a TPU backend."""
    if not GLOBAL_FLAGS.get(flag):
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # no backend initialised / plugin init failed: not a TPU
        return False


def warn_fallback(kernel: str, exc: Exception) -> None:
    """One-time warning when a Pallas kernel fails and the XLA path is used —
    silent permanent degradation is worse than one log line."""
    if kernel not in _warned:
        _warned.add(kernel)
        _logger.warning("Pallas kernel %s failed (%s); using XLA fallback", kernel, exc)
