"""Kernel-path selection: one place deciding Pallas vs XLA-fallback.

The applicability checks run BEFORE tracing so a shape the Mosaic compiler
cannot lower never reaches jit (a lowering error inside a captured train step
cannot be caught by the eager try/except)."""

from __future__ import annotations

import logging
from typing import Any

import jax
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.flags import GLOBAL_FLAGS
from paddle_tpu.observability import get_registry

# named TPUCompilerParams before jax 0.5 — the one shared shim every kernel
# module imports (keep version dances out of the kernels themselves)
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

_logger = logging.getLogger("paddle_tpu.kernels")
_warned: set = set()
_fallbacks_total = get_registry().counter(
    "paddle_tpu_kernel_fallbacks_total",
    "Pallas kernel failures that degraded to the XLA fallback path, by kernel.",
    labelnames=("kernel",),
)


def pallas_enabled(flag: str) -> bool:
    """Flag on AND running on a TPU backend."""
    if not GLOBAL_FLAGS.get(flag):
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # no backend initialised / plugin init failed: not a TPU
        return False


def warn_fallback(kernel: str, exc: Exception) -> None:
    """Counted (every occurrence) + warned (once) when a Pallas kernel fails
    and the XLA path is used — silent permanent degradation is worse than one
    log line, and the counter makes the degradation scrapeable."""
    _fallbacks_total.labels(kernel=kernel).inc()
    if kernel not in _warned:
        _warned.add(kernel)
        _logger.warning("Pallas kernel %s failed (%s); using XLA fallback", kernel, exc)
