"""Benchmark-driven kernel-config autotuning.

Reference: ``paddle/phi/kernels/autotune/auto_tune_base.h:48`` (time each
candidate kernel config at first use) + ``cache.h:97`` (per-shape config
cache). TPU-native shape: the tunable axis is the Pallas block geometry
(blk_q/blk_k for flash attention, row-block for rms_norm) — the MXU/VMEM
trade-off XLA cannot make for a hand-written kernel.

Protocol: at the first call for a given (kernel, shape-key), each candidate
config is compiled and timed on the live backend (median of ``repeats`` runs
after a warmup); the winner is cached in-process and optionally persisted to
a JSON file (``FLAGS_kernel_autotune_cache`` path) so later processes skip
the sweep. Disabled by default (``FLAGS_use_kernel_autotune``) — tuning costs
a few hundred ms per shape and is meant for long training runs / benches.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax

from paddle_tpu.flags import GLOBAL_FLAGS, define_flag
from paddle_tpu.observability import get_registry

define_flag("use_kernel_autotune", bool, False, "Time Pallas block-size candidates at first use per shape.")
define_flag("kernel_autotune_cache", str, "", "Optional JSON file persisting autotune picks across processes.")
define_flag("kernel_autotune_verbose", bool, False, "Echo autotune pick lines at INFO on stderr (replaces the old PADDLE_TPU_AUTOTUNE_VERBOSE env print).")

_logger = logging.getLogger("paddle_tpu.kernels.autotune")
_picks_total = get_registry().counter(
    "paddle_tpu_autotune_picks_total",
    "Autotune sweeps completed (a config timed, picked and cached), by kernel.",
    labelnames=("kernel",),
)
_verbose_state: List[Any] = []  # [handler, prior logger level] while installed


def _sync_verbose_logging(enabled: bool) -> None:
    """Opt-in stderr echo of pick lines (FLAGS_kernel_autotune_verbose): the
    observability-layer replacement for the old raw print. Driven by an
    on_change listener (registered below), so flipping the flag off removes
    the handler and restores the module logger's prior level immediately —
    not only when the next uncached sweep happens to run."""
    if enabled and not _verbose_state:
        import sys

        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter("%(message)s"))
        _verbose_state[:] = [h, _logger.level]
        _logger.addHandler(h)
        if _logger.getEffectiveLevel() > logging.INFO:
            _logger.setLevel(logging.INFO)
    elif not enabled and _verbose_state:
        h, prior = _verbose_state
        _logger.removeHandler(h)
        _logger.setLevel(prior)
        _verbose_state.clear()


def _refresh_verbose(value: Any) -> None:
    _sync_verbose_logging(bool(value))


GLOBAL_FLAGS.on_change("kernel_autotune_verbose", _refresh_verbose)
_sync_verbose_logging(bool(GLOBAL_FLAGS.get("kernel_autotune_verbose")))  # seeds env

# autotune() and the cache's load/persist path run once per KERNEL CALL on
# tuned shapes (e.g. _autotune_rms_rows fires on every fused_rms_norm
# dispatch), so their flag reads are on_change-cached locals instead of
# registry-lock reads (analyzer check CC704, the _NAN_CHECK discipline)
_TUNE_ENABLED = [False]
_CACHE_PATH = [""]


def _refresh_tune_enabled(value: Any) -> None:
    _TUNE_ENABLED[0] = bool(value)


def _refresh_cache_path(value: Any) -> None:
    _CACHE_PATH[0] = str(value or "")


GLOBAL_FLAGS.on_change("use_kernel_autotune", _refresh_tune_enabled)
GLOBAL_FLAGS.on_change("kernel_autotune_cache", _refresh_cache_path)
_TUNE_ENABLED[0] = bool(GLOBAL_FLAGS.get("use_kernel_autotune"))  # seeds env
_CACHE_PATH[0] = str(GLOBAL_FLAGS.get("kernel_autotune_cache") or "")

__all__ = ["autotune", "AutotuneCache", "cache"]


class AutotuneCache:
    """Per-process (kernel, key) → config cache with optional JSON persistence."""

    def __init__(self) -> None:
        self._picks: Dict[str, Any] = {}
        self._loaded_path: Optional[str] = None

    @staticmethod
    def _k(kernel: str, key: Tuple) -> str:
        return f"{kernel}|{'|'.join(map(str, key))}"

    def _maybe_load(self) -> None:
        path = _CACHE_PATH[0]
        if path and path != self._loaded_path and os.path.exists(path):
            try:
                with open(path) as f:
                    stored = json.load(f)
                # stored configs are JSON lists; callers use tuples
                self._picks.update({k: tuple(v) if isinstance(v, list) else v for k, v in stored.items()})
            except Exception as exc:  # noqa: BLE001 - cache corruption is not fatal
                _logger.warning("autotune cache %s unreadable: %s", path, exc)
            self._loaded_path = path

    def get(self, kernel: str, key: Tuple) -> Optional[Any]:
        self._maybe_load()
        return self._picks.get(self._k(kernel, key))

    def put(self, kernel: str, key: Tuple, config: Any) -> None:
        self._picks[self._k(kernel, key)] = config
        path = _CACHE_PATH[0]
        if path:
            try:
                serial = {
                    k: list(v) if isinstance(v, tuple) else v for k, v in self._picks.items()
                }
                with open(path, "w") as f:
                    json.dump(serial, f, indent=1)
            except Exception as exc:  # noqa: BLE001 - persistence is best-effort; in-process cache still holds the pick
                _logger.warning("autotune cache %s not writable: %s", path, exc)

    def clear(self) -> None:
        self._picks.clear()
        self._loaded_path = None


cache = AutotuneCache()


def _time_once(fn: Callable[[], Any]) -> float:
    t0 = time.perf_counter()
    out = fn()
    jax.block_until_ready(out)
    return time.perf_counter() - t0


def autotune(
    kernel: str,
    key: Tuple,
    candidates: Sequence[Any],
    build: Callable[[Any], Optional[Callable[[], Any]]],
    default: Any,
    repeats: int = 3,
) -> Any:
    """Pick the fastest config for ``kernel`` at shape ``key``.

    ``build(config)`` returns a zero-arg runner executing the kernel with that
    config on representative inputs, or None if the config is inapplicable.
    Falls back to ``default`` when tuning is disabled, off-TPU, or every
    candidate fails. The chosen config is cached under (kernel, key).
    """
    if not _TUNE_ENABLED[0]:
        return default
    try:
        if jax.default_backend() != "tpu":
            return default
    except Exception:  # noqa: BLE001 - no backend initialised: tuning is TPU-only
        return default
    hit = cache.get(kernel, key)
    if hit is not None:
        return hit
    best, best_t = None, float("inf")
    results: List[Tuple[Any, float]] = []
    for cfg in candidates:
        runner = build(cfg)
        if runner is None:
            continue
        try:
            _time_once(runner)  # compile + settle
            t = min(_time_once(runner) for _ in range(max(1, repeats)))
        except Exception as exc:  # noqa: BLE001 - candidate may not lower
            _logger.debug("autotune %s cfg=%s failed: %r", kernel, cfg, exc)
            continue
        results.append((cfg, t))
        if t < best_t:
            best, best_t = cfg, t
    if best is None:
        best = default
    cache.put(kernel, key, best)
    _picks_total.labels(kernel=kernel).inc()
    _logger.info(
        "autotune %s key=%s picked %s (%.3fms) over %s",
        kernel,
        key,
        best,
        best_t * 1e3 if best_t < float("inf") else -1.0,
        [(c, round(t * 1e3, 3)) for c, t in results],
    )
    return best
