"""``paddle_tpu.sparse`` — sparse tensor family over jax BCOO/BCSR.

Reference: ``paddle/phi/core/sparse_coo_tensor.h`` /
``sparse_csr_tensor.h`` + ``python/paddle/sparse/`` (51 ops in
``sparse_ops.yaml``). TPU-native redesign: storage is
``jax.experimental.sparse.BCOO`` (indices ``[nnz, ndim]`` + values), which
XLA compiles as gather/scatter/segment-sum programs — there are no sparse
MXU kernels, so the win is *memory* (O(nnz) storage, masked compute), the
same trade the reference's SparseCooTensor makes on GPU.

API parity: ``sparse_coo_tensor``, ``sparse_csr_tensor``,
``Tensor.to_sparse_coo``/``to_dense`` (installed on the dense Tensor),
value-wise unary ops, COO±COO elementwise, sparse×dense ``matmul``,
``masked_matmul``, ``coalesce``, ``transpose``, ``sum``.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from paddle_tpu.core.tensor import Tensor

__all__ = [
    "SparseCooTensor",
    "SparseCsrTensor",
    "sparse_coo_tensor",
    "sparse_csr_tensor",
    "is_same_shape",
    "add",
    "subtract",
    "multiply",
    "divide",
    "matmul",
    "masked_matmul",
    "relu",
    "abs",
    "sin",
    "sinh",
    "tan",
    "tanh",
    "asin",
    "asinh",
    "atan",
    "atanh",
    "sqrt",
    "square",
    "log1p",
    "expm1",
    "neg",
    "pow",
    "cast",
    "transpose",
    "sum",
    "coalesce",
    "acos",
    "acosh",
    "isnan",
    "leaky_relu",
    "relu6",
    "divide_scalar",
    "scale",
    "full_like",
    "mv",
    "addmm",
    "mask_as",
    "reshape",
    "slice",
    "softmax",
]


class SparseCooTensor:
    """COO sparse tensor (reference ``sparse_coo_tensor.h``): paddle-layout
    ``indices [sparse_dim, nnz]`` + ``values [nnz, ...dense dims]``."""

    is_sparse_coo_flag = True

    def __init__(self, bcoo: jsparse.BCOO) -> None:
        self._bcoo = bcoo

    # -- constructors --------------------------------------------------------
    @classmethod
    def from_parts(cls, indices: Any, values: Any, shape: Sequence[int]) -> "SparseCooTensor":
        idx = jnp.asarray(indices.data if isinstance(indices, Tensor) else indices)
        val = jnp.asarray(values.data if isinstance(values, Tensor) else values)
        # paddle stores [sparse_dim, nnz]; BCOO wants [nnz, sparse_dim]
        bcoo = jsparse.BCOO((val, idx.T.astype(jnp.int32)), shape=tuple(int(s) for s in shape))
        return cls(bcoo)

    @classmethod
    def from_dense(cls, x: Any, sparse_dim: Optional[int] = None) -> "SparseCooTensor":
        arr = x.data if isinstance(x, Tensor) else jnp.asarray(x)
        n_sparse = sparse_dim if sparse_dim is not None else arr.ndim
        return cls(jsparse.BCOO.fromdense(arr, n_batch=0, n_dense=arr.ndim - n_sparse))

    # -- paddle surface ------------------------------------------------------
    @property
    def shape(self) -> List[int]:
        return list(self._bcoo.shape)

    @property
    def dtype(self) -> Any:
        return self._bcoo.data.dtype

    @property
    def nnz(self) -> int:
        return int(self._bcoo.nse)

    def indices(self) -> Tensor:
        return Tensor(self._bcoo.indices.T)  # [sparse_dim, nnz]

    def values(self) -> Tensor:
        return Tensor(self._bcoo.data)

    def to_dense(self) -> Tensor:
        b = self._bcoo
        if b.data.dtype == jnp.bool_:
            # scatter-add (todense) rejects bool; widen and cast back
            d = jsparse.BCOO((b.data.astype(jnp.int8), b.indices), shape=b.shape)
            return Tensor(d.todense().astype(jnp.bool_))
        return Tensor(b.todense())

    def to_sparse_csr(self) -> "SparseCsrTensor":
        return SparseCsrTensor.from_coo(self)

    def is_sparse(self) -> bool:
        return True

    def is_sparse_coo(self) -> bool:
        return True

    def is_sparse_csr(self) -> bool:
        return False

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(self._bcoo.sum_duplicates())

    def astype(self, dtype: Any) -> "SparseCooTensor":
        from paddle_tpu.core.dtypes import convert_dtype

        b = self._bcoo
        return SparseCooTensor(
            jsparse.BCOO((b.data.astype(convert_dtype(dtype)), b.indices), shape=b.shape)
        )

    def numpy(self) -> np.ndarray:
        return np.asarray(self._bcoo.todense())

    def __repr__(self) -> str:
        return (
            f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, dtype={self.dtype})"
        )

    # -- value-wise + arithmetic --------------------------------------------
    def _map_values(self, fn) -> "SparseCooTensor":
        b = self._bcoo
        return SparseCooTensor(jsparse.BCOO((fn(b.data), b.indices), shape=b.shape))

    def __neg__(self) -> "SparseCooTensor":
        return self._map_values(jnp.negative)

    def __add__(self, other: Any) -> "SparseCooTensor":
        return add(self, other)

    def __sub__(self, other: Any) -> "SparseCooTensor":
        return subtract(self, other)

    def __mul__(self, other: Any) -> Any:
        return multiply(self, other)

    def __matmul__(self, other: Any) -> Any:
        return matmul(self, other)

    def matmul(self, other: Any) -> Any:
        return matmul(self, other)

    # transposes sparse dims only (paddle sparse.transpose parity for COO)
    def transpose(self, perm: Sequence[int]) -> "SparseCooTensor":
        return transpose(self, perm)


class SparseCsrTensor:
    """CSR sparse matrix (reference ``sparse_csr_tensor.h``): crows/cols/values.

    Stored as BCSR for 2-D; batched CSR falls back through COO.
    """

    def __init__(self, crows: Any, cols: Any, values: Any, shape: Sequence[int]) -> None:
        self._crows = jnp.asarray(crows.data if isinstance(crows, Tensor) else crows, jnp.int32)
        self._cols = jnp.asarray(cols.data if isinstance(cols, Tensor) else cols, jnp.int32)
        self._values = jnp.asarray(values.data if isinstance(values, Tensor) else values)
        self._shape = tuple(int(s) for s in shape)

    @classmethod
    def from_coo(cls, coo: SparseCooTensor) -> "SparseCsrTensor":
        if len(coo.shape) != 2:
            raise ValueError("SparseCsrTensor supports 2-D matrices")
        b = coo.coalesce()._bcoo
        rows = b.indices[:, 0]
        cols = b.indices[:, 1]
        order = jnp.lexsort((cols, rows))
        rows, cols, vals = rows[order], cols[order], b.data[order]
        n = coo.shape[0]
        crows = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(jnp.bincount(rows, length=n)).astype(jnp.int32)]
        )
        return cls(crows, cols, vals, coo.shape)

    @property
    def shape(self) -> List[int]:
        return list(self._shape)

    @property
    def dtype(self) -> Any:
        return self._values.dtype

    @property
    def nnz(self) -> int:
        return int(self._values.shape[0])

    def crows(self) -> Tensor:
        return Tensor(self._crows)

    def cols(self) -> Tensor:
        return Tensor(self._cols)

    def values(self) -> Tensor:
        return Tensor(self._values)

    def to_sparse_coo(self, sparse_dim: int = 2) -> SparseCooTensor:
        counts = jnp.diff(self._crows)
        rows = jnp.repeat(jnp.arange(self._shape[0], dtype=jnp.int32), counts,
                          total_repeat_length=self.nnz)
        idx = jnp.stack([rows, self._cols], axis=1)
        return SparseCooTensor(jsparse.BCOO((self._values, idx), shape=self._shape))

    def to_dense(self) -> Tensor:
        return self.to_sparse_coo().to_dense()

    def is_sparse(self) -> bool:
        return True

    def is_sparse_coo(self) -> bool:
        return False

    def is_sparse_csr(self) -> bool:
        return True

    def numpy(self) -> np.ndarray:
        return np.asarray(self.to_dense().data)

    def __repr__(self) -> str:
        return f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, dtype={self.dtype})"


# ---------------------------------------------------------------------------
# functional API (paddle.sparse.*)
# ---------------------------------------------------------------------------


def sparse_coo_tensor(
    indices: Any,
    values: Any,
    shape: Optional[Sequence[int]] = None,
    dtype: Any = None,
    place: Any = None,
    stop_gradient: bool = True,
) -> SparseCooTensor:
    """``paddle.sparse.sparse_coo_tensor`` parity."""
    idx = np.asarray(indices.numpy() if isinstance(indices, Tensor) else indices)
    val = values.data if isinstance(values, Tensor) else jnp.asarray(values)
    if dtype is not None:
        from paddle_tpu.core.dtypes import convert_dtype

        val = val.astype(convert_dtype(dtype))
    if shape is None:
        sparse_shape = tuple(int(m) + 1 for m in idx.max(axis=1))
        shape = sparse_shape + tuple(val.shape[1:])
    return SparseCooTensor.from_parts(idx, val, shape)


def sparse_csr_tensor(
    crows: Any, cols: Any, values: Any, shape: Sequence[int], dtype: Any = None, **kw: Any
) -> SparseCsrTensor:
    t = SparseCsrTensor(crows, cols, values, shape)
    if dtype is not None:
        from paddle_tpu.core.dtypes import convert_dtype

        t._values = t._values.astype(convert_dtype(dtype))
    return t


def is_same_shape(x: Any, y: Any) -> bool:
    return list(x.shape) == list(y.shape)


def _coo(x: Any) -> SparseCooTensor:
    if isinstance(x, SparseCooTensor):
        return x
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    raise TypeError(f"expected a sparse tensor, got {type(x).__name__}")


def add(x: SparseCooTensor, y: Any) -> SparseCooTensor:
    """COO + COO (union of patterns) — reference ``sparse/unary_kernel`` add."""
    xb = _coo(x)._bcoo
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        yb = _coo(y)._bcoo
        out = jsparse.BCOO(
            (jnp.concatenate([xb.data, yb.data]), jnp.concatenate([xb.indices, yb.indices])),
            shape=xb.shape,
        ).sum_duplicates()
        return SparseCooTensor(out)
    raise TypeError("sparse.add supports sparse + sparse; use to_dense() for mixed")


def subtract(x: SparseCooTensor, y: Any) -> SparseCooTensor:
    return add(x, _coo(y)._map_values(jnp.negative))


def multiply(x: SparseCooTensor, y: Any) -> Any:
    """Elementwise multiply: sparse × dense keeps the sparse pattern (a mask);
    sparse × scalar scales values."""
    xb = _coo(x)._bcoo
    if isinstance(y, (int, float)):
        return SparseCooTensor(jsparse.BCOO((xb.data * y, xb.indices), shape=xb.shape))
    if isinstance(y, Tensor) or hasattr(y, "shape"):
        dense = y.data if isinstance(y, Tensor) else jnp.asarray(y)
        gathered = dense[tuple(xb.indices[:, i] for i in range(xb.indices.shape[1]))]
        return SparseCooTensor(jsparse.BCOO((xb.data * gathered, xb.indices), shape=xb.shape))
    raise TypeError(f"cannot multiply sparse by {type(y).__name__}")


def divide(x: SparseCooTensor, y: Any) -> Any:
    if isinstance(y, (int, float)):
        return multiply(x, 1.0 / y)
    dense = y.data if isinstance(y, Tensor) else jnp.asarray(y)
    xb = _coo(x)._bcoo
    gathered = dense[tuple(xb.indices[:, i] for i in range(xb.indices.shape[1]))]
    return SparseCooTensor(jsparse.BCOO((xb.data / gathered, xb.indices), shape=xb.shape))


def matmul(x: Any, y: Any) -> Any:
    """sparse @ dense → dense (reference ``sparse/matmul_kernel.cu``); XLA
    lowers bcoo_dot_general to gather + segment-sum."""
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        xb = _coo(x)._bcoo
        dense = y.data if isinstance(y, Tensor) else jnp.asarray(y)
        out = jsparse.bcoo_dot_general(
            xb, dense, dimension_numbers=(([xb.ndim - 1], [0]), ([], []))
        )
        return Tensor(out)
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        # dense @ sparse via (sparse^T @ dense^T)^T
        yb = _coo(y)
        dense = x.data if isinstance(x, Tensor) else jnp.asarray(x)
        yt = transpose(yb, list(range(len(yb.shape)))[::-1])
        return Tensor(
            jsparse.bcoo_dot_general(
                yt._bcoo, dense.T, dimension_numbers=(([1], [0]), ([], []))
            ).T
        )
    raise TypeError("sparse.matmul needs at least one sparse operand")


def masked_matmul(x: Any, y: Any, mask: SparseCooTensor) -> SparseCooTensor:
    """(x @ y) evaluated ONLY at ``mask``'s nonzero positions (reference
    ``sparse/masked_matmul_kernel``): O(nnz·K) work instead of O(M·N·K)."""
    xd = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    yd = y.data if isinstance(y, Tensor) else jnp.asarray(y)
    mb = _coo(mask)._bcoo
    rows = mb.indices[:, 0]
    cols = mb.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", xd[rows, :], yd[:, cols].T)
    return SparseCooTensor(jsparse.BCOO((vals, mb.indices), shape=mb.shape))


def _unary(name: str, fn) -> Any:
    def op(x: Any) -> Any:
        return _coo(x)._map_values(fn)

    op.__name__ = name
    op.__doc__ = f"Value-wise ``{name}`` on a sparse tensor (reference sparse_ops.yaml)."
    return op


relu = _unary("relu", jax.nn.relu)
abs = _unary("abs", jnp.abs)  # noqa: A001 - paddle API name
sin = _unary("sin", jnp.sin)
sinh = _unary("sinh", jnp.sinh)
tan = _unary("tan", jnp.tan)
tanh = _unary("tanh", jnp.tanh)
asin = _unary("asin", jnp.arcsin)
asinh = _unary("asinh", jnp.arcsinh)
atan = _unary("atan", jnp.arctan)
atanh = _unary("atanh", jnp.arctanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
log1p = _unary("log1p", jnp.log1p)
expm1 = _unary("expm1", jnp.expm1)
neg = _unary("neg", jnp.negative)


def pow(x: Any, factor: float) -> SparseCooTensor:  # noqa: A001
    return _coo(x)._map_values(lambda v: jnp.power(v, factor))


def cast(x: Any, index_dtype: Any = None, value_dtype: Any = None) -> SparseCooTensor:
    b = _coo(x)._bcoo
    from paddle_tpu.core.dtypes import convert_dtype

    data = b.data if value_dtype is None else b.data.astype(convert_dtype(value_dtype))
    idx = b.indices if index_dtype is None else b.indices.astype(convert_dtype(index_dtype))
    return SparseCooTensor(jsparse.BCOO((data, idx), shape=b.shape))


def transpose(x: SparseCooTensor, perm: Sequence[int]) -> SparseCooTensor:
    b = _coo(x)._bcoo
    perm = [int(p) for p in perm]
    n_sp = b.indices.shape[1]
    if sorted(perm) != list(range(len(b.shape))):
        raise ValueError(f"perm {perm} is not a permutation of {len(b.shape)} dims")
    if perm[n_sp:] != list(range(n_sp, len(b.shape))):
        raise NotImplementedError(
            "sparse.transpose permutes sparse dims only; dense trailing dims "
            f"must stay in place (sparse_dim={n_sp}, perm={perm})"
        )
    new_idx = b.indices[:, jnp.asarray(perm[:n_sp])]
    new_shape = tuple(b.shape[p] for p in perm)
    return SparseCooTensor(jsparse.BCOO((b.data, new_idx), shape=new_shape))


def sum(x: Any, axis: Optional[int] = None, dtype: Any = None, keepdim: bool = False) -> Any:  # noqa: A001
    """Sum over the whole tensor (dense scalar) or one sparse axis."""
    b = _coo(x)._bcoo
    if axis is None:
        out = jnp.sum(b.data)
        if dtype is not None:
            from paddle_tpu.core.dtypes import convert_dtype

            out = out.astype(convert_dtype(dtype))
        return Tensor(out)
    nd = len(b.shape)
    axis = axis % nd
    n_sp = b.indices.shape[1]
    if axis >= n_sp:
        # dense trailing axis: reduce inside the values block
        # (values axis 0 is nnz, so tensor axis maps to values axis - n_sp + 1)
        v_axis = axis - n_sp + 1
        new_data = jnp.sum(b.data, axis=v_axis)
        new_shape = tuple(s for i, s in enumerate(b.shape) if i != axis)
        res = SparseCooTensor(jsparse.BCOO((new_data, b.indices), shape=new_shape))
        if keepdim:
            dense = res.to_dense().data
            return SparseCooTensor.from_dense(jnp.expand_dims(dense, axis))
        return res
    keep = [i for i in range(n_sp) if i != axis]
    new_idx = b.indices[:, jnp.asarray(keep)]
    new_shape = tuple(b.shape[i] for i in keep) + tuple(b.shape[n_sp:])
    out = jsparse.BCOO((b.data, new_idx), shape=new_shape).sum_duplicates()
    res = SparseCooTensor(out)
    if keepdim:
        dense = res.to_dense().data
        return SparseCooTensor.from_dense(jnp.expand_dims(dense, axis))
    return res


def coalesce(x: SparseCooTensor) -> SparseCooTensor:
    return _coo(x).coalesce()


# -- sparse long-tail parity (VERDICT r5: close sparse_ops.yaml gaps) --------

acos = _unary("acos", jnp.arccos)
acosh = _unary("acosh", jnp.arccosh)
isnan = _unary("isnan", jnp.isnan)
leaky_relu = _unary("leaky_relu", jax.nn.leaky_relu)
relu6 = _unary("relu6", lambda v: jnp.clip(v, 0.0, 6.0))


def divide_scalar(x: Any, scalar: float) -> SparseCooTensor:
    return _coo(x)._map_values(lambda v: v / scalar)


def scale(x: Any, scale: float = 1.0, bias: float = 0.0, bias_after_scale: bool = True):
    if bias != 0.0:
        raise ValueError("sparse.scale with bias would densify; bias must be 0")
    return _coo(x)._map_values(lambda v: v * scale)


def full_like(x: Any, fill_value: float, dtype: Any = None):
    from paddle_tpu.core.dtypes import convert_dtype

    dt = convert_dtype(dtype) if dtype else None
    return _coo(x)._map_values(lambda v: jnp.full_like(v, fill_value, dtype=dt))


def mv(x: Any, vec: Any) -> Tensor:
    """Sparse matrix x dense vector (reference ``sparse mv kernel``)."""
    v = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    return Tensor(_coo(x)._bcoo @ v)


def addmm(input: Any, x: Any, y: Any, beta: float = 1.0, alpha: float = 1.0):  # noqa: A002
    """beta * input + alpha * (x @ y) with sparse ``x`` (reference addmm)."""
    yv = y._data if isinstance(y, Tensor) else jnp.asarray(y)
    iv = input._data if isinstance(input, Tensor) else jnp.asarray(input)
    return Tensor(beta * iv + alpha * (_coo(x)._bcoo @ yv))


def mask_as(x: Any, mask: Any) -> SparseCooTensor:
    """Take dense ``x``'s values at ``mask``'s sparsity pattern (reference
    ``sparse mask_as``)."""
    xv = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    mb = _coo(mask)._bcoo
    n = mb.indices.shape[0]
    idx = tuple(mb.indices[:, d] for d in range(mb.indices.shape[1]))
    vals = xv[idx]
    return SparseCooTensor(jsparse.BCOO((vals, mb.indices), shape=mb.shape))


def reshape(x: Any, shape: Sequence[int]) -> SparseCooTensor:
    """Reshape a COO tensor by re-deriving flat indices (reference sparse
    reshape kernel)."""
    c = _coo(x).coalesce()._bcoo
    old_shape = c.shape
    strides = np.cumprod([1] + list(old_shape[::-1][:-1]))[::-1]
    flat = jnp.zeros((c.indices.shape[0],), c.indices.dtype)
    for d in range(len(old_shape)):  # builtin sum is shadowed by sparse.sum
        flat = flat + c.indices[:, d] * int(strides[d])
    shape = tuple(int(s) for s in shape)
    if int(np.prod(shape)) != int(np.prod(old_shape)):
        raise ValueError(f"cannot reshape {old_shape} to {shape}")
    new_strides = np.cumprod([1] + list(shape[::-1][:-1]))[::-1]
    new_idx = jnp.stack(
        [(flat // int(new_strides[d])) % shape[d] for d in range(len(shape))], axis=1
    )
    return SparseCooTensor(jsparse.BCOO((c.data, new_idx), shape=shape))


def slice(x: Any, axes: Sequence[int], starts: Sequence[int], ends: Sequence[int]):  # noqa: A001
    """Slice a COO tensor (reference sparse slice kernel): filter coordinates
    into the window, shift, rebuild — stays sparse, static nnz bound."""
    c = _coo(x).coalesce()._bcoo
    shp = list(c.shape)
    keep = jnp.ones((c.indices.shape[0],), bool)
    shift = [0] * len(shp)
    for ax, st, en in zip(axes, starts, ends):
        ax = int(ax) % len(shp)
        st = int(st) if st >= 0 else int(st) + shp[ax]
        en = min(int(en) if en >= 0 else int(en) + shp[ax], shp[ax])
        keep = keep & (c.indices[:, ax] >= st) & (c.indices[:, ax] < en)
        shift[ax] = st
        shp[ax] = en - st
    data = jnp.where(keep, c.data, 0)
    idx = c.indices - jnp.asarray(shift, c.indices.dtype)[None, :]
    idx = jnp.where(keep[:, None], idx, 0)  # parked at origin with value 0
    out = jsparse.BCOO((data, idx), shape=tuple(shp)).sum_duplicates()
    return SparseCooTensor(out)


def softmax(x: Any, axis: int = -1):
    """Sparse softmax over the last axis (reference ``sparse softmax
    kernel``): softmax over the nonzeros of each row, zeros stay zero."""
    if axis != -1:
        raise NotImplementedError("sparse.softmax supports axis=-1")
    c = _coo(x).coalesce()._bcoo
    nd = len(c.shape)
    row_shape = c.shape[:-1]
    row_strides = np.cumprod([1] + list(row_shape[::-1][:-1]))[::-1]
    row = jnp.zeros((c.indices.shape[0],), c.indices.dtype)
    for d in range(nd - 1):  # builtin sum is shadowed by sparse.sum
        row = row + c.indices[:, d] * int(row_strides[d])
    n_rows = int(np.prod(c.shape[:-1]))
    row = row.astype(jnp.int32)
    row_max = jax.ops.segment_max(c.data, row, n_rows)
    e = jnp.exp(c.data - row_max[row])
    denom = jax.ops.segment_sum(e, row, n_rows)
    return SparseCooTensor(jsparse.BCOO((e / denom[row], c.indices), shape=c.shape))


# -- install dense-Tensor conversions (paddle Tensor API parity) -------------


def _tensor_to_sparse_coo(self: Tensor, sparse_dim: Optional[int] = None) -> SparseCooTensor:
    return SparseCooTensor.from_dense(self, sparse_dim)


def _tensor_to_sparse_csr(self: Tensor) -> SparseCsrTensor:
    return SparseCooTensor.from_dense(self).to_sparse_csr()


Tensor.to_sparse_coo = _tensor_to_sparse_coo
Tensor.to_sparse_csr = _tensor_to_sparse_csr
