"""High-level training API (reference ``python/paddle/hapi``)."""

from paddle_tpu.hapi.model import Model  # noqa: F401
from paddle_tpu.hapi.callbacks import Callback, EarlyStopping, LRScheduler  # noqa: F401
