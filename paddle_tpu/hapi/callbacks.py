"""Training callbacks (reference ``python/paddle/hapi/callbacks.py``)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

__all__ = ["Callback", "EarlyStopping", "LRScheduler", "ProgBarLogger"]


class Callback:
    def set_model(self, model: Any) -> None:
        self.model = model

    def on_train_begin(self, logs: Optional[Dict] = None) -> None: ...
    def on_train_end(self, logs: Optional[Dict] = None) -> None: ...
    def on_epoch_begin(self, epoch: int, logs: Optional[Dict] = None) -> None: ...
    def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None) -> None: ...
    def on_train_batch_begin(self, step: int, logs: Optional[Dict] = None) -> None: ...
    def on_train_batch_end(self, step: int, logs: Optional[Dict] = None) -> None: ...
    def on_eval_begin(self, logs: Optional[Dict] = None) -> None: ...
    def on_eval_end(self, logs: Optional[Dict] = None) -> None: ...


class EarlyStopping(Callback):
    def __init__(
        self,
        monitor: str = "loss",
        mode: str = "auto",
        patience: int = 0,
        min_delta: float = 0.0,
        baseline: Optional[float] = None,
        save_best_model: bool = True,
    ) -> None:
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.wait = 0
        self.best: Optional[float] = baseline
        self.stopped_epoch = 0
        self.stop_training = False
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode

    def _better(self, cur: float) -> bool:
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs: Optional[Dict] = None) -> None:
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple)) else cur)
        if self._better(cur):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step: bool = True, by_epoch: bool = False) -> None:
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from paddle_tpu.optimizer.lr import LRScheduler as Sched

        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step: int, logs: Optional[Dict] = None) -> None:
        if self.by_step and (s := self._sched()) is not None:
            s.step()

    def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None) -> None:
        if self.by_epoch and (s := self._sched()) is not None:
            s.step()


class ProgBarLogger(Callback):
    def __init__(self, log_freq: int = 10, verbose: int = 1) -> None:
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_batch_end(self, step: int, logs: Optional[Dict] = None) -> None:
        if self.verbose and step % self.log_freq == 0:
            items = ", ".join(f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                              for k, v in (logs or {}).items())
            print(f"step {step} - {items}")
