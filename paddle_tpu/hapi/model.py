"""hapi Model: fit / evaluate / predict.

Reference: ``python/paddle/hapi/model.py`` — the Keras-style facade over a
Layer: ``prepare(optimizer, loss, metrics)`` then ``fit``/``evaluate``/
``predict``/``save``/``load``. The train step runs under ``jit.to_static``
(one compiled XLA program per shape signature) — the hapi path gets the
compiled-executor behavior the reference gets from static graphs.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

import paddle_tpu
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.hapi.callbacks import Callback, ProgBarLogger

__all__ = ["Model"]


def _to_batches(data: Any, batch_size: int, shuffle: bool, seed: int = 0):
    """Accept a DataLoader-like iterable or an (inputs, labels) array pair.
    The range stop drops any last partial batch (keeps one compiled shape)
    while a dataset smaller than one batch still yields once."""
    if hasattr(data, "__iter__") and not isinstance(data, (tuple, list)):
        yield from data
        return
    xs, ys = data
    xs = np.asarray(xs)
    ys = np.asarray(ys)
    n = xs.shape[0]
    idx = np.arange(n)
    if shuffle:
        np.random.default_rng(seed).shuffle(idx)
    for i in range(0, n - n % batch_size or n, batch_size):
        sel = idx[i : i + batch_size]
        yield xs[sel], ys[sel]


class Model:
    def __init__(self, network: Any, inputs: Any = None, labels: Any = None) -> None:
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics: List[Any] = []
        self._train_step = None

    def prepare(
        self,
        optimizer: Any = None,
        loss: Any = None,
        metrics: Any = None,
        amp_configs: Any = None,
    ) -> None:
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = list(metrics) if metrics is not None else []

        net, opt, loss_fn = self.network, optimizer, loss

        @paddle_tpu.jit.to_static
        def train_step(net: Any, opt: Any, x: Tensor, y: Tensor) -> Tensor:
            out = net(x)
            l = loss_fn(out, y)
            l.backward()
            opt.step()
            opt.clear_grad()
            return l

        self._train_step = train_step

    # -- training ----------------------------------------------------------
    def fit(
        self,
        train_data: Any = None,
        eval_data: Any = None,
        batch_size: int = 1,
        epochs: int = 1,
        eval_freq: int = 1,
        log_freq: int = 10,
        save_dir: Optional[str] = None,
        shuffle: bool = True,
        verbose: int = 1,
        callbacks: Optional[Sequence[Callback]] = None,
    ) -> Dict[str, List[float]]:
        assert self._optimizer is not None, "call prepare() first"
        import types

        if isinstance(train_data, types.GeneratorType):
            if epochs > 1:
                # a generator is one-shot: epochs 2..N would silently train
                # zero batches — materialize once instead
                train_data = list(train_data)
        cbs = list(callbacks or [])
        if verbose and not any(isinstance(cb, ProgBarLogger) for cb in cbs):
            cbs.append(ProgBarLogger(log_freq=log_freq, verbose=verbose))
        for cb in cbs:
            cb.set_model(self)
            cb.on_train_begin()
        history: Dict[str, List[float]] = {"loss": []}
        step = 0
        for epoch in range(epochs):
            for cb in cbs:
                cb.on_epoch_begin(epoch)
            self.network.train()
            epoch_losses = []
            for bx, by in _to_batches(train_data, batch_size, shuffle, seed=epoch):
                x = paddle_tpu.to_tensor(bx)
                y = paddle_tpu.to_tensor(by)
                loss = self._train_step(self.network, self._optimizer, x, y)
                lval = float(loss)
                epoch_losses.append(lval)
                step += 1
                for cb in cbs:
                    cb.on_train_batch_end(step, {"loss": lval})
            history["loss"].append(float(np.mean(epoch_losses)) if epoch_losses else 0.0)
            logs: Dict[str, Any] = {"loss": history["loss"][-1]}
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_data, batch_size=batch_size, verbose=0)
                logs.update(eval_logs)
                for cb in cbs:
                    cb.on_eval_end(logs)
            for cb in cbs:
                cb.on_epoch_end(epoch, logs)
            if any(getattr(cb, "stop_training", False) for cb in cbs):
                break
            if save_dir:
                self.save(os.path.join(save_dir, f"epoch_{epoch}"))
        for cb in cbs:
            cb.on_train_end()
        return history

    def evaluate(
        self, eval_data: Any, batch_size: int = 1, log_freq: int = 10, verbose: int = 1,
        callbacks: Any = None,
    ) -> Dict[str, float]:
        self.network.eval()
        losses = []
        for m in self._metrics:
            m.reset()
        with paddle_tpu.no_grad():
            for bx, by in _to_batches(eval_data, batch_size, shuffle=False):
                x = paddle_tpu.to_tensor(bx)
                y = paddle_tpu.to_tensor(by)
                out = self.network(x)
                if self._loss is not None:
                    losses.append(float(self._loss(out, y)))
                for m in self._metrics:
                    outs = m.compute(out, y) if hasattr(m, "compute") else (out, y)
                    if isinstance(outs, (tuple, list)):
                        m.update(*outs)
                    else:
                        m.update(outs)
        logs: Dict[str, float] = {}
        if losses:
            logs["eval_loss"] = float(np.mean(losses))
        for m in self._metrics:
            logs[f"eval_{m.name()}"] = m.accumulate()
        return logs

    def predict(self, test_data: Any, batch_size: int = 1, **kw: Any) -> List[np.ndarray]:
        self.network.eval()
        outs = []
        with paddle_tpu.no_grad():
            if hasattr(test_data, "__iter__") and not isinstance(test_data, (tuple, list, np.ndarray)):
                batches = test_data
            else:
                arr = np.asarray(test_data)
                batches = (arr[i : i + batch_size] for i in range(0, len(arr), batch_size))
            for bx in batches:
                if isinstance(bx, (tuple, list)):
                    bx = bx[0]
                outs.append(self.network(paddle_tpu.to_tensor(np.asarray(bx))).numpy())
        return outs

    # -- io ----------------------------------------------------------------
    def save(self, path: str, training: bool = True) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        paddle_tpu.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            paddle_tpu.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path: str, skip_mismatch: bool = False, reset_optimizer: bool = False) -> None:
        self.network.set_state_dict(paddle_tpu.load(path + ".pdparams"))
        if not reset_optimizer and self._optimizer is not None and os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(paddle_tpu.load(path + ".pdopt"))

    def parameters(self) -> List[Any]:
        return self.network.parameters()

    def summary(self, input_size: Any = None, dtype: Any = None) -> Dict[str, int]:
        total = sum(int(np.prod(p.shape)) for p in self.network.parameters())
        trainable = sum(
            int(np.prod(p.shape)) for p in self.network.parameters() if not p.stop_gradient
        )
        return {"total_params": total, "trainable_params": trainable}
