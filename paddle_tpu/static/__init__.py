"""``paddle_tpu.static`` — static-graph compat shims.

The reference's static mode (Program + StandaloneExecutor + CINN) maps onto
trace-and-compile: ``paddle_tpu.jit.to_static`` IS the static mode. This
module keeps the high-traffic ``paddle.static`` surface (InputSpec, save/load
inference model) for script portability.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax.numpy as jnp

from paddle_tpu.core.dtypes import convert_dtype

__all__ = ["InputSpec", "save_inference_model", "load_inference_model"]


class InputSpec:
    def __init__(self, shape: Sequence[Any], dtype: Any = "float32", name: Optional[str] = None, stop_gradient: bool = True) -> None:
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self) -> str:
        return f"InputSpec(shape={self.shape}, dtype={jnp.dtype(self.dtype).name}, name={self.name})"


def save_inference_model(path_prefix: str, feed_vars: Any, fetch_vars: Any, executor: Any = None, **kwargs: Any) -> None:
    """Trace-mode bridge for the static API (reference
    ``paddle/static/io.py`` save_inference_model): ``feed_vars`` is a list of
    :class:`InputSpec` (the trace-mode analog of feed Variables) and
    ``fetch_vars`` the Layer whose forward produces the fetches. Writes the
    same serialized-program bundle as ``paddle_tpu.jit.save``."""
    from paddle_tpu.jit import save as jit_save
    from paddle_tpu.nn.layer.layers import Layer

    layer = fetch_vars if isinstance(fetch_vars, Layer) else kwargs.get("program")
    if not isinstance(layer, Layer):
        raise TypeError(
            "trace-mode save_inference_model needs the model Layer as "
            "fetch_vars (or program=layer) and InputSpecs as feed_vars"
        )
    specs = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    jit_save(layer, path_prefix, input_spec=list(specs))


def load_inference_model(path_prefix: str, executor: Any = None, **kwargs: Any) -> Any:
    from paddle_tpu.jit.save_load import load

    return load(path_prefix)
