"""``paddle_tpu.static`` — static-graph compat shims.

The reference's static mode (Program + StandaloneExecutor + CINN) maps onto
trace-and-compile: ``paddle_tpu.jit.to_static`` IS the static mode. This
module keeps the high-traffic ``paddle.static`` surface (InputSpec, save/load
inference model) for script portability.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax.numpy as jnp

from paddle_tpu.core.dtypes import convert_dtype

__all__ = ["InputSpec", "save_inference_model", "load_inference_model"]


class InputSpec:
    def __init__(self, shape: Sequence[Any], dtype: Any = "float32", name: Optional[str] = None, stop_gradient: bool = True) -> None:
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self) -> str:
        return f"InputSpec(shape={self.shape}, dtype={jnp.dtype(self.dtype).name}, name={self.name})"


def save_inference_model(path_prefix: str, feed_vars: Any, fetch_vars: Any, executor: Any = None, **kwargs: Any) -> None:
    raise NotImplementedError(
        "static save_inference_model: use paddle_tpu.jit.save(layer, path, input_spec=...)"
    )


def load_inference_model(path_prefix: str, executor: Any = None, **kwargs: Any) -> Any:
    from paddle_tpu.jit.save_load import load

    return load(path_prefix)
