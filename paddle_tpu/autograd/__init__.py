"""``paddle_tpu.autograd`` (reference ``python/paddle/autograd``)."""

from paddle_tpu.autograd.functional import hessian, jacobian, jvp, vjp  # noqa: F401
from paddle_tpu.autograd.py_layer import PyLayer, PyLayerContext  # noqa: F401
from paddle_tpu.core.autograd import grad  # noqa: F401
from paddle_tpu.core.autograd import run_backward as _run_backward
from paddle_tpu.core.autograd import (  # noqa: F401
    enable_grad,
    is_grad_enabled,
    no_grad,
    set_grad_enabled,
)


def backward(tensors, grad_tensors=None, retain_graph=False):
    """``paddle.autograd.backward`` parity (reference ``backward_mode.py``)."""
    if grad_tensors is not None and not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    _run_backward(list(tensors), grad_tensors, retain_graph=retain_graph)


class saved_tensors_hooks:  # noqa: N801
    """Compat context; residuals are managed by XLA buffers (vjp closures), so
    pack/unpack hooks are accepted but the default implementation is identity."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None
