"""PyLayer: user-defined forward/backward (reference
``python/paddle/autograd/py_layer.py``).

The custom backward runs eagerly at backward time (it may itself dispatch ops
under no_grad), wired into the tape as a GradNode whose "vjp" calls the user's
``backward`` staticmethod — mirroring the reference's PyLayer GradNode.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax

import paddle_tpu
from paddle_tpu.core import autograd as _ag
from paddle_tpu.core.tensor import Tensor


class PyLayerContext:
    def __init__(self) -> None:
        self._saved: Tuple[Any, ...] = ()
        self.not_inplace_tensors: Tuple[Any, ...] = ()

    def save_for_backward(self, *tensors: Any) -> None:
        self._saved = tensors

    def saved_tensor(self) -> Tuple[Any, ...]:
        return self._saved

    saved_tensors = property(lambda self: self._saved)


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx: PyLayerContext, *args: Any, **kwargs: Any) -> Any:
        raise NotImplementedError

    @staticmethod
    def backward(ctx: PyLayerContext, *grads: Any) -> Any:
        raise NotImplementedError

    @classmethod
    def apply(cls, *args: Any, **kwargs: Any) -> Any:
        ctx = PyLayerContext()
        tensor_inputs: List[Tensor] = [
            a for a in list(args) + list(kwargs.values())
            if isinstance(a, Tensor) and not a.stop_gradient
        ]
        record = _ag.is_grad_enabled() and bool(tensor_inputs)

        with _ag.set_grad_enabled(False):
            outputs = cls.forward(ctx, *args, **kwargs)

        if not record:
            return outputs

        single = not isinstance(outputs, (list, tuple))
        out_list = [outputs] if single else list(outputs)
        out_tensors = [o for o in out_list if isinstance(o, Tensor)]
        out_avals = [jax.ShapeDtypeStruct(tuple(o.shape), o.dtype) for o in out_tensors]

        def vjp_fn(cots: Any) -> Tuple[Any, ...]:
            cot_list = [cots] if len(out_avals) == 1 else list(cots)
            grad_in = [Tensor(c) if c is not None else None for c in cot_list]
            with _ag.set_grad_enabled(False):
                result = cls.backward(ctx, *grad_in)
            if not isinstance(result, (list, tuple)):
                result = (result,)
            flat = []
            for r in result:
                if r is None:
                    flat.append(None)
                else:
                    flat.append(r.data if isinstance(r, Tensor) else r)
            if len(flat) != len(tensor_inputs):
                # paddle allows returning grads for all inputs incl. non-diff;
                # keep only the positions of recorded diff inputs.
                flat = flat[: len(tensor_inputs)]
            return tuple(flat)

        node = _ag.GradNode(cls.__name__, vjp_fn, tensor_inputs, out_avals)
        idx = 0
        wrapped = []
        for o in out_list:
            if isinstance(o, Tensor):
                t = Tensor(o.data, stop_gradient=False)
                t._grad_node = node
                t._grad_output_index = idx
                idx += 1
                wrapped.append(t)
            else:
                wrapped.append(o)
        return wrapped[0] if single else tuple(wrapped)
