"""Functional autograd: jacobian / hessian / jvp / vjp.

Reference: ``python/paddle/autograd/autograd.py:461`` (Jacobian/Hessian with
lazy row evaluation) and ``paddle.incubate.autograd.jvp/vjp``. TPU-native:
these map 1:1 onto jax transforms — ``jax.jacrev``/``jax.jacfwd``/``jax.jvp``/
``jax.vjp`` compose with everything else and compile into the surrounding
program, instead of a row-at-a-time double-backward loop.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ["jacobian", "hessian", "jvp", "vjp"]


def _unwrap(x: Any) -> Any:
    if isinstance(x, Tensor):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(v) for v in x)
    return jnp.asarray(x)


def _wrap(x: Any) -> Any:
    if isinstance(x, (list, tuple)):
        return type(x)(_wrap(v) for v in x)
    return Tensor(x)


def _functionalize(func: Callable) -> Callable:
    """Adapt a Tensor-in/Tensor-out callable to arrays (the jax transforms
    need pure array functions)."""

    def fn(*arrays: Any) -> Any:
        out = func(*[Tensor(a) for a in arrays])
        return _unwrap(out)

    return fn


def jacobian(
    ys: Any = None,
    xs: Any = None,
    batch_axis: Any = None,
    *,
    func: Callable = None,
    mode: str = "rev",
) -> Any:
    """Jacobian of ``func`` at ``xs`` (functional form:
    ``jacobian(func=f, xs=x)``), or of the relation ``ys = f(xs)`` expressed
    as ``jacobian(func, xs)`` positionally — the reference's class-based lazy
    Jacobian is replaced by direct jax evaluation (XLA computes all rows in
    one fused program; laziness buys nothing under a compiler)."""
    if func is None:
        if callable(ys):
            func, xs = ys, xs
        else:
            raise TypeError("jacobian needs a callable: jacobian(func, xs)")
    single = not isinstance(xs, (list, tuple))
    xs_list = [xs] if single else list(xs)
    arrays = [_unwrap(x) for x in xs_list]
    jac_t = jax.jacrev if mode == "rev" else jax.jacfwd
    out = jac_t(_functionalize(func), argnums=tuple(range(len(arrays))))(*arrays)
    out = out[0] if single and isinstance(out, tuple) and len(out) == 1 else out
    return _wrap(out)


def hessian(func: Callable, xs: Any, batch_axis: Any = None) -> Any:
    """Hessian of a scalar-output ``func`` at ``xs`` (reference
    ``autograd.hessian``): forward-over-reverse, the standard efficient
    composition."""
    single = not isinstance(xs, (list, tuple))
    xs_list = [xs] if single else list(xs)
    arrays = [_unwrap(x) for x in xs_list]
    fn = _functionalize(func)

    def scalar_fn(*a: Any) -> Any:
        out = fn(*a)
        if hasattr(out, "shape") and out.shape not in ((), (1,)):
            raise ValueError(
                f"hessian needs a scalar-output function, got shape {out.shape}"
            )
        return jnp.reshape(out, ())

    h = jax.jacfwd(jax.jacrev(scalar_fn, argnums=tuple(range(len(arrays)))),
                   argnums=tuple(range(len(arrays))))(*arrays)
    if single:
        return _wrap(h[0][0])
    return _wrap(h)


def jvp(func: Callable, xs: Any, v: Any = None) -> Tuple[Any, Any]:
    """Forward-mode Jacobian-vector product (reference
    ``incubate.autograd.jvp``). Returns ``(func(xs), J @ v)``."""
    single = not isinstance(xs, (list, tuple))
    xs_list = [xs] if single else list(xs)
    arrays = [_unwrap(x) for x in xs_list]
    if v is None:
        tangents = [jnp.ones_like(a) for a in arrays]
    else:
        v_list = [v] if single else list(v)
        tangents = [_unwrap(t) for t in v_list]
    out, tang = jax.jvp(_functionalize(func), tuple(arrays), tuple(tangents))
    return _wrap(out), _wrap(tang)


def vjp(func: Callable, xs: Any, v: Any = None) -> Tuple[Any, Any]:
    """Reverse-mode vector-Jacobian product (reference
    ``incubate.autograd.vjp``). Returns ``(func(xs), v^T @ J)``."""
    single = not isinstance(xs, (list, tuple))
    xs_list = [xs] if single else list(xs)
    arrays = [_unwrap(x) for x in xs_list]
    out, pullback = jax.vjp(_functionalize(func), *arrays)
    if v is None:
        cot = jnp.ones_like(out) if not isinstance(out, (list, tuple)) else type(out)(
            jnp.ones_like(o) for o in out
        )
    else:
        cot = _unwrap(v)
    grads = pullback(cot)
    grads = grads[0] if single and len(grads) == 1 else grads
    return _wrap(out), _wrap(grads)
