"""Functional autograd: jacobian / hessian / jvp / vjp.

Reference: ``python/paddle/autograd/autograd.py:461`` (Jacobian/Hessian with
lazy row evaluation) and ``paddle.incubate.autograd.jvp/vjp``. TPU-native:
these map 1:1 onto jax transforms — ``jax.jacrev``/``jax.jacfwd``/``jax.jvp``/
``jax.vjp`` compose with everything else and compile into the surrounding
program, instead of a row-at-a-time double-backward loop.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ["jacobian", "hessian", "jvp", "vjp"]


def _unwrap(x: Any) -> Any:
    if isinstance(x, Tensor):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(v) for v in x)
    return jnp.asarray(x)


def _wrap(x: Any) -> Any:
    if isinstance(x, (list, tuple)):
        return type(x)(_wrap(v) for v in x)
    return Tensor(x)


def _functionalize(func: Callable) -> Callable:
    """Adapt a Tensor-in/Tensor-out callable to arrays (the jax transforms
    need pure array functions)."""

    def fn(*arrays: Any) -> Any:
        out = func(*[Tensor(a) for a in arrays])
        return _unwrap(out)

    return fn


def jacobian(
    ys: Any = None,
    xs: Any = None,
    batch_axis: Any = None,
    *,
    func: Callable = None,
    mode: str = "rev",
) -> Any:
    """Jacobian of ``func`` at ``xs`` (functional form:
    ``jacobian(func=f, xs=x)``), or of the relation ``ys = f(xs)`` expressed
    as ``jacobian(func, xs)`` positionally — the reference's class-based lazy
    Jacobian is replaced by direct jax evaluation (XLA computes all rows in
    one fused program; laziness buys nothing under a compiler)."""
    if func is None:
        if callable(ys):
            func, xs = ys, xs
        else:
            raise TypeError("jacobian needs a callable: jacobian(func, xs)")
    single = not isinstance(xs, (list, tuple))
    xs_list = [xs] if single else list(xs)
    arrays = [_unwrap(x) for x in xs_list]
    jac_t = jax.jacrev if mode == "rev" else jax.jacfwd
    fn = _functionalize(func)
    jac_fn = jac_t(fn, argnums=tuple(range(len(arrays))))
    if batch_axis is not None:
        # batched Jacobian [B, out, in] (reference batch_axis=0 semantics):
        # vmap over the batch instead of materializing the O(B^2) cross-batch
        # Jacobian with its zero blocks
        if batch_axis != 0:
            raise NotImplementedError("jacobian supports batch_axis=0 or None")
        jac_fn = jax.vmap(jac_fn)
    out = jac_fn(*arrays)
    out = out[0] if single and isinstance(out, tuple) and len(out) == 1 else out
    return _wrap(out)


def hessian(func: Callable, xs: Any, batch_axis: Any = None) -> Any:
    """Hessian of a scalar-output ``func`` at ``xs`` (reference
    ``autograd.hessian``): forward-over-reverse, the standard efficient
    composition."""
    single = not isinstance(xs, (list, tuple))
    xs_list = [xs] if single else list(xs)
    arrays = [_unwrap(x) for x in xs_list]
    fn = _functionalize(func)

    def scalar_fn(*a: Any) -> Any:
        out = fn(*a)
        if hasattr(out, "shape") and out.shape not in ((), (1,)):
            raise ValueError(
                f"hessian needs a scalar-output function, got shape {out.shape}"
            )
        return jnp.reshape(out, ())

    hess_fn = jax.jacfwd(
        jax.jacrev(scalar_fn, argnums=tuple(range(len(arrays)))),
        argnums=tuple(range(len(arrays))),
    )
    if batch_axis is not None:
        if batch_axis != 0:
            raise NotImplementedError("hessian supports batch_axis=0 or None")
        hess_fn = jax.vmap(hess_fn)
    h = hess_fn(*arrays)
    if single:
        return _wrap(h[0][0])
    return _wrap(h)


def jvp(func: Callable, xs: Any, v: Any = None) -> Tuple[Any, Any]:
    """Forward-mode Jacobian-vector product (reference
    ``incubate.autograd.jvp``). Returns ``(func(xs), J @ v)``."""
    single = not isinstance(xs, (list, tuple))
    xs_list = [xs] if single else list(xs)
    arrays = [_unwrap(x) for x in xs_list]
    if v is None:
        tangents = [jnp.ones_like(a) for a in arrays]
    else:
        v_list = [v] if single else list(v)
        tangents = [_unwrap(t) for t in v_list]
    out, tang = jax.jvp(_functionalize(func), tuple(arrays), tuple(tangents))
    return _wrap(out), _wrap(tang)


def vjp(func: Callable, xs: Any, v: Any = None) -> Tuple[Any, Any]:
    """Reverse-mode vector-Jacobian product (reference
    ``incubate.autograd.vjp``). Returns ``(func(xs), v^T @ J)``."""
    single = not isinstance(xs, (list, tuple))
    xs_list = [xs] if single else list(xs)
    arrays = [_unwrap(x) for x in xs_list]
    out, pullback = jax.vjp(_functionalize(func), *arrays)
    if v is None:
        cot = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        # normalize the user cotangent onto the OUTPUT's pytree structure —
        # paddle convention passes multi-output v as a list, while the
        # function may return a tuple
        out_leaves, out_tree = jax.tree_util.tree_flatten(out)
        v_items = list(v) if isinstance(v, (list, tuple)) else [v]
        if len(v_items) != len(out_leaves):
            raise ValueError(
                f"vjp cotangent has {len(v_items)} leaves; output has {len(out_leaves)}"
            )
        cot = jax.tree_util.tree_unflatten(out_tree, [_unwrap(t) for t in v_items])
    grads = pullback(cot)
    grads = grads[0] if single and len(grads) == 1 else grads
    return _wrap(out), _wrap(grads)
