"""Deterministic, site-based fault injection.

The recovery paths this framework promises (engine step replay, torn-
checkpoint skip, collective error handling) are unreachable in a healthy CI
environment — this module makes failures reproducible on demand, the way the
reference fork's ``CommTaskManager`` tests poke its detect→dump→abort path.

Model: production code declares **fault sites** by calling
:func:`fault_point("site.name")` at the exact dispatch boundaries a real
fault would surface at (the engine's two jit call sites, every collective
entry point's instrumented wrapper, checkpoint file writes, block-pool
allocation, and the serving frontend's intake/respond seams —
``serving.intake`` fires inside ``ServingFrontend.submit`` before any
validation, ``serving.respond`` fires before each streamed HTTP chunk so
overload × fault interplay, e.g. a respond failure mid-shed-storm, is
reproducible). A :class:`FaultPlan` is a set of ``(site, call_index,
exception)`` triggers: the ``call_index``-th call of ``site`` since the plan
was installed raises ``exception`` — fully deterministic given a
deterministic workload, and :meth:`FaultPlan.sample` derives a plan from a
seed so randomized campaigns are replayable from the seed alone.

Activation is either the :func:`inject` context manager (tests/bench) or the
``FLAGS_fault_inject_plan`` flag / ``FLAGS_fault_inject_plan`` env var
(whole-process campaigns, e.g. under the launcher). With no plan installed a
fault site costs ONE cached-bool list read — the same flag-listener-cached
gate pattern as the metrics layer, so sites are safe on hot paths.

Every trigger that fires is counted in ``faults_injected_total`` (by site)
through the global metrics registry.
"""

from __future__ import annotations

import builtins
import random
import threading
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Dict, Iterable, Iterator, Optional, Sequence, Tuple, Type

from paddle_tpu.flags import GLOBAL_FLAGS
from paddle_tpu.observability import metrics as _obs

__all__ = [
    "FaultPlan",
    "FaultTrigger",
    "InjectedFault",
    "KNOWN_SITES",
    "fault_point",
    "inject",
    "install_plan",
    "site_call_count",
]

# Canonical fault-site names (the ``fault_point`` call sites across the
# package), for ``FaultPlan.sample(KNOWN_SITES, ...)`` campaigns. Collective
# sites are one per instrumented entry point (``collective.<op>``); only the
# stable, always-present ones are listed here.
KNOWN_SITES = (
    "engine.prefill",
    "engine.decode",
    "checkpoint.write",
    "block_pool.allocate",
    "serving.intake",
    "serving.respond",
    # span/flight-recorder export seam: a failing export or dump must never
    # take down the serving pump or the engine step path (the callers there
    # use the safe_* forms; campaigns prove it)
    "tracing.export",
    # prefix-cache seams (inference/prefix_cache.py): a failing chain lookup
    # must degrade to a cold cache miss (the prompt is recomputed), and a
    # failing copy-on-write fork must degrade to recompute of the partial
    # block — campaigns prove neither can fail a request. Both are pinned
    # zero-cost-when-empty like block_pool.allocate.
    "prefix_cache.match",
    "prefix_cache.cow",
    # cluster-router seams (serving/router.py): ``router.dispatch`` fires at
    # the top of every routing decision (submit and failover re-dispatch);
    # ``router.health_probe`` fires per replica probe — a probe failure must
    # degrade the replica, never kill the router; ``replica.kill`` also fires
    # per replica probe, and a trigger there flips that frontend to PERMANENT
    # failure, so CPU CI exercises death-as-routing-event (salvage,
    # re-dispatch, failover accounting) end to end. All three are pinned
    # zero-cost-when-empty like the existing sites.
    "router.dispatch",
    "router.health_probe",
    "replica.kill",
    # hierarchical-KV seams (inference/kv_tier.py + engine._prefetch_spilled):
    # ``kv_tier.spill`` fires at the top of HostKVTier.put, per evicted
    # chain block being spilled D2H — an injected failure drops the chain
    # (the pre-tier behavior, nothing half-stored); ``kv_tier.prefetch``
    # fires per admission that matched a spilled chain, before any landing
    # slot is reserved — an injected failure degrades that request to
    # recomputing its suffix (device-resident matches stay mapped). Both
    # are pinned zero-cost-when-empty by tests/test_kv_tier.py.
    "kv_tier.spill",
    "kv_tier.prefetch",
    # speculative-decoding seam (inference/engine.py::_commit_speculation):
    # fires per drafted slot per step, between the dispatch that scored the
    # draft and the host-side accept/rewind bookkeeping. A trigger degrades
    # that slot to plain decode for the step — accept nothing, keep row 0's
    # argmax (independent of the draft), rewind the drafted rows — so no
    # tokens are lost and no refcount/reservation accounting drifts; pinned
    # by tests/test_spec_decode.py and zero-cost-when-empty like the rest.
    "spec.verify",
    # quantized-KV dequant seam (incubate/.../block_attention.py): fires at
    # trace time inside each quantized Pallas-kernel dispatch, BEFORE the
    # kernel is baked into the step. A trigger is swallowed by the kernel
    # dispatch's existing except→warn_fallback arm, degrading that dispatch
    # to the XLA dequant-gather fallback — counted in
    # paddle_tpu_kernel_fallbacks_total, never a recovery trigger (the
    # engine's step never sees the exception). Pinned zero-cost-when-empty.
    "quant.dequant",
)


class InjectedFault(RuntimeError):
    """Default exception raised by a triggered fault site.

    Distinguishable in ``except`` paths: recovery machinery (e.g. the
    engine's step retry) treats an ``InjectedFault`` from a dispatch site
    exactly like the donating-backend failure it models — a dispatch whose
    buffers are gone — so the full recovery path runs on CPU CI too.
    """


@dataclass(frozen=True)
class FaultTrigger:
    """Fire ``exception`` on the ``call_index``-th call of ``site`` (0-based,
    counted from plan installation)."""

    site: str
    call_index: int
    exception: Type[BaseException] = InjectedFault

    def spec(self) -> str:
        return f"{self.site}:{self.call_index}:{self.exception.__name__}"


def _resolve_exception(name: str) -> Type[BaseException]:
    if name == "InjectedFault":
        return InjectedFault
    exc = getattr(builtins, name, None)
    if isinstance(exc, type) and issubclass(exc, BaseException):
        return exc
    raise ValueError(
        f"unknown exception type {name!r} in fault plan (builtins and "
        f"'InjectedFault' are accepted)"
    )


class FaultPlan:
    """An immutable set of :class:`FaultTrigger`\\ s."""

    def __init__(self, triggers: Iterable[FaultTrigger] = ()) -> None:
        self.triggers: Tuple[FaultTrigger, ...] = tuple(triggers)
        for t in self.triggers:
            if t.call_index < 0:
                raise ValueError(f"negative call_index in trigger {t}")

    def __bool__(self) -> bool:
        return bool(self.triggers)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, FaultPlan) and self.triggers == other.triggers

    def __repr__(self) -> str:
        return f"FaultPlan({list(self.triggers)!r})"

    @classmethod
    def single(
        cls,
        site: str,
        call_index: int,
        exception: Type[BaseException] = InjectedFault,
    ) -> "FaultPlan":
        return cls([FaultTrigger(site, call_index, exception)])

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """Parse the ``FLAGS_fault_inject_plan`` format:
        ``site:call_index:ExceptionName`` entries joined by ``;``
        (e.g. ``"engine.decode:3:InjectedFault;collective.all_reduce:0:RuntimeError"``).
        """
        triggers = []
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            parts = entry.rsplit(":", 2)
            if len(parts) != 3:
                raise ValueError(
                    f"bad fault-plan entry {entry!r} "
                    "(expected site:call_index:ExceptionName)"
                )
            site, idx, exc = parts
            triggers.append(FaultTrigger(site, int(idx), _resolve_exception(exc)))
        return cls(triggers)

    def spec(self) -> str:
        """Serialize back to the flag format (round-trips through parse)."""
        return ";".join(t.spec() for t in self.triggers)

    @classmethod
    def sample(
        cls,
        sites: Sequence[str],
        n_faults: int,
        seed: int,
        max_call_index: int = 64,
        exception: Type[BaseException] = InjectedFault,
    ) -> "FaultPlan":
        """Derive a plan from a seed: ``n_faults`` (site, call_index) picks
        drawn with a private ``random.Random(seed)`` — the same seed always
        yields the same plan, so a failing randomized campaign is replayable
        from its seed alone."""
        if not sites:
            raise ValueError("sample() needs at least one site")
        rng = random.Random(seed)
        triggers = []
        for _ in range(int(n_faults)):
            triggers.append(
                FaultTrigger(
                    rng.choice(list(sites)),
                    rng.randrange(int(max_call_index)),
                    exception,
                )
            )
        return cls(triggers)


# -- runtime state ------------------------------------------------------------

# cached "any plan installed" gate: one list read on the hot path (the same
# pattern as metrics._ENABLED); everything else lives behind the lock
_ACTIVE = [False]
_LOCK = threading.Lock()
_PLAN: Optional[FaultPlan] = None
_COUNTS: Dict[str, int] = {}
# (site, call_index) pairs already fired: each trigger fires at most once
_FIRED: set = set()

_injected_total = _obs.GLOBAL_METRICS.counter(
    "faults_injected_total",
    "Fault-plan triggers that fired, by site.",
    labelnames=("site",),
)


def install_plan(plan: Optional[FaultPlan]) -> None:
    """Install ``plan`` process-wide (None or an empty plan deactivates).
    Installation resets every site's call counter, so ``call_index`` is
    always relative to the moment the plan went live."""
    global _PLAN
    with _LOCK:
        _PLAN = plan if plan else None
        _COUNTS.clear()
        _FIRED.clear()
        _ACTIVE[0] = _PLAN is not None


@contextmanager
def inject(plan: FaultPlan) -> Iterator[FaultPlan]:
    """Scoped installation: installs ``plan``, restores the previous plan
    (usually none) on exit."""
    with _LOCK:
        prev = _PLAN
    install_plan(plan)
    try:
        yield plan
    finally:
        install_plan(prev)


def site_call_count(site: str) -> int:
    """Calls of ``site`` observed since the current plan was installed."""
    with _LOCK:
        return _COUNTS.get(site, 0)


def fault_point(site: str) -> None:
    """Declare a fault site. No plan installed: one cached-bool read."""
    if not _ACTIVE[0]:
        return
    _trip(site)


def _trip(site: str) -> None:
    with _LOCK:
        plan = _PLAN
        if plan is None:  # raced with a concurrent uninstall
            return
        idx = _COUNTS.get(site, 0)
        _COUNTS[site] = idx + 1
        exc_type = None
        for t in plan.triggers:
            if t.site == site and t.call_index == idx and (site, idx) not in _FIRED:
                _FIRED.add((site, idx))
                exc_type = t.exception
                break
    if exc_type is not None:
        _injected_total.labels(site=site).inc()
        # the black box records every fired trigger: a postmortem must be
        # able to tell an injected failure from an organic one at a glance
        # (lazy import: the observability package init would cycle here)
        from paddle_tpu.observability import flight_recorder as _flight

        _flight.record_event(
            "fault_injected", site=site, index=idx, exception=exc_type.__name__
        )
        raise exc_type(f"injected fault at site {site!r} (call #{idx})")


# -- flag wiring --------------------------------------------------------------

def _on_flag_change(value: str) -> None:
    install_plan(FaultPlan.parse(value) if value else None)


GLOBAL_FLAGS.on_change("fault_inject_plan", _on_flag_change)
# seed from the env var / a value set before this import
_on_flag_change(GLOBAL_FLAGS.get("fault_inject_plan"))
