"""Testing substrate: deterministic fault injection.

Robustness can only be tested if failures can be produced on demand —
:mod:`.faults` is the seeded, site-based injector the engine-recovery,
checkpoint and collective fault paths are pinned with.
"""

from paddle_tpu.testing.faults import (  # noqa: F401
    KNOWN_SITES,
    FaultPlan,
    FaultTrigger,
    InjectedFault,
    fault_point,
    inject,
    install_plan,
    site_call_count,
)

__all__ = [
    "KNOWN_SITES",
    "FaultPlan",
    "FaultTrigger",
    "InjectedFault",
    "fault_point",
    "inject",
    "install_plan",
    "site_call_count",
]
