"""Audio feature layers (reference ``python/paddle/audio/features/layers.py``):
Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC — thin Layers over
``signal.stft`` + host-built mel/DCT projection matrices."""

from __future__ import annotations

from typing import Any, Optional, Union

import jax.numpy as jnp

import paddle_tpu.signal as signal
from paddle_tpu.audio.functional import (
    compute_fbank_matrix,
    create_dct,
    get_window,
    power_to_db,
)
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: Union[str, tuple] = "hann",
                 power: float = 2.0, center: bool = True, pad_mode: str = "reflect",
                 dtype: str = "float32") -> None:
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.window = get_window(window, self.win_length, fftbins=True, dtype=dtype)

    def forward(self, x: Any) -> Tensor:
        spec = signal.stft(
            x, self.n_fft, self.hop_length, self.win_length, self.window,
            center=self.center, pad_mode=self.pad_mode,
        )
        mag = spec.abs() if hasattr(spec, "abs") else Tensor(jnp.abs(spec._data))
        if self.power == 1.0:
            return mag
        return Tensor(jnp.power(mag._data.astype(jnp.float32), self.power))


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 2048, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: Union[str, tuple] = "hann",
                 power: float = 2.0, center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: Union[str, float] = "slaney",
                 dtype: str = "float32") -> None:
        super().__init__()
        self.spectrogram = Spectrogram(
            n_fft, hop_length, win_length, window, power, center, pad_mode, dtype
        )
        self.fbank = compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm, dtype
        )  # [n_mels, freq]

    def forward(self, x: Any) -> Tensor:
        s = self.spectrogram(x)  # [..., freq, frames]
        return Tensor(jnp.einsum("mf,...ft->...mt", self.fbank._data, s._data))


class LogMelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 2048, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: Union[str, tuple] = "hann",
                 power: float = 2.0, center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: Union[str, float] = "slaney",
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None, dtype: str = "float32") -> None:
        super().__init__()
        self.mel = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center, pad_mode,
            n_mels, f_min, f_max, htk, norm, dtype,
        )
        self.ref_value, self.amin, self.top_db = ref_value, amin, top_db

    def forward(self, x: Any) -> Tensor:
        return power_to_db(self.mel(x), self.ref_value, self.amin, self.top_db)


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_fft: int = 2048,
                 hop_length: Optional[int] = None, win_length: Optional[int] = None,
                 window: Union[str, tuple] = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None, htk: bool = False,
                 norm: Union[str, float] = "slaney", ref_value: float = 1.0,
                 amin: float = 1e-10, top_db: Optional[float] = None,
                 dtype: str = "float32") -> None:
        super().__init__()
        self.logmel = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center, pad_mode,
            n_mels, f_min, f_max, htk, norm, ref_value, amin, top_db, dtype,
        )
        self.dct = create_dct(n_mfcc, n_mels, dtype=dtype)  # [n_mels, n_mfcc]

    def forward(self, x: Any) -> Tensor:
        lm = self.logmel(x)  # [..., n_mels, frames]
        return Tensor(jnp.einsum("mk,...mt->...kt", self.dct._data, lm._data))
