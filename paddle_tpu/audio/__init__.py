"""``paddle_tpu.audio`` — audio feature extraction.

Reference: ``python/paddle/audio/`` (``functional/window.py`` window
families, ``functional/functional.py`` mel/dct math, ``features/layers.py``
Spectrogram / MelSpectrogram / LogMelSpectrogram / MFCC layers).

TPU-native shape: every feature is a composition of the framework's
``signal.stft`` (batched matmul-friendly framing) and dense mel/DCT
projection matrices built host-side with numpy — the whole pipeline jits
into a handful of XLA ops, no librosa dependency.
"""

from paddle_tpu.audio import backends  # noqa: F401
from paddle_tpu.audio import functional  # noqa: F401
from paddle_tpu.audio.backends import info, load, save  # noqa: F401
from paddle_tpu.audio.features import (  # noqa: F401
    MFCC,
    LogMelSpectrogram,
    MelSpectrogram,
    Spectrogram,
)

from paddle_tpu.audio import datasets  # noqa: F401
from paddle_tpu.audio import features  # noqa: F401

__all__ = ["functional", "features", "backends", "datasets", "info", "load", "save",
           "Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]
