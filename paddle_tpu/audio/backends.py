"""Audio IO backend (reference ``python/paddle/audio/backends/wave_backend.py``):
WAV load/save/info over the stdlib ``wave`` module — no external codec."""

from __future__ import annotations

import wave
from typing import Any, Optional, Tuple

import numpy as np

from paddle_tpu.core.tensor import Tensor

__all__ = ["AudioInfo", "info", "load", "save", "list_available_backends",
           "get_current_backend", "set_backend"]


class AudioInfo:
    def __init__(self, sample_rate: int, num_frames: int, num_channels: int,
                 bits_per_sample: int, encoding: str) -> None:
        self.sample_rate = sample_rate
        self.num_frames = num_frames
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def info(filepath: str) -> AudioInfo:
    with wave.open(filepath, "rb") as f:
        return AudioInfo(
            f.getframerate(), f.getnframes(), f.getnchannels(),
            f.getsampwidth() * 8, f"PCM_{'S' if f.getsampwidth() > 1 else 'U'}",
        )


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True) -> Tuple[Tensor, int]:
    """Returns ``(waveform [C, T] (or [T, C]), sample_rate)`` like the
    reference; 16-bit PCM normalized to [-1, 1] when ``normalize``."""
    with wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        channels = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
    if width == 2:
        data = np.frombuffer(raw, np.int16)
    elif width == 1:
        data = np.frombuffer(raw, np.uint8).astype(np.int16) - 128
    elif width == 4:
        data = np.frombuffer(raw, np.int32)
    else:
        raise ValueError(f"unsupported sample width {width}")
    data = data.reshape(-1, channels)
    if normalize:
        data = data.astype(np.float32) / float(2 ** (8 * width - 1))
    if channels_first:
        data = data.T
    return Tensor(np.ascontiguousarray(data)), sr


def save(filepath: str, src: Any, sample_rate: int, channels_first: bool = True,
         encoding: str = "PCM_16", bits_per_sample: Optional[int] = 16) -> None:
    if encoding != "PCM_16" or bits_per_sample not in (None, 16):
        raise NotImplementedError(
            f"wave backend writes PCM_16 only; got encoding={encoding!r}, "
            f"bits_per_sample={bits_per_sample!r}"
        )
    arr = np.asarray(src.numpy() if isinstance(src, Tensor) else src)
    if channels_first:
        arr = arr.T  # -> [T, C]
    if arr.dtype.kind == "f":
        arr = np.clip(arr, -1.0, 1.0)
        arr = (arr * (2**15 - 1)).astype(np.int16)
    elif arr.dtype == np.int32:
        arr = (arr >> 16).astype(np.int16)  # rescale, don't wrap modulo 2^16
    elif arr.dtype == np.uint8:
        arr = ((arr.astype(np.int16) - 128) << 8).astype(np.int16)
    elif arr.dtype != np.int16:
        raise TypeError(f"unsupported sample dtype {arr.dtype}")
    with wave.open(filepath, "wb") as f:
        f.setnchannels(arr.shape[1] if arr.ndim == 2 else 1)
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(np.ascontiguousarray(arr).tobytes())


def list_available_backends() -> list:
    return ["wave_backend"]


def get_current_backend() -> str:
    return "wave_backend"


def set_backend(backend_name: str) -> None:
    if backend_name != "wave_backend":
        raise NotImplementedError("only the stdlib wave backend exists on this build")
