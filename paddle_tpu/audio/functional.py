"""Audio functional ops (reference ``python/paddle/audio/functional``)."""

from __future__ import annotations

from typing import Any, Optional, Union

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor

__all__ = [
    "get_window", "hz_to_mel", "mel_to_hz", "mel_frequencies",
    "fft_frequencies", "compute_fbank_matrix", "power_to_db", "create_dct",
]


def get_window(window: Union[str, tuple], win_length: int, fftbins: bool = True,
               dtype: str = "float64") -> Tensor:
    """Window families (reference ``window.py:get_window``): hamming, hann,
    blackman, bartlett, kaiser, gaussian, exponential, taylor, bohman,
    nuttall, cosine, tukey, triang, rect."""
    name, args = (window, ()) if isinstance(window, str) else (window[0], tuple(window[1:]))
    M = int(win_length)
    sym = not fftbins
    n = M if sym else M + 1  # periodic windows drop the last symmetric point
    t = np.arange(n, dtype=np.float64)

    def cosine_sum(coeffs):
        w = np.zeros(n, np.float64)
        for k, a in enumerate(coeffs):
            w += (-1) ** k * a * np.cos(2 * np.pi * k * t / max(n - 1, 1))
        return w

    if name in ("rect", "boxcar", "rectangular"):
        w = np.ones(n)
    elif name == "hamming":
        w = cosine_sum([0.54, 0.46])
    elif name in ("hann", "hanning"):
        w = cosine_sum([0.5, 0.5])
    elif name == "blackman":
        w = cosine_sum([0.42, 0.5, 0.08])
    elif name == "nuttall":
        w = cosine_sum([0.3635819, 0.4891775, 0.1365995, 0.0106411])
    elif name == "bartlett":
        w = 1.0 - np.abs(2 * t / max(n - 1, 1) - 1.0)
    elif name == "triang":
        # scipy triang differs from bartlett: nonzero endpoints
        if n % 2 == 0:
            half = np.arange(1, n // 2 + 1)
            rising = (2 * half - 1.0) / n
            w = np.concatenate([rising, rising[::-1]])
        else:
            half = np.arange(1, (n + 1) // 2 + 1)
            rising = 2 * half / (n + 1.0)
            w = np.concatenate([rising, rising[-2::-1]])
    elif name == "cosine":
        w = np.sin(np.pi / n * (t + 0.5))
    elif name == "bohman":
        x = np.abs(2 * t / max(n - 1, 1) - 1.0)
        w = (1 - x) * np.cos(np.pi * x) + np.sin(np.pi * x) / np.pi
    elif name == "kaiser":
        beta = args[0] if args else 12.0
        w = np.i0(beta * np.sqrt(1 - (2 * t / max(n - 1, 1) - 1) ** 2)) / np.i0(beta)
    elif name == "gaussian":
        std = args[0] if args else 7.0
        w = np.exp(-0.5 * ((t - (n - 1) / 2.0) / std) ** 2)
    elif name == "exponential":
        center = args[0] if len(args) > 0 and args[0] is not None else (n - 1) / 2.0
        tau = args[1] if len(args) > 1 else 1.0
        w = np.exp(-np.abs(t - center) / tau)
    elif name == "tukey":
        alpha = args[0] if args else 0.5
        w = np.ones(n)
        edge = int(np.floor(alpha * (n - 1) / 2.0))
        if edge > 0:
            ramp = 0.5 * (1 + np.cos(np.pi * (2 * t[: edge + 1] / (alpha * (n - 1)) - 1)))
            w[: edge + 1] = ramp
            w[-(edge + 1):] = ramp[::-1]
    elif name == "taylor":
        # 4-term, 30 dB sidelobe Taylor window, peak-normalized (the
        # reference's norm=True default)
        nbar, sll = (int(args[0]) if args else 4), (args[1] if len(args) > 1 else 30.0)
        B = 10 ** (sll / 20)
        A = np.arccosh(B) / np.pi
        s2 = nbar**2 / (A**2 + (nbar - 0.5) ** 2)
        ma = np.arange(1, nbar)
        Fm = np.empty(nbar - 1)
        signs = np.empty_like(ma, float)
        signs[::2] = 1
        signs[1::2] = -1
        m2 = ma**2
        for mi, _m in enumerate(ma):
            numer = signs[mi] * np.prod(1 - m2[mi] / s2 / (A**2 + (ma - 0.5) ** 2))
            denom = 2 * np.prod(1 - m2[mi] / m2[:mi]) * np.prod(1 - m2[mi] / m2[mi + 1:])
            Fm[mi] = numer / denom
        w = np.ones(n)
        pos = (t - (n - 1) / 2.0) / n
        for mi, m in enumerate(ma):
            w = w + 2 * Fm[mi] * np.cos(2 * np.pi * m * pos)
        w = w / (1.0 + 2.0 * Fm.sum())  # peak normalization (center == 1)
    else:
        raise ValueError(f"unsupported window {name!r}")
    if not sym:
        w = w[:-1]
    # jnp.asarray honors the request when x64 is enabled; under the default
    # config float64 downcasts to float32 with jax's usual truncation warning
    return Tensor(jnp.asarray(w, jnp.dtype(dtype)))


def hz_to_mel(freq: Any, htk: bool = False):
    f = np.asarray(freq, np.float64) if not isinstance(freq, Tensor) else freq.numpy()
    if htk:
        out = 2595.0 * np.log10(1.0 + f / 700.0)
    else:  # Slaney
        f_min, f_sp = 0.0, 200.0 / 3
        out = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = np.log(6.4) / 27.0
        out = np.where(f >= min_log_hz, min_log_mel + np.log(np.maximum(f, 1e-10) / min_log_hz) / logstep, out)
    return float(out) if np.ndim(out) == 0 else out


def mel_to_hz(mel: Any, htk: bool = False):
    m = np.asarray(mel, np.float64) if not isinstance(mel, Tensor) else mel.numpy()
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        out = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = np.log(6.4) / 27.0
        out = np.where(m >= min_log_mel, min_log_hz * np.exp(logstep * (m - min_log_mel)), out)
    return float(out) if np.ndim(out) == 0 else out


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0, f_max: float = 11025.0,
                    htk: bool = False):
    return mel_to_hz(np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk), n_mels), htk)


def fft_frequencies(sr: int, n_fft: int):
    return np.linspace(0, sr / 2.0, 1 + n_fft // 2)


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64, f_min: float = 0.0,
                         f_max: Optional[float] = None, htk: bool = False,
                         norm: Union[str, float] = "slaney", dtype: str = "float32") -> Tensor:
    """Mel filterbank ``[n_mels, 1 + n_fft//2]`` (reference
    ``functional.py:compute_fbank_matrix``)."""
    f_max = f_max if f_max is not None else sr / 2.0
    fftfreqs = fft_frequencies(sr, n_fft)
    melfreqs = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = np.diff(melfreqs)
    ramps = melfreqs[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (melfreqs[2 : n_mels + 2] - melfreqs[:n_mels])
        weights *= enorm[:, None]
    elif isinstance(norm, (int, float)):
        weights /= np.maximum(np.linalg.norm(weights, ord=norm, axis=-1, keepdims=True), 1e-10)
    return Tensor(jnp.asarray(weights, jnp.dtype(dtype)))


def power_to_db(spect: Any, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: Optional[float] = 80.0) -> Tensor:
    x = spect._data if isinstance(spect, Tensor) else jnp.asarray(spect)
    log_spec = 10.0 * jnp.log10(jnp.maximum(x, amin))
    log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(ref_value, amin))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
    return Tensor(log_spec)


def create_dct(n_mfcc: int, n_mels: int, norm: Optional[str] = "ortho",
               dtype: str = "float32") -> Tensor:
    """DCT-II matrix ``[n_mels, n_mfcc]`` (reference ``create_dct``)."""
    n = np.arange(n_mels, dtype=np.float64)
    k = np.arange(n_mfcc, dtype=np.float64)[None, :]
    dct = np.cos(np.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        dct[:, 0] *= np.sqrt(1.0 / n_mels)
        dct[:, 1:] *= np.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return Tensor(jnp.asarray(dct, jnp.dtype(dtype)))
