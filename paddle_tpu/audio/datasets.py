"""Audio datasets (reference ``python/paddle/audio/datasets/``: ESC50, TESS
over an ``AudioClassificationDataset`` base). Local-archive parsers only (no
downloader — zero-egress environment; point ``data_dir`` at the extracted
archive root). Feature modes mirror the reference: ``raw`` waveforms or
``mfcc``/``logmelspectrogram``/``melspectrogram``/``spectrogram`` computed
through :mod:`paddle_tpu.audio.features`.
"""

from __future__ import annotations

import csv
import os
from typing import Any, List, Optional, Tuple

import numpy as np

from paddle_tpu.audio import backends, features
from paddle_tpu.io import Dataset

__all__ = ["AudioClassificationDataset", "ESC50", "TESS"]

_FEATURES = {
    "raw": None,
    "spectrogram": features.Spectrogram,
    "melspectrogram": features.MelSpectrogram,
    "logmelspectrogram": features.LogMelSpectrogram,
    "mfcc": features.MFCC,
}


def _require_dir(data_dir: Optional[str], name: str) -> str:
    if not data_dir or not os.path.isdir(data_dir):
        raise FileNotFoundError(
            f"{name} needs a local data_dir with the extracted archive (no "
            f"downloader in this environment); got {data_dir!r}"
        )
    return data_dir


class AudioClassificationDataset(Dataset):
    """Reference ``datasets/dataset.py``: (waveform-or-feature, label) pairs
    from a file list; the feature extractor runs lazily per item."""

    def __init__(self, files: List[str], labels: List[int], feat_type: str = "raw",
                 sample_rate: Optional[int] = None, **feat_kwargs: Any) -> None:
        if feat_type not in _FEATURES:
            raise ValueError(
                f"feat_type must be one of {sorted(_FEATURES)}, got {feat_type!r}"
            )
        self.files = files
        self.labels = labels
        self.feat_type = feat_type
        self._sample_rate = sample_rate
        self._feat_kwargs = feat_kwargs
        self._extractors: dict = {}  # sr -> layer (mixed-rate dirs stay correct)

    def _feature(self, wav, sr: int):
        if self.feat_type == "raw":
            return wav
        if sr not in self._extractors:
            kwargs = dict(self._feat_kwargs)
            if self.feat_type != "spectrogram":  # Spectrogram takes no sr
                kwargs.setdefault("sr", sr)
            self._extractors[sr] = _FEATURES[self.feat_type](**kwargs)
        return self._extractors[sr](wav)

    def __len__(self) -> int:
        return len(self.files)

    def __getitem__(self, idx: int) -> Tuple[Any, int]:
        wav, sr = backends.load(self.files[idx])
        if self._sample_rate is not None and sr != self._sample_rate:
            raise ValueError(
                f"{self.files[idx]}: sample rate {sr} != expected {self._sample_rate}"
            )
        return self._feature(wav, sr), int(self.labels[idx])


class ESC50(AudioClassificationDataset):
    """Reference ``esc50.py``: 50-class environmental sounds; 5 cross-
    validation folds — ``mode='train'`` takes folds != split_fold,
    ``mode='dev'`` takes fold == split_fold."""

    def __init__(self, data_dir: Optional[str] = None, mode: str = "train",
                 split_fold: int = 1, feat_type: str = "raw", **feat_kwargs: Any) -> None:
        root = _require_dir(data_dir, "ESC50")
        meta = os.path.join(root, "meta", "esc50.csv")
        audio_dir = os.path.join(root, "audio")
        files, labels = [], []
        with open(meta) as f:
            for row in csv.DictReader(f):
                fold = int(row["fold"])
                keep = fold != split_fold if mode == "train" else fold == split_fold
                if keep:
                    files.append(os.path.join(audio_dir, row["filename"]))
                    labels.append(int(row["target"]))
        super().__init__(files, labels, feat_type, **feat_kwargs)


class TESS(AudioClassificationDataset):
    """Reference ``tess.py``: Toronto emotional speech set — 7 emotions
    parsed from filenames ``<speaker>_<word>_<emotion>.wav``; ``n_folds``-way
    modulo split over the sorted file list."""

    EMOTIONS = ["angry", "disgust", "fear", "happy", "neutral", "ps", "sad"]

    def __init__(self, data_dir: Optional[str] = None, mode: str = "train",
                 n_folds: int = 5, split_fold: int = 1, feat_type: str = "raw",
                 **feat_kwargs: Any) -> None:
        root = _require_dir(data_dir, "TESS")
        wavs: List[str] = []
        for dirpath, _dirs, names in os.walk(root):
            wavs.extend(os.path.join(dirpath, n) for n in names if n.endswith(".wav"))
        wavs.sort()
        files, labels = [], []
        for i, path in enumerate(wavs):
            fold = i % n_folds + 1
            keep = fold != split_fold if mode == "train" else fold == split_fold
            if not keep:
                continue
            emotion = os.path.splitext(os.path.basename(path))[0].split("_")[-1].lower()
            if emotion not in self.EMOTIONS:
                continue
            files.append(path)
            labels.append(self.EMOTIONS.index(emotion))
        super().__init__(files, labels, feat_type, **feat_kwargs)
