"""Distribution implementations.

Reference: ``python/paddle/distribution/{distribution,normal,uniform,
categorical,bernoulli,exponential,gamma,laplace,kl}.py``. Sampling draws keys
from the global generator (``paddle_tpu.core.rng``) so ``paddle.seed``
reproducibility matches the rest of the framework.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp

import paddle_tpu.core.rng as _rng
from paddle_tpu.core.tensor import Tensor

__all__ = [
    "Distribution",
    "Normal",
    "Uniform",
    "Categorical",
    "Bernoulli",
    "Exponential",
    "Gamma",
    "Laplace",
    "Beta",
    "Dirichlet",
    "Multinomial",
    "Gumbel",
    "LogNormal",
    "Poisson",
    "Geometric",
    "Cauchy",
    "kl_divergence",
]


def _arr(x: Any) -> jnp.ndarray:
    if isinstance(x, Tensor):
        return x._data.astype(jnp.float32)
    return jnp.asarray(x, jnp.float32)


def _shape(sample_shape: Sequence[int], batch: tuple) -> tuple:
    return tuple(sample_shape) + batch


class Distribution:
    def __init__(self, batch_shape: tuple = (), event_shape: tuple = ()) -> None:
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self) -> tuple:
        return self._batch_shape

    @property
    def event_shape(self) -> tuple:
        return self._event_shape

    @property
    def mean(self) -> Tensor:
        raise NotImplementedError

    @property
    def variance(self) -> Tensor:
        raise NotImplementedError

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        raise NotImplementedError

    def rsample(self, shape: Sequence[int] = ()) -> Tensor:
        raise NotImplementedError

    def log_prob(self, value: Any) -> Tensor:
        raise NotImplementedError

    def prob(self, value: Any) -> Tensor:
        return Tensor(jnp.exp(self.log_prob(value)._data))

    def entropy(self) -> Tensor:
        raise NotImplementedError

    def kl_divergence(self, other: "Distribution") -> Tensor:
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc: Any, scale: Any, name: Optional[str] = None) -> None:
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self) -> Tensor:
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self) -> Tensor:
        return Tensor(jnp.broadcast_to(self.scale**2, self.batch_shape))

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        eps = jax.random.normal(_rng.next_key(), _shape(shape, self.batch_shape))
        return Tensor(self.loc + self.scale * eps)

    rsample = sample

    def log_prob(self, value: Any) -> Tensor:
        v = _arr(value)
        var = self.scale**2
        return Tensor(
            -((v - self.loc) ** 2) / (2 * var) - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi)
        )

    def entropy(self) -> Tensor:
        e = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
        return Tensor(jnp.broadcast_to(e, self.batch_shape))


class Uniform(Distribution):
    def __init__(self, low: Any, high: Any, name: Optional[str] = None) -> None:
        self.low = _arr(low)
        self.high = _arr(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape, self.high.shape))

    @property
    def mean(self) -> Tensor:
        return Tensor(jnp.broadcast_to((self.low + self.high) / 2, self.batch_shape))

    @property
    def variance(self) -> Tensor:
        return Tensor(jnp.broadcast_to((self.high - self.low) ** 2 / 12, self.batch_shape))

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        u = jax.random.uniform(_rng.next_key(), _shape(shape, self.batch_shape))
        return Tensor(self.low + (self.high - self.low) * u)

    rsample = sample

    def log_prob(self, value: Any) -> Tensor:
        v = _arr(value)
        inside = (v >= self.low) & (v < self.high)
        return Tensor(jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf))

    def entropy(self) -> Tensor:
        return Tensor(jnp.broadcast_to(jnp.log(self.high - self.low), self.batch_shape))


class Categorical(Distribution):
    def __init__(self, logits: Any, name: Optional[str] = None) -> None:
        self.logits = _arr(logits)
        self._log_p = jax.nn.log_softmax(self.logits, axis=-1)
        super().__init__(self.logits.shape[:-1])

    @property
    def probs(self) -> Tensor:
        return Tensor(jnp.exp(self._log_p))

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        out = jax.random.categorical(
            _rng.next_key(), self.logits, shape=_shape(shape, self.batch_shape)
        )
        return Tensor(out)

    def log_prob(self, value: Any) -> Tensor:
        idx = jnp.asarray(
            value._data if isinstance(value, Tensor) else value, jnp.int32
        )
        return Tensor(jnp.take_along_axis(self._log_p, idx[..., None], axis=-1)[..., 0])

    def entropy(self) -> Tensor:
        p = jnp.exp(self._log_p)
        return Tensor(-(p * self._log_p).sum(-1))


class Bernoulli(Distribution):
    def __init__(self, probs: Any, name: Optional[str] = None) -> None:
        self.probs_ = jnp.clip(_arr(probs), 1e-7, 1 - 1e-7)
        super().__init__(self.probs_.shape)

    @property
    def mean(self) -> Tensor:
        return Tensor(self.probs_)

    @property
    def variance(self) -> Tensor:
        return Tensor(self.probs_ * (1 - self.probs_))

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        u = jax.random.bernoulli(
            _rng.next_key(), self.probs_, _shape(shape, self.batch_shape)
        )
        return Tensor(u.astype(jnp.float32))

    def log_prob(self, value: Any) -> Tensor:
        import jax.scipy.special as jss

        v = _arr(value)
        # xlogy: deterministic outcomes (p in {0,1}) stay finite
        return Tensor(jss.xlogy(v, self.probs_) + jss.xlog1py(1 - v, -self.probs_))

    def entropy(self) -> Tensor:
        p = self.probs_
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log(1 - p)))


class Exponential(Distribution):
    def __init__(self, rate: Any, name: Optional[str] = None) -> None:
        self.rate = _arr(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self) -> Tensor:
        return Tensor(1.0 / self.rate)

    @property
    def variance(self) -> Tensor:
        return Tensor(1.0 / self.rate**2)

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        e = jax.random.exponential(_rng.next_key(), _shape(shape, self.batch_shape))
        return Tensor(e / self.rate)

    rsample = sample

    def log_prob(self, value: Any) -> Tensor:
        v = _arr(value)
        return Tensor(jnp.where(v >= 0, jnp.log(self.rate) - self.rate * v, -jnp.inf))

    def entropy(self) -> Tensor:
        return Tensor(1.0 - jnp.log(self.rate))


class Gamma(Distribution):
    def __init__(self, concentration: Any, rate: Any, name: Optional[str] = None) -> None:
        self.concentration = _arr(concentration)
        self.rate = _arr(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape, self.rate.shape))

    @property
    def mean(self) -> Tensor:
        return Tensor(self.concentration / self.rate)

    @property
    def variance(self) -> Tensor:
        return Tensor(self.concentration / self.rate**2)

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        g = jax.random.gamma(
            _rng.next_key(), self.concentration, _shape(shape, self.batch_shape)
        )
        return Tensor(g / self.rate)

    def log_prob(self, value: Any) -> Tensor:
        v = _arr(value)
        a, b = self.concentration, self.rate
        return Tensor(
            a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v - jax.lax.lgamma(a)
        )


class Laplace(Distribution):
    def __init__(self, loc: Any, scale: Any, name: Optional[str] = None) -> None:
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self) -> Tensor:
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self) -> Tensor:
        return Tensor(jnp.broadcast_to(2 * self.scale**2, self.batch_shape))

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        s = jax.random.laplace(_rng.next_key(), _shape(shape, self.batch_shape))
        return Tensor(self.loc + self.scale * s)

    rsample = sample

    def log_prob(self, value: Any) -> Tensor:
        v = _arr(value)
        return Tensor(-jnp.abs(v - self.loc) / self.scale - jnp.log(2 * self.scale))

    def entropy(self) -> Tensor:
        return Tensor(jnp.broadcast_to(1 + jnp.log(2 * self.scale), self.batch_shape))


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    """Pairwise KL (reference ``distribution/kl.py`` register_kl)."""
    if isinstance(p, Normal) and isinstance(q, Normal):
        var_ratio = (p.scale / q.scale) ** 2
        t1 = ((p.loc - q.loc) / q.scale) ** 2
        return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        pp = jnp.exp(p._log_p)
        return Tensor((pp * (p._log_p - q._log_p)).sum(-1))
    if isinstance(p, Bernoulli) and isinstance(q, Bernoulli):
        a, b = p.probs_, q.probs_
        return Tensor(
            a * (jnp.log(a) - jnp.log(b)) + (1 - a) * (jnp.log(1 - a) - jnp.log(1 - b))
        )
    if isinstance(p, Uniform) and isinstance(q, Uniform):
        return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))
    if isinstance(p, Exponential) and isinstance(q, Exponential):
        r = p.rate / q.rate
        return Tensor(jnp.log(r) + 1.0 / r - 1.0)
    if isinstance(p, Gamma) and isinstance(q, Gamma):
        import jax.scipy.special as jss

        a1, b1, a2, b2 = p.concentration, p.rate, q.concentration, q.rate
        return Tensor(
            (a1 - a2) * jss.digamma(a1)
            - jax.lax.lgamma(a1)
            + jax.lax.lgamma(a2)
            + a2 * (jnp.log(b1) - jnp.log(b2))
            + a1 * (b2 - b1) / b1
        )
    if isinstance(p, Beta) and isinstance(q, Beta):
        import jax.scipy.special as jss

        a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta

        def lbeta(a, b):
            return jax.lax.lgamma(a) + jax.lax.lgamma(b) - jax.lax.lgamma(a + b)

        return Tensor(
            lbeta(a2, b2)
            - lbeta(a1, b1)
            + (a1 - a2) * jss.digamma(a1)
            + (b1 - b2) * jss.digamma(b1)
            + (a2 - a1 + b2 - b1) * jss.digamma(a1 + b1)
        )
    raise NotImplementedError(
        f"kl_divergence not registered for ({type(p).__name__}, {type(q).__name__})"
    )


class Beta(Distribution):
    """Reference ``distribution/beta.py``."""

    def __init__(self, alpha: Any, beta: Any, name: Optional[str] = None) -> None:
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape, self.beta.shape))

    @property
    def mean(self) -> Tensor:
        return Tensor(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self) -> Tensor:
        s = self.alpha + self.beta
        return Tensor(self.alpha * self.beta / (s * s * (s + 1)))

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        out = jax.random.beta(
            _rng.next_key(), self.alpha, self.beta, _shape(shape, self.batch_shape)
        )
        return Tensor(out)

    def log_prob(self, value: Any) -> Tensor:
        v = _arr(value)
        a, b = self.alpha, self.beta
        lbeta = jax.lax.lgamma(a) + jax.lax.lgamma(b) - jax.lax.lgamma(a + b)
        return Tensor((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta)

    def entropy(self) -> Tensor:
        import jax.scipy.special as jss

        a, b = self.alpha, self.beta
        lbeta = jax.lax.lgamma(a) + jax.lax.lgamma(b) - jax.lax.lgamma(a + b)
        return Tensor(
            lbeta
            - (a - 1) * jss.digamma(a)
            - (b - 1) * jss.digamma(b)
            + (a + b - 2) * jss.digamma(a + b)
        )


class Dirichlet(Distribution):
    """Reference ``distribution/dirichlet.py``."""

    def __init__(self, concentration: Any, name: Optional[str] = None) -> None:
        self.concentration = _arr(concentration)
        super().__init__(self.concentration.shape[:-1], self.concentration.shape[-1:])

    @property
    def mean(self) -> Tensor:
        return Tensor(self.concentration / self.concentration.sum(-1, keepdims=True))

    @property
    def variance(self) -> Tensor:
        a = self.concentration
        a0 = a.sum(-1, keepdims=True)
        return Tensor(a * (a0 - a) / (a0 * a0 * (a0 + 1)))

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        out = jax.random.dirichlet(
            _rng.next_key(), self.concentration, tuple(shape) + self.batch_shape
        )
        return Tensor(out)

    def log_prob(self, value: Any) -> Tensor:
        v = _arr(value)
        a = self.concentration
        lnorm = jax.lax.lgamma(a).sum(-1) - jax.lax.lgamma(a.sum(-1))
        return Tensor(((a - 1) * jnp.log(v)).sum(-1) - lnorm)

    def entropy(self) -> Tensor:
        import jax.scipy.special as jss

        a = self.concentration
        a0 = a.sum(-1)
        k = a.shape[-1]
        lnorm = jax.lax.lgamma(a).sum(-1) - jax.lax.lgamma(a0)
        return Tensor(
            lnorm
            + (a0 - k) * jss.digamma(a0)
            - ((a - 1) * jss.digamma(a)).sum(-1)
        )


class Multinomial(Distribution):
    """Reference ``distribution/multinomial.py``: n trials over K categories."""

    def __init__(self, total_count: int, probs: Any, name: Optional[str] = None) -> None:
        self.total_count = int(total_count)
        self.probs_ = _arr(probs)
        self.probs_ = self.probs_ / self.probs_.sum(-1, keepdims=True)
        super().__init__(self.probs_.shape[:-1], self.probs_.shape[-1:])

    @property
    def mean(self) -> Tensor:
        return Tensor(self.total_count * self.probs_)

    @property
    def variance(self) -> Tensor:
        return Tensor(self.total_count * self.probs_ * (1 - self.probs_))

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        logits = jnp.log(self.probs_)
        draws = jax.random.categorical(
            _rng.next_key(),
            logits,
            shape=tuple(shape) + (self.total_count,) + self.batch_shape,
            axis=-1,
        )
        k = self.probs_.shape[-1]
        counts = jax.nn.one_hot(draws, k).sum(axis=len(tuple(shape)))
        return Tensor(counts)

    def log_prob(self, value: Any) -> Tensor:
        v = _arr(value)
        import jax.scipy.special as jss

        logf = (
            jax.lax.lgamma(jnp.asarray(self.total_count + 1.0))
            - jax.lax.lgamma(v + 1.0).sum(-1)
        )
        # xlogy: a zero count against a zero probability contributes 0, not NaN
        return Tensor(logf + jss.xlogy(v, self.probs_).sum(-1))


class Gumbel(Distribution):
    """Reference ``distribution/gumbel.py``."""

    _EULER = 0.5772156649015329

    def __init__(self, loc: Any, scale: Any, name: Optional[str] = None) -> None:
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self) -> Tensor:
        return Tensor(jnp.broadcast_to(self.loc + self._EULER * self.scale, self.batch_shape))

    @property
    def variance(self) -> Tensor:
        return Tensor(
            jnp.broadcast_to((jnp.pi**2 / 6) * self.scale**2, self.batch_shape)
        )

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        g = jax.random.gumbel(_rng.next_key(), _shape(shape, self.batch_shape))
        return Tensor(self.loc + self.scale * g)

    rsample = sample

    def log_prob(self, value: Any) -> Tensor:
        z = (_arr(value) - self.loc) / self.scale
        return Tensor(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    def entropy(self) -> Tensor:
        return Tensor(
            jnp.broadcast_to(jnp.log(self.scale) + 1 + self._EULER, self.batch_shape)
        )


class LogNormal(Distribution):
    """Reference ``distribution/lognormal.py``."""

    def __init__(self, loc: Any, scale: Any, name: Optional[str] = None) -> None:
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self) -> Tensor:
        return Tensor(jnp.exp(self.loc + self.scale**2 / 2))

    @property
    def variance(self) -> Tensor:
        s2 = self.scale**2
        return Tensor((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2))

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        n = jax.random.normal(_rng.next_key(), _shape(shape, self.batch_shape))
        return Tensor(jnp.exp(self.loc + self.scale * n))

    rsample = sample

    def log_prob(self, value: Any) -> Tensor:
        v = _arr(value)
        z = (jnp.log(v) - self.loc) / self.scale
        return Tensor(
            -0.5 * z**2 - jnp.log(self.scale) - jnp.log(v) - 0.5 * jnp.log(2 * jnp.pi)
        )

    def entropy(self) -> Tensor:
        return Tensor(
            jnp.broadcast_to(
                self.loc + 0.5 + jnp.log(self.scale) + 0.5 * jnp.log(2 * jnp.pi),
                self.batch_shape,
            )
        )


class Poisson(Distribution):
    """Reference ``distribution/poisson.py``."""

    def __init__(self, rate: Any, name: Optional[str] = None) -> None:
        self.rate = _arr(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self) -> Tensor:
        return Tensor(self.rate)

    @property
    def variance(self) -> Tensor:
        return Tensor(self.rate)

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        out = jax.random.poisson(
            _rng.next_key(), self.rate, _shape(shape, self.batch_shape)
        )
        return Tensor(out.astype(jnp.float32))

    def log_prob(self, value: Any) -> Tensor:
        v = _arr(value)
        return Tensor(v * jnp.log(self.rate) - self.rate - jax.lax.lgamma(v + 1.0))


class Geometric(Distribution):
    """Reference ``distribution/geometric.py``: failures before first success."""

    def __init__(self, probs: Any, name: Optional[str] = None) -> None:
        self.probs_ = _arr(probs)
        super().__init__(self.probs_.shape)

    @property
    def mean(self) -> Tensor:
        return Tensor((1 - self.probs_) / self.probs_)

    @property
    def variance(self) -> Tensor:
        return Tensor((1 - self.probs_) / self.probs_**2)

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        u = jax.random.uniform(
            _rng.next_key(), _shape(shape, self.batch_shape), minval=1e-7, maxval=1.0
        )
        return Tensor(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs_)))

    def log_prob(self, value: Any) -> Tensor:
        import jax.scipy.special as jss

        v = _arr(value)
        # xlog1py: v=0 at probs=1 contributes 0, not NaN
        return Tensor(jss.xlog1py(v, -self.probs_) + jnp.log(self.probs_))

    def entropy(self) -> Tensor:
        import jax.scipy.special as jss

        p = self.probs_
        return Tensor(-(jss.xlog1py(1 - p, -p) + jss.xlogy(p, p)) / p)


class Cauchy(Distribution):
    """Reference ``distribution/cauchy.py`` (mean/variance undefined)."""

    def __init__(self, loc: Any, scale: Any, name: Optional[str] = None) -> None:
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        c = jax.random.cauchy(_rng.next_key(), _shape(shape, self.batch_shape))
        return Tensor(self.loc + self.scale * c)

    rsample = sample

    def log_prob(self, value: Any) -> Tensor:
        z = (_arr(value) - self.loc) / self.scale
        return Tensor(-jnp.log(jnp.pi * self.scale * (1 + z**2)))

    def entropy(self) -> Tensor:
        return Tensor(
            jnp.broadcast_to(jnp.log(4 * jnp.pi * self.scale), self.batch_shape)
        )
