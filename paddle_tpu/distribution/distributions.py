"""Distribution implementations.

Reference: ``python/paddle/distribution/{distribution,normal,uniform,
categorical,bernoulli,exponential,gamma,laplace,kl}.py``. Sampling draws keys
from the global generator (``paddle_tpu.core.rng``) so ``paddle.seed``
reproducibility matches the rest of the framework.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp

import paddle_tpu.core.rng as _rng
from paddle_tpu.core.tensor import Tensor

__all__ = [
    "Distribution",
    "Normal",
    "Uniform",
    "Categorical",
    "Bernoulli",
    "Exponential",
    "Gamma",
    "Laplace",
    "kl_divergence",
]


def _arr(x: Any) -> jnp.ndarray:
    if isinstance(x, Tensor):
        return x._data.astype(jnp.float32)
    return jnp.asarray(x, jnp.float32)


def _shape(sample_shape: Sequence[int], batch: tuple) -> tuple:
    return tuple(sample_shape) + batch


class Distribution:
    def __init__(self, batch_shape: tuple = (), event_shape: tuple = ()) -> None:
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self) -> tuple:
        return self._batch_shape

    @property
    def event_shape(self) -> tuple:
        return self._event_shape

    @property
    def mean(self) -> Tensor:
        raise NotImplementedError

    @property
    def variance(self) -> Tensor:
        raise NotImplementedError

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        raise NotImplementedError

    def rsample(self, shape: Sequence[int] = ()) -> Tensor:
        raise NotImplementedError

    def log_prob(self, value: Any) -> Tensor:
        raise NotImplementedError

    def prob(self, value: Any) -> Tensor:
        return Tensor(jnp.exp(self.log_prob(value)._data))

    def entropy(self) -> Tensor:
        raise NotImplementedError

    def kl_divergence(self, other: "Distribution") -> Tensor:
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc: Any, scale: Any, name: Optional[str] = None) -> None:
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self) -> Tensor:
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self) -> Tensor:
        return Tensor(jnp.broadcast_to(self.scale**2, self.batch_shape))

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        eps = jax.random.normal(_rng.next_key(), _shape(shape, self.batch_shape))
        return Tensor(self.loc + self.scale * eps)

    rsample = sample

    def log_prob(self, value: Any) -> Tensor:
        v = _arr(value)
        var = self.scale**2
        return Tensor(
            -((v - self.loc) ** 2) / (2 * var) - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi)
        )

    def entropy(self) -> Tensor:
        e = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
        return Tensor(jnp.broadcast_to(e, self.batch_shape))


class Uniform(Distribution):
    def __init__(self, low: Any, high: Any, name: Optional[str] = None) -> None:
        self.low = _arr(low)
        self.high = _arr(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape, self.high.shape))

    @property
    def mean(self) -> Tensor:
        return Tensor(jnp.broadcast_to((self.low + self.high) / 2, self.batch_shape))

    @property
    def variance(self) -> Tensor:
        return Tensor(jnp.broadcast_to((self.high - self.low) ** 2 / 12, self.batch_shape))

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        u = jax.random.uniform(_rng.next_key(), _shape(shape, self.batch_shape))
        return Tensor(self.low + (self.high - self.low) * u)

    rsample = sample

    def log_prob(self, value: Any) -> Tensor:
        v = _arr(value)
        inside = (v >= self.low) & (v < self.high)
        return Tensor(jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf))

    def entropy(self) -> Tensor:
        return Tensor(jnp.broadcast_to(jnp.log(self.high - self.low), self.batch_shape))


class Categorical(Distribution):
    def __init__(self, logits: Any, name: Optional[str] = None) -> None:
        self.logits = _arr(logits)
        self._log_p = jax.nn.log_softmax(self.logits, axis=-1)
        super().__init__(self.logits.shape[:-1])

    @property
    def probs(self) -> Tensor:
        return Tensor(jnp.exp(self._log_p))

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        out = jax.random.categorical(
            _rng.next_key(), self.logits, shape=_shape(shape, self.batch_shape)
        )
        return Tensor(out)

    def log_prob(self, value: Any) -> Tensor:
        idx = jnp.asarray(
            value._data if isinstance(value, Tensor) else value, jnp.int32
        )
        return Tensor(jnp.take_along_axis(self._log_p, idx[..., None], axis=-1)[..., 0])

    def entropy(self) -> Tensor:
        p = jnp.exp(self._log_p)
        return Tensor(-(p * self._log_p).sum(-1))


class Bernoulli(Distribution):
    def __init__(self, probs: Any, name: Optional[str] = None) -> None:
        self.probs_ = jnp.clip(_arr(probs), 1e-7, 1 - 1e-7)
        super().__init__(self.probs_.shape)

    @property
    def mean(self) -> Tensor:
        return Tensor(self.probs_)

    @property
    def variance(self) -> Tensor:
        return Tensor(self.probs_ * (1 - self.probs_))

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        u = jax.random.bernoulli(
            _rng.next_key(), self.probs_, _shape(shape, self.batch_shape)
        )
        return Tensor(u.astype(jnp.float32))

    def log_prob(self, value: Any) -> Tensor:
        v = _arr(value)
        return Tensor(v * jnp.log(self.probs_) + (1 - v) * jnp.log(1 - self.probs_))

    def entropy(self) -> Tensor:
        p = self.probs_
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log(1 - p)))


class Exponential(Distribution):
    def __init__(self, rate: Any, name: Optional[str] = None) -> None:
        self.rate = _arr(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self) -> Tensor:
        return Tensor(1.0 / self.rate)

    @property
    def variance(self) -> Tensor:
        return Tensor(1.0 / self.rate**2)

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        e = jax.random.exponential(_rng.next_key(), _shape(shape, self.batch_shape))
        return Tensor(e / self.rate)

    rsample = sample

    def log_prob(self, value: Any) -> Tensor:
        v = _arr(value)
        return Tensor(jnp.where(v >= 0, jnp.log(self.rate) - self.rate * v, -jnp.inf))

    def entropy(self) -> Tensor:
        return Tensor(1.0 - jnp.log(self.rate))


class Gamma(Distribution):
    def __init__(self, concentration: Any, rate: Any, name: Optional[str] = None) -> None:
        self.concentration = _arr(concentration)
        self.rate = _arr(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape, self.rate.shape))

    @property
    def mean(self) -> Tensor:
        return Tensor(self.concentration / self.rate)

    @property
    def variance(self) -> Tensor:
        return Tensor(self.concentration / self.rate**2)

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        g = jax.random.gamma(
            _rng.next_key(), self.concentration, _shape(shape, self.batch_shape)
        )
        return Tensor(g / self.rate)

    def log_prob(self, value: Any) -> Tensor:
        v = _arr(value)
        a, b = self.concentration, self.rate
        return Tensor(
            a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v - jax.lax.lgamma(a)
        )


class Laplace(Distribution):
    def __init__(self, loc: Any, scale: Any, name: Optional[str] = None) -> None:
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self) -> Tensor:
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self) -> Tensor:
        return Tensor(jnp.broadcast_to(2 * self.scale**2, self.batch_shape))

    def sample(self, shape: Sequence[int] = ()) -> Tensor:
        s = jax.random.laplace(_rng.next_key(), _shape(shape, self.batch_shape))
        return Tensor(self.loc + self.scale * s)

    rsample = sample

    def log_prob(self, value: Any) -> Tensor:
        v = _arr(value)
        return Tensor(-jnp.abs(v - self.loc) / self.scale - jnp.log(2 * self.scale))

    def entropy(self) -> Tensor:
        return Tensor(jnp.broadcast_to(1 + jnp.log(2 * self.scale), self.batch_shape))


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    """Pairwise KL (reference ``distribution/kl.py`` register_kl)."""
    if isinstance(p, Normal) and isinstance(q, Normal):
        var_ratio = (p.scale / q.scale) ** 2
        t1 = ((p.loc - q.loc) / q.scale) ** 2
        return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        pp = jnp.exp(p._log_p)
        return Tensor((pp * (p._log_p - q._log_p)).sum(-1))
    if isinstance(p, Bernoulli) and isinstance(q, Bernoulli):
        a, b = p.probs_, q.probs_
        return Tensor(
            a * (jnp.log(a) - jnp.log(b)) + (1 - a) * (jnp.log(1 - a) - jnp.log(1 - b))
        )
    if isinstance(p, Uniform) and isinstance(q, Uniform):
        return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))
    if isinstance(p, Exponential) and isinstance(q, Exponential):
        r = p.rate / q.rate
        return Tensor(jnp.log(r) + 1.0 / r - 1.0)
    raise NotImplementedError(
        f"kl_divergence not registered for ({type(p).__name__}, {type(q).__name__})"
    )
