"""Probability distributions (reference ``python/paddle/distribution`` — 30+
distributions over a Distribution base with sample/log_prob/entropy/kl).

Core families implemented natively over jax.random; ``kl_divergence``
dispatches on the pair of types (the reference's registered-kl pattern).
"""

from paddle_tpu.distribution.distributions import (  # noqa: F401
    Bernoulli,
    Beta,
    Categorical,
    Cauchy,
    Dirichlet,
    Distribution,
    Exponential,
    Gamma,
    Geometric,
    Gumbel,
    Laplace,
    LogNormal,
    Multinomial,
    Normal,
    Poisson,
    Uniform,
    kl_divergence,
)
