"""``paddle_tpu.jit`` (reference ``python/paddle/jit``)."""

from paddle_tpu.jit.api import StaticFunction, ignore_module, not_to_static, to_static  # noqa: F401
from paddle_tpu.jit.save_load import load, save  # noqa: F401
