"""jit.save / jit.load: serialized inference programs.

Reference: ``paddle.jit.save``/``load`` (``python/paddle/jit/api.py``,
``translated_layer.py``) export a Program + params. TPU-native equivalent:
export the StableHLO text of the traced function + a params archive; load
reconstitutes a callable that executes the compiled program.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor


def save(layer: Any, path: str, input_spec: Optional[Sequence[Any]] = None, **config: Any) -> None:
    """Serialize a Layer (or traced function) for inference.

    Writes ``<path>.pdiparams`` (pickled numpy state dict) and
    ``<path>.pdmodel`` (StableHLO text of the jitted forward, when input_spec
    with concrete shapes is given).
    """
    from paddle_tpu.nn.layer.layers import Layer

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if isinstance(layer, Layer):
        state = {k: np.asarray(v.numpy()) for k, v in layer.state_dict().items()}
        with open(path + ".pdiparams", "wb") as f:
            pickle.dump(state, f, protocol=4)
        if input_spec:
            params = {k: v._data for k, v in layer.state_dict().items()}

            def pure_forward(params_, *xs):
                saved = [(t, t._data) for t in layer.state_dict().values()]
                try:
                    for k, t in layer.state_dict().items():
                        t._data = params_[k]
                    out = layer(*[Tensor(x) for x in xs])
                    return jax.tree_util.tree_map(
                        lambda o: o._data if isinstance(o, Tensor) else o,
                        out,
                        is_leaf=lambda o: isinstance(o, Tensor),
                    )
                finally:
                    for t, d in saved:
                        t._data = d

            specs = [
                jax.ShapeDtypeStruct(tuple(s.shape), jnp.dtype(getattr(s, "dtype", "float32")))
                for s in input_spec
            ]
            lowered = jax.jit(pure_forward).lower(params, *specs)
            with open(path + ".pdmodel", "w") as f:
                f.write(lowered.as_text())
    else:
        raise TypeError("jit.save expects a Layer")


class TranslatedLayer:
    """Loaded inference bundle (reference ``translated_layer.py`` parity)."""

    def __init__(self, state: dict, model_text: Optional[str]) -> None:
        self._state = {k: Tensor(v) for k, v in state.items()}
        self._model_text = model_text

    def state_dict(self) -> dict:
        return self._state

    @property
    def program_text(self) -> Optional[str]:
        return self._model_text


def load(path: str, **config: Any) -> TranslatedLayer:
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    model_text = None
    if os.path.exists(path + ".pdmodel"):
        with open(path + ".pdmodel") as f:
            model_text = f.read()
    return TranslatedLayer(state, model_text)
