"""jit.save / jit.load: serialized inference programs.

Reference: ``paddle.jit.save``/``load`` (``python/paddle/jit/api.py``,
``translated_layer.py``) export a Program + params; the deployment side loads
them through the inference AnalysisPredictor
(``paddle/fluid/inference/api/analysis_predictor.h:105``). TPU-native
equivalent: serialize the traced function with ``jax.export`` (a portable
StableHLO artifact with calling convention + vjp-free forward) plus a params
archive; load reconstitutes an executable ``TranslatedLayer``. The
``paddle_tpu.inference`` package builds the Predictor API on top of this.
"""

from __future__ import annotations

import json
import os
import pickle
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.export  # noqa: F401  (jax 0.4.x: not re-exported by `import jax`)
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor

_MAGIC = b"PDTPU\x01"  # binary serialized jax.export artifact marker


def _pure_forward(layer: Any) -> Callable:
    """Functionalize a Layer: (params_dict, *input_arrays) -> output arrays."""

    def pure_forward(params_, *xs):
        saved = [(t, t._data) for t in layer.state_dict().values()]
        try:
            for k, t in layer.state_dict().items():
                t._data = params_[k]
            out = layer(*[Tensor(x) for x in xs])
            return jax.tree_util.tree_map(
                lambda o: o._data if isinstance(o, Tensor) else o,
                out,
                is_leaf=lambda o: isinstance(o, Tensor),
            )
        finally:
            for t, d in saved:
                t._data = d

    return pure_forward


def decommit_from_mesh(tree: Any) -> Any:
    """Round-trip multi-device-sharded arrays through host so they become
    uncommitted single-device arrays (mesh-agnostic). Single-device arrays
    pass through untouched — no gratuitous D2H copy."""

    def fix(a: Any) -> Any:
        sharding = getattr(a, "sharding", None)
        if sharding is not None and len(getattr(sharding, "device_set", ())) > 1:
            return jnp.asarray(np.asarray(a))
        return a

    return jax.tree_util.tree_map(fix, tree)


def specs_from_input_spec(
    input_spec: Sequence[Any], float_dtype: Any = None
) -> List[jax.ShapeDtypeStruct]:
    """Shared InputSpec→ShapeDtypeStruct conversion (save/serve use the same
    rules). ``float_dtype`` overrides the dtype of floating specs (mixed-
    precision serving)."""
    specs = []
    for s in input_spec:
        dt = jnp.dtype(getattr(s, "dtype", None) or "float32")
        if float_dtype is not None and jnp.issubdtype(dt, jnp.floating):
            dt = jnp.dtype(float_dtype)
        specs.append(jax.ShapeDtypeStruct(tuple(s.shape), dt))
    return specs


def _export_layer(layer: Any, input_spec: Sequence[Any], params: dict) -> "jax.export.Exported":
    """Export the layer's forward as a portable artifact.

    Tries a multi-platform (cpu+tpu) export first so a bundle saved on the dev
    box runs on the serving chip and vice versa; falls back to the current
    platform when an op lacks multi-platform lowering.
    """
    pure = _pure_forward(layer)
    specs = specs_from_input_spec(input_spec)
    # training may have left params sharded over a device mesh; exporting
    # mesh-placed weights records an N-device calling convention that a
    # single-device serving context cannot satisfy. Decommit to keep the
    # bundle mesh-agnostic.
    params = decommit_from_mesh(params)
    return export_fn(pure, params, specs)


def export_fn(fn: Any, params: Any, specs: Sequence[Any]) -> "jax.export.Exported":
    """Export ``fn(params, *specs)`` portably: cpu+tpu platforms first, with a
    diagnosed single-platform fallback. Grad recording is disabled for the
    trace — export must produce a vjp-free forward."""
    import sys

    from paddle_tpu.core import autograd as _ag

    with _ag.set_grad_enabled(False):
        try:
            return jax.export.export(jax.jit(fn), platforms=("cpu", "tpu"))(params, *specs)
        except Exception as exc:  # noqa: BLE001 - per-platform fallback
            print(
                f"jit.save: multi-platform export failed ({exc!r}); "
                "falling back to the current platform only"[:500],
                file=sys.stderr,
            )
            return jax.export.export(jax.jit(fn))(params, *specs)


def write_bundle(
    path: str,
    exported: "jax.export.Exported",
    state: dict,
    input_spec: Sequence[Any],
    specs: Optional[Sequence[Any]] = None,
    extra_spec: Optional[dict] = None,
) -> None:
    """Write the three bundle files (the ONE place that knows the on-disk
    format): ``.pdiparams`` pickled numpy state, ``.pdmodel`` serialized
    program, ``.pdspec`` feed/fetch signature. ``specs`` (when given) carry
    the traced input dtypes; ``input_spec`` carries the user-facing names."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump({k: np.asarray(v) for k, v in state.items()}, f, protocol=4)
    with open(path + ".pdmodel", "wb") as f:
        f.write(_MAGIC + exported.serialize())
    traced = specs if specs is not None else input_spec
    spec = {
        "inputs": [
            {
                "name": getattr(orig, "name", None) or f"x{i}",
                "shape": list(s.shape),
                "dtype": str(jnp.dtype(getattr(s, "dtype", "float32"))),
            }
            for i, (orig, s) in enumerate(zip(input_spec, traced))
        ],
        "outputs": [
            {"name": f"fetch{i}", "shape": list(a.shape), "dtype": str(a.dtype)}
            for i, a in enumerate(exported.out_avals)
        ],
        "platforms": list(exported.platforms),
    }
    spec.update(extra_spec or {})
    with open(path + ".pdspec", "w") as f:
        json.dump(spec, f, indent=1)


def save(layer: Any, path: str, input_spec: Optional[Sequence[Any]] = None, **config: Any) -> None:
    """Serialize a Layer for inference.

    Writes:
      - ``<path>.pdiparams`` — pickled numpy state dict
      - ``<path>.pdmodel``   — serialized ``jax.export`` artifact (binary;
        StableHLO + calling convention), when ``input_spec`` is given
      - ``<path>.pdspec``    — JSON feed/fetch signature for the Predictor
    """
    from paddle_tpu.nn.layer.layers import Layer

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if not isinstance(layer, Layer):
        raise TypeError("jit.save expects a Layer")
    state = {k: np.asarray(v.numpy()) for k, v in layer.state_dict().items()}
    if not input_spec:
        with open(path + ".pdiparams", "wb") as f:
            pickle.dump(state, f, protocol=4)
        return
    params = {k: v._data for k, v in layer.state_dict().items()}
    exported = _export_layer(layer, input_spec, params)
    write_bundle(path, exported, state, input_spec)


class TranslatedLayer:
    """Loaded inference bundle (reference ``translated_layer.py`` parity).

    When the bundle carries a serialized program, the instance is callable:
    ``layer(x, ...)`` executes the compiled forward with the loaded params.
    """

    def __init__(
        self,
        state: dict,
        exported: Optional["jax.export.Exported"] = None,
        spec: Optional[dict] = None,
        model_text: Optional[str] = None,
    ) -> None:
        self._state = {k: Tensor(v) for k, v in state.items()}
        self._exported = exported
        self._spec = spec or {}
        self._model_text = model_text
        self._compiled: Optional[Callable] = None

    def state_dict(self) -> dict:
        return self._state

    @property
    def program_text(self) -> Optional[str]:
        if self._model_text is not None:
            return self._model_text
        if self._exported is not None:
            return str(self._exported.mlir_module())
        return None

    @property
    def input_spec(self) -> List[dict]:
        return list(self._spec.get("inputs", []))

    @property
    def output_spec(self) -> List[dict]:
        return list(self._spec.get("outputs", []))

    def __call__(self, *args: Any) -> Any:
        if self._exported is None:
            raise RuntimeError(
                "this bundle has no serialized program (saved without input_spec); "
                "only state_dict() is available"
            )
        if self._compiled is None:
            call = self._exported.call
            # params passed as an argument (NOT closed over): closure arrays
            # would be baked into the executable as constants, doubling HBM.
            self._compiled = jax.jit(lambda params_, *xs: call(params_, *xs))
        params = {k: t._data for k, t in self._state.items()}
        arrays = [a._data if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
        out = self._compiled(params, *arrays)
        return jax.tree_util.tree_map(Tensor, out)


def load(path: str, **config: Any) -> TranslatedLayer:
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    exported = None
    model_text = None
    if os.path.exists(path + ".pdmodel"):
        with open(path + ".pdmodel", "rb") as f:
            blob = f.read()
        if blob.startswith(_MAGIC):
            exported = jax.export.deserialize(blob[len(_MAGIC):])
        else:  # pre-r4 text bundles
            model_text = blob.decode("utf-8", errors="replace")
    spec = None
    if os.path.exists(path + ".pdspec"):
        with open(path + ".pdspec") as f:
            spec = json.load(f)
    return TranslatedLayer(state, exported, spec, model_text)
