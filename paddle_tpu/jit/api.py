"""jit capture: trace Tensor programs into compiled XLA executables.

TPU-native counterpart of the reference's ``paddle.jit.to_static`` + CINN
(SURVEY §3.3): where the reference intercepts bytecode (SOT) or rewrites ASTs
to build a Program, here the Tensor ops are already pure jax functions, so
**Python tracing under jax.jit is the whole capture machinery** — no bytecode
interpreter needed, and XLA plays the role of CINN/PirInterpreter.

State threading: a traced function may mutate framework state — Layer
parameters (optimizer updates), buffers (batch-norm running stats), optimizer
accumulators. ``StaticFunction`` discovers Layers/Optimizers reachable from
the call, passes their arrays as inputs, restores them as outputs, and donates
the input buffers — so a full train step (forward + loss.backward() +
opt.step()) compiles into ONE XLA program with in-place buffer reuse. This is
the analog of the reference's whole-program Program + executor path, minus the
hand-rolled interpreter.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.core import autograd as _ag
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.observability.recompile import (
    CAUSE_FIRST_CALL,
    CAUSE_MODE_FLIP,
    CAUSE_NEW_SHAPE_DTYPE,
    GLOBAL_WATCHDOG,
)

# trace failures that mean "this fragment is not capturable", not user bugs:
# a tracer leaked into Python control flow / indexing / int conversion
_TRACE_BREAK_ERRORS = (
    jax.errors.ConcretizationTypeError,  # includes TracerBoolConversionError
    jax.errors.TracerArrayConversionError,
    jax.errors.TracerIntegerConversionError,
    jax.errors.NonConcreteBooleanIndexError,
)

__all__ = ["to_static", "StaticFunction", "not_to_static", "ignore_module"]


def _is_tensor(x: Any) -> bool:
    return isinstance(x, Tensor)


class _StateSpec:
    """The mutable framework state captured by one trace: ordered tensors
    (params/buffers) and optimizer accumulator slots."""

    def __init__(self) -> None:
        self.tensors: List[Tensor] = []
        self.optimizers: List[Any] = []
        self._seen: set = set()

    def add_tensor(self, t: Tensor) -> None:
        if id(t) not in self._seen:
            self._seen.add(id(t))
            self.tensors.append(t)

    def add_layer(self, layer: Any) -> None:
        for p in layer.parameters():
            self.add_tensor(p)
        for b in layer.buffers():
            self.add_tensor(b)

    def add_optimizer(self, opt: Any) -> None:
        if id(opt) in self._seen:
            return
        self._seen.add(id(opt))
        self.optimizers.append(opt)
        for p in opt._parameters:
            self.add_tensor(p)
        # Materialize accumulators now so they are trace inputs, not baked
        # constants (single compilation instead of two).
        for p in opt._parameters:
            if not p.stop_gradient:
                opt._state_for(p)

    def snapshot(self) -> Tuple[List[Any], List[Dict[str, Any]], Any]:
        import paddle_tpu.core.rng as _rng

        tensor_arrays = [t._data for t in self.tensors]
        opt_states = []
        for opt in self.optimizers:
            if opt._step_buf is None:
                opt._step_buf = jnp.zeros((), jnp.int32)
            acc = {}
            for p in opt._parameters:
                st = opt._accumulators.get(id(p))
                if st is not None:
                    acc[p.name] = st
            opt_states.append({"step": opt._step_buf, "acc": acc, "lr": jnp.asarray(opt.get_lr(), jnp.float32)})
        # The global PRNG key is threaded as state so random ops (dropout)
        # draw fresh masks on every call of the compiled program.
        rng_key = _rng.default_generator()._key
        return tensor_arrays, opt_states, rng_key

    def bind(self, tensor_arrays: Sequence[Any], opt_states: Sequence[Dict[str, Any]], rng_key: Any, tracing: bool) -> None:
        import paddle_tpu.core.rng as _rng

        for t, arr in zip(self.tensors, tensor_arrays):
            t._data = arr
        for opt, st in zip(self.optimizers, opt_states):
            opt._step_buf = st["step"]
            for p in opt._parameters:
                if p.name in st["acc"]:
                    opt._accumulators[id(p)] = st["acc"][p.name]
            opt._lr_array = st["lr"] if tracing else None
        _rng.default_generator()._key = rng_key

    def readback(self) -> Tuple[List[Any], List[Dict[str, Any]], Any]:
        import paddle_tpu.core.rng as _rng

        tensor_arrays = [t._data for t in self.tensors]
        opt_states = []
        for opt in self.optimizers:
            acc = {}
            for p in opt._parameters:
                st = opt._accumulators.get(id(p))
                if st is not None:
                    acc[p.name] = st
            opt_states.append({"step": opt._step_buf, "acc": acc, "lr": jnp.zeros((), jnp.float32)})
            opt._lr_array = None
        return tensor_arrays, opt_states, _rng.default_generator()._key


def _discover_state(objs: Sequence[Any]) -> _StateSpec:
    from paddle_tpu.nn.layer.layers import Layer
    from paddle_tpu.optimizer.optimizer import Optimizer

    spec = _StateSpec()
    for obj in objs:
        # unwrap optimizer wrappers (DygraphShardingOptimizer,
        # HybridParallelOptimizer) down to the stateful inner Optimizer
        while not isinstance(obj, Optimizer) and hasattr(obj, "_inner_opt"):
            obj = obj._inner_opt
        if isinstance(obj, Optimizer):
            spec.add_optimizer(obj)
    for obj in objs:
        if isinstance(obj, Layer):
            spec.add_layer(obj)
    return spec


class StaticFunction:
    """Callable wrapping a traced+compiled program cache
    (reference ``dy2static/program_translator.py`` StaticFunction parity)."""

    def __init__(self, fn: Callable, input_spec: Any = None, build_strategy: Any = None, full_graph: bool = True) -> None:
        functools.update_wrapper(self, fn)
        self._fn = fn
        self._input_spec = input_spec
        self._cache: Dict[Any, Any] = {}
        self._bound_self = getattr(fn, "__self__", None)
        # full_graph=False is the SOT analog (reference jit/sot/translate.py:
        # guard-based capture with graph breaks): on an untraceable fragment
        # (data-dependent Python control flow) the call falls back to eager
        # for that guard key instead of raising; the key set below is the
        # guard cache, so later calls with the same signature skip the
        # doomed re-trace.
        self._full_graph = bool(full_graph)
        self._eager_keys: set = set()
        # every key ever traced (never popped, unlike _cache): the recompile
        # watchdog's attribution history — a later key differing ONLY in the
        # training tuple is a train/eval mode flip, not a new shape bucket
        self._compiled_keys: set = set()

    @property
    def function(self) -> Callable:
        return self._fn

    def __get__(self, instance: Any, owner: Any = None) -> "StaticFunction":
        if instance is None:
            return self
        # Cache the bound wrapper on the instance so the compiled-program cache
        # survives across attribute accesses.
        name = getattr(self._fn, "__name__", "forward")
        cached = instance.__dict__.get(f"__static_{name}__")
        if cached is None:
            cached = StaticFunction(
                self._fn.__get__(instance, owner), self._input_spec,
                full_graph=self._full_graph,
            )
            instance.__dict__[f"__static_{name}__"] = cached
        return cached

    def _cache_key(self, flat_in: Sequence[Any], treedef: Any, state: _StateSpec, scan_objs: Sequence[Any]) -> Any:
        from paddle_tpu.nn.layer.layers import Layer

        sig = []
        for leaf in flat_in:
            if isinstance(leaf, Tensor):
                sig.append(("T", tuple(leaf.shape), str(jnp.dtype(leaf.dtype))))
            elif isinstance(leaf, (jax.Array,)):
                sig.append(("A", tuple(leaf.shape), str(leaf.dtype)))
            else:
                sig.append(("S", repr(leaf)))
        # training flags of every reachable (sub)layer: train()/eval() bakes
        # different dropout/batch-norm programs, so mode changes must retrace.
        training = []
        for obj in scan_objs:
            if isinstance(obj, Layer):
                training.append(obj.training)
                training.extend(l.training for l in obj.sublayers())
        return (treedef, tuple(sig), tuple(id(t) for t in state.tensors), tuple(training))

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs), is_leaf=_is_tensor)
        scan_objs = list(args) + list(kwargs.values())
        if self._bound_self is not None:
            scan_objs.append(self._bound_self)
        state = _discover_state(scan_objs)
        key = self._cache_key(leaves, treedef, state, scan_objs)

        if key in self._eager_keys:  # guard cache: known graph break
            return self._fn(*args, **kwargs)

        tensor_pos = [i for i, l in enumerate(leaves) if isinstance(l, (Tensor, jax.Array))]
        in_arrays = [leaves[i]._data if isinstance(leaves[i], Tensor) else leaves[i] for i in tensor_pos]
        state_arrays, opt_states, rng_key = state.snapshot()

        cache_miss = key not in self._cache
        if cache_miss:
            fn = self._fn

            def staged(state_arrays_, opt_states_, rng_key_, in_arrays_):
                import paddle_tpu.core.rng as _rng

                # snapshot .grad alongside ._data: a trace that fails AFTER
                # backward() has already written tracer-valued grads into the
                # live Parameters — restoring only _data would hand the
                # graph-break eager re-run (and grad accumulation) leaked
                # tracers that poison every later op
                saved = [(t, t._data, t._grad) for t in state.tensors]
                saved_opt = [
                    (opt, opt._step_buf, dict(opt._accumulators), opt._lr_array)
                    for opt in state.optimizers
                ]
                saved_rng = _rng.default_generator()._key
                try:
                    state.bind(state_arrays_, opt_states_, rng_key_, tracing=True)
                    rebuilt = list(leaves)
                    for pos, arr in zip(tensor_pos, in_arrays_):
                        orig = leaves[pos]
                        if isinstance(orig, Tensor):
                            t = Tensor(arr, stop_gradient=orig.stop_gradient)
                            rebuilt[pos] = t
                        else:
                            rebuilt[pos] = arr
                    a, k = jax.tree_util.tree_unflatten(treedef, rebuilt)
                    out = fn(*a, **k)
                    out_arrays = jax.tree_util.tree_map(
                        lambda o: o._data if isinstance(o, Tensor) else o,
                        out,
                        is_leaf=_is_tensor,
                    )
                    new_state, new_opt, new_rng = state.readback()
                    return out_arrays, new_state, new_opt, new_rng
                finally:
                    for t, d, g in saved:
                        t._data = d
                        t._grad = g
                    for opt, sb, acc, lra in saved_opt:
                        opt._step_buf = sb
                        opt._accumulators = acc
                        opt._lr_array = lra
                    _rng.default_generator()._key = saved_rng

            self._cache[key] = jax.jit(staged, donate_argnums=(0, 1))

        try:
            out_arrays, new_state, new_opt, new_rng = self._cache[key](
                state_arrays, opt_states, rng_key, in_arrays
            )
        except _TRACE_BREAK_ERRORS as exc:
            self._cache.pop(key, None)
            if self._full_graph:
                raise
            # graph break (reference SOT's fallback-to-eager): drop the doomed
            # compile-cache entry, remember the guard key, run eagerly
            import warnings

            self._eager_keys.add(key)
            warnings.warn(
                f"to_static({getattr(self._fn, '__name__', '?')}): graph break — "
                f"falling back to eager for this input signature "
                f"({type(exc).__name__}); pass full_graph=True to make this an error",
                stacklevel=2,
            )
            return self._fn(*args, **kwargs)
        except BaseException:  # any first-exec failure must uncache; see below
            if cache_miss:
                # the first execution failed past the trace-break net (XLA
                # runtime error, data-dependent check): drop the entry so a
                # retry re-traces and the watchdog records the compile —
                # otherwise the cached program serves forever uncounted
                self._cache.pop(key, None)
            raise
        # Commit mutated state back into the framework objects.
        import paddle_tpu.core.rng as _rng

        with _ag.set_grad_enabled(False):
            for t, arr in zip(state.tensors, new_state):
                t._data = arr
            for opt, st in zip(state.optimizers, new_opt):
                opt._step_buf = st["step"]
                for p in opt._parameters:
                    if p.name in st["acc"]:
                        opt._accumulators[id(p)] = st["acc"][p.name]
                opt._step_count += 1
            # the key comes back replicated over the step's mesh; committing
            # it that way would silently place every LATER tensor creation on
            # the mesh (fresh layers, exports, ... inherit 8-device
            # shardings). Round-trip the 16-byte key through host so it
            # becomes an UNCOMMITTED default-device array — compatible with
            # both later single-device work and the next sharded step.
            sharding = getattr(new_rng, "sharding", None)
            if sharding is not None and len(getattr(sharding, "device_set", ())) > 1:
                import numpy as _np

                new_rng = jnp.asarray(_np.asarray(new_rng))
            _rng.default_generator()._key = new_rng
        if cache_miss:
            # record only HERE — after the trace succeeded AND state was
            # committed: a graph break above never produced a compiled
            # program, and a RecompileBudgetWarning escalated to an error
            # (warnings-as-errors) must not be conflated with an execution
            # failure — at this point the donated buffers' replacements are
            # already committed and the cache entry stays valid
            if not self._compiled_keys:
                cause = CAUSE_FIRST_CALL
            elif any(
                k[:3] == key[:3] and k[3] != key[3] for k in self._compiled_keys
            ):
                cause = CAUSE_MODE_FLIP
            else:
                cause = CAUSE_NEW_SHAPE_DTYPE
            self._compiled_keys.add(key)
            jitted = self._cache.get(key)

            def _cost_thunk(_jitted=jitted):
                # devprof cost capture (runs only at devprof_sample_rate>0):
                # an introspective AOT lowering of the program just compiled.
                # Built from ShapeDtypeStructs, not the live arrays — argnums
                # (0, 1) are donated, so on TPU the input buffers are already
                # consumed; avals survive donation (shape/dtype metadata is
                # readable on deleted arrays) and .lower takes them directly.
                abst = lambda a: (  # noqa: E731 - local one-liner
                    jax.ShapeDtypeStruct(a.shape, a.dtype)
                    if hasattr(a, "shape") and hasattr(a, "dtype")
                    else a
                )
                if _jitted is None:
                    return None
                return _jitted.lower(
                    *jax.tree_util.tree_map(
                        abst, (state_arrays, opt_states, rng_key, in_arrays)
                    )
                ).compile().cost_analysis()

            GLOBAL_WATCHDOG.record_compile(
                getattr(self._fn, "__qualname__", None)
                or getattr(self._fn, "__name__", "<fn>"),
                signature=key[1],
                cause=cause,
                cost_thunk=_cost_thunk,
            )
        return jax.tree_util.tree_map(
            lambda o: Tensor(o) if isinstance(o, jax.Array) else o, out_arrays
        )

    def concrete_program(self) -> Any:  # pragma: no cover - introspection aid
        return self._cache


def to_static(
    function: Optional[Callable] = None,
    input_spec: Any = None,
    build_strategy: Any = None,
    backend: Any = None,
    full_graph: bool = True,
    **kwargs: Any,
) -> Any:
    """``paddle.jit.to_static`` parity (reference ``python/paddle/jit/api.py:195``)."""

    def deco(fn: Callable) -> StaticFunction:
        if isinstance(fn, StaticFunction):
            return fn
        from paddle_tpu.nn.layer.layers import Layer

        if isinstance(fn, Layer):
            fn.forward = StaticFunction(fn.forward, input_spec, full_graph=full_graph)
            return fn
        return StaticFunction(fn, input_spec, build_strategy, full_graph)

    if function is not None:
        return deco(function)
    return deco


def not_to_static(fn: Callable) -> Callable:
    fn.__paddle_tpu_not_to_static__ = True  # type: ignore[attr-defined]
    return fn


def ignore_module(modules: Any) -> None:
    """Compat no-op: tracing has no module blacklist needs."""
