"""Tensor creation ops (reference ``python/paddle/tensor/creation.py``)."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dtypes import convert_dtype
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.registry import defop

__all__ = [
    "to_tensor",
    "zeros",
    "ones",
    "full",
    "empty",
    "zeros_like",
    "ones_like",
    "full_like",
    "empty_like",
    "arange",
    "linspace",
    "logspace",
    "eye",
    "meshgrid",
    "diag",
    "diagflat",
    "tril",
    "triu",
    "clone",
    "assign",
    "create_parameter",
]


def _shape(shape: Any) -> Sequence[int]:
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy().reshape(-1))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def to_tensor(
    data: Any,
    dtype: Any = None,
    place: Any = None,
    stop_gradient: bool = True,
) -> Tensor:
    """``paddle.to_tensor`` parity: array-like → device Tensor."""
    if isinstance(data, Tensor):
        t = Tensor(data._data, dtype=dtype, place=place, stop_gradient=stop_gradient)
        return t
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


def zeros(shape: Any, dtype: Any = "float32", name: Any = None) -> Tensor:
    return Tensor(jnp.zeros(_shape(shape), convert_dtype(dtype)))


def ones(shape: Any, dtype: Any = "float32", name: Any = None) -> Tensor:
    return Tensor(jnp.ones(_shape(shape), convert_dtype(dtype)))


def full(shape: Any, fill_value: Any, dtype: Any = "float32", name: Any = None) -> Tensor:
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return Tensor(jnp.full(_shape(shape), fill_value, convert_dtype(dtype)))


def empty(shape: Any, dtype: Any = "float32", name: Any = None) -> Tensor:
    # XLA/PJRT buffers are materialized on write; zeros is the honest "empty".
    return zeros(shape, dtype)


def _like_dtype(x: Tensor, dtype: Any) -> Any:
    return convert_dtype(dtype) if dtype is not None else x.dtype


def zeros_like(x: Tensor, dtype: Any = None, name: Any = None) -> Tensor:
    return Tensor(jnp.zeros(x.shape, _like_dtype(x, dtype)))


def ones_like(x: Tensor, dtype: Any = None, name: Any = None) -> Tensor:
    return Tensor(jnp.ones(x.shape, _like_dtype(x, dtype)))


def full_like(x: Tensor, fill_value: Any, dtype: Any = None, name: Any = None) -> Tensor:
    return Tensor(jnp.full(x.shape, fill_value, _like_dtype(x, dtype)))


def empty_like(x: Tensor, dtype: Any = None, name: Any = None) -> Tensor:
    return zeros_like(x, dtype)


def arange(start: Any = 0, end: Any = None, step: Any = 1, dtype: Any = None, name: Any = None) -> Tensor:
    for v in (start, end, step):
        if isinstance(v, Tensor):
            raise TypeError("arange bounds must be python scalars")
    if end is None:
        start, end = 0, start
    return Tensor(jnp.arange(start, end, step, dtype=convert_dtype(dtype) if dtype else None))


def linspace(start: Any, stop: Any, num: int, dtype: Any = None, name: Any = None) -> Tensor:
    return Tensor(jnp.linspace(start, stop, int(num), dtype=convert_dtype(dtype) if dtype else None))


def logspace(start: Any, stop: Any, num: int, base: float = 10.0, dtype: Any = None, name: Any = None) -> Tensor:
    return Tensor(jnp.logspace(start, stop, int(num), base=base, dtype=convert_dtype(dtype) if dtype else None))


def eye(num_rows: int, num_columns: Optional[int] = None, dtype: Any = "float32", name: Any = None) -> Tensor:
    return Tensor(jnp.eye(num_rows, num_columns, dtype=convert_dtype(dtype)))


def meshgrid(*args: Tensor, **kwargs: Any) -> List[Tensor]:
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    arrays = [a._data if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
    return [Tensor(m) for m in jnp.meshgrid(*arrays, indexing="ij")]


@defop("diag")
def diag(x, offset=0, padding_value=0):
    if x.ndim == 1 and padding_value != 0:
        n = x.shape[0] + abs(offset)
        out = jnp.full((n, n), padding_value, x.dtype)
        return out + (jnp.diag(x, k=offset) - jnp.diag(jnp.full(x.shape, padding_value, x.dtype), k=offset))
    return jnp.diag(x, k=offset)


@defop("diagflat")
def diagflat(x, offset=0):
    return jnp.diagflat(x, k=offset)


@defop("tril")
def tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


@defop("triu")
def triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


@defop("clone_fn", tensor_method=None)
def _clone_op(x):
    return x + jnp.zeros((), x.dtype)


def clone(x: Tensor) -> Tensor:
    return x.clone()


def assign(x: Any, output: Optional[Tensor] = None) -> Tensor:
    value = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    if output is not None:
        output.set_value(value)
        return output
    return Tensor(value)


def create_parameter(
    shape: Sequence[int],
    dtype: Any = "float32",
    name: Optional[str] = None,
    attr: Any = None,
    is_bias: bool = False,
    default_initializer: Any = None,
) -> "Tensor":
    """``paddle.create_parameter`` parity."""
    from paddle_tpu.core.tensor import Parameter
    from paddle_tpu.nn import initializer as I

    init = default_initializer
    if init is None:
        init = I.Constant(0.0) if is_bias else I.XavierNormal()
    p = Parameter(jnp.zeros(_shape(shape), convert_dtype(dtype)), name=name)
    init(p)
    return p
