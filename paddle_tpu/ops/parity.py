"""Long-tail op parity: the remaining XLA-mappable entries of the reference's
``paddle/phi/ops/yaml/ops.yaml`` (466 ops) not covered by the thematic op
modules. Grouped by family; each op lowers to jnp/lax and fuses under XLA.
The checked-in audit (``tests/test_op_parity_audit.py``) diffs this surface
against the ops.yaml manifest.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import rng as _rng
from paddle_tpu.core.dtypes import convert_dtype
from paddle_tpu.core.tensor import Tensor, register_tensor_method
from paddle_tpu.ops.registry import defop

__all__ = [
    # special functions
    "gammaln", "gammainc", "gammaincc", "polygamma", "i0e", "i1", "i1e",
    # random families
    "binomial", "dirichlet", "standard_gamma", "gaussian",
    "truncated_gaussian_random",
    # complex views
    "complex", "as_complex", "as_real",
    # linalg / matrix (the *_ in-place variants bind as Tensor methods)
    "inverse", "lu_unpack", "diag_embed", "fill_diagonal",
    "fill_diagonal_tensor", "tril_indices", "triu_indices", "reduce_as",
    "squared_l2_norm", "l1_norm", "frobenius_norm", "p_norm",
    # distances
    "pdist", "cdist",
    # manipulation
    "index_fill", "tensor_unfold", "fill",
    "is_empty", "reverse", "view_dtype", "view_shape", "shape",
    # losses
    "hinge_loss", "huber_loss", "identity_loss",
    "sigmoid_cross_entropy_with_logits",
    # decode / sampling
    "top_p_sampling", "gather_tree", "viterbi_decode",
    # segment / graph message passing
    "segment_pool", "send_u_recv", "send_ue_recv", "send_uv",
    # vision / spatial
    "grid_sample", "affine_grid", "temporal_shift", "affine_channel",
    "lp_pool2d", "unpool", "unpool3d", "nms", "box_coder", "roi_align",
    "roi_pool", "box_clip", "prior_box", "matrix_nms",
    # misc parity
    "clip_by_norm", "edit_distance", "add_position_encoding", "spectral_norm",
]


# ---- special functions -----------------------------------------------------
# ref ops.yaml: gammaln, gammaincc, polygamma, i0e, i1, i1e (Bessel/Gamma
# kernels under paddle/phi/kernels/*; here: jax.scipy.special, MXU-free VPU math)

gammaln = defop("gammaln", tensor_method="gammaln")(jax.scipy.special.gammaln)
gammainc = defop("gammainc", tensor_method="gammainc")(
    lambda x, y: jax.scipy.special.gammainc(x, y)
)
gammaincc = defop("gammaincc", tensor_method="gammaincc")(
    lambda x, y: jax.scipy.special.gammaincc(x, y)
)


@defop("polygamma", tensor_method="polygamma")
def polygamma(x, n=0):
    if n == 0:
        return jax.scipy.special.digamma(x)
    return jax.scipy.special.polygamma(n, x)


i0e = defop("i0e", tensor_method="i0e")(jax.scipy.special.i0e)
i1 = defop("i1", tensor_method="i1")(jax.scipy.special.i1)
i1e = defop("i1e", tensor_method="i1e")(jax.scipy.special.i1e)


# ---- random families -------------------------------------------------------
# ref ops.yaml: binomial, dirichlet (distribution kernels); gaussian /
# truncated_gaussian_random (creation); standard_gamma


def binomial(count, prob, name=None):
    c = count._data if isinstance(count, Tensor) else jnp.asarray(count)
    p = prob._data if isinstance(prob, Tensor) else jnp.asarray(prob)
    c, p = jnp.broadcast_arrays(c, p)
    return Tensor(
        jax.random.binomial(_rng.next_key(), c.astype(jnp.float32), p).astype(jnp.int64)
    )


def dirichlet(alpha, name=None):
    a = alpha._data if isinstance(alpha, Tensor) else jnp.asarray(alpha)
    return Tensor(jax.random.dirichlet(_rng.next_key(), a))


def standard_gamma(x, name=None):
    a = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.gamma(_rng.next_key(), a))


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype="float32", name=None):
    key = jax.random.PRNGKey(seed) if seed else _rng.next_key()
    shp = tuple(int(s) for s in (shape if not isinstance(shape, int) else (shape,)))
    dt = convert_dtype(dtype)
    return Tensor(mean + std * jax.random.normal(key, shp, dt))


def truncated_gaussian_random(shape, mean=0.0, std=1.0, seed=0, a=-2.0, b=2.0,
                              dtype="float32", name=None):
    key = jax.random.PRNGKey(seed) if seed else _rng.next_key()
    shp = tuple(int(s) for s in (shape if not isinstance(shape, int) else (shape,)))
    dt = convert_dtype(dtype)
    return Tensor(mean + std * jax.random.truncated_normal(key, a, b, shp, dt))


# ---- complex views ---------------------------------------------------------
# ref ops.yaml: complex, as_complex, as_real


@defop("complex", tensor_method=None)
def complex(real, imag):  # noqa: A001
    # promote like the reference kernel (dtype::ToComplex of the common
    # type): float64 inputs build complex128, not a silent float32 downcast;
    # integer and half-precision inputs take the float32 floor
    # (lax.complex supports only f32/f64 operands)
    dt = jnp.result_type(real, imag)
    if not jnp.issubdtype(dt, jnp.floating) or jnp.finfo(dt).bits < 32:
        dt = jnp.float32
    return jax.lax.complex(jnp.asarray(real, dt), jnp.asarray(imag, dt))


@defop("as_complex", tensor_method="as_complex")
def as_complex(x):
    if x.shape[-1] != 2:
        raise ValueError(f"as_complex needs trailing dim 2, got {x.shape}")
    return jax.lax.complex(x[..., 0], x[..., 1])


@defop("as_real", tensor_method="as_real")
def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


# ---- linalg / matrix -------------------------------------------------------
# ref ops.yaml: inverse, lu_unpack, diag_embed, fill_diagonal(+_tensor),
# tril_indices, triu_indices, reduce_as, squared_l2_norm, l1_norm,
# frobenius_norm, p_norm

inverse = defop("inverse", tensor_method="inverse")(jnp.linalg.inv)


@defop("lu_unpack", tensor_method=None)
def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True):
    """Unpack the packed LU factorization (ref ``lu_unpack`` kernel): ``x``
    is the packed LU matrix, ``y`` the 1-based pivot vector."""
    m, n = x.shape[-2], x.shape[-1]
    k = min(m, n)
    L = jnp.tril(x[..., :, :k], -1) + jnp.eye(m, k, dtype=x.dtype)
    U = jnp.triu(x[..., :k, :])
    piv = y.astype(jnp.int32) - 1

    def perm_from_pivots(p):
        base = jnp.arange(m, dtype=jnp.int32)

        def swap(i, order):
            j = p[i]
            a, b = order[i], order[j]
            return order.at[i].set(b).at[j].set(a)

        return jax.lax.fori_loop(0, p.shape[0], swap, base)

    if piv.ndim == 1:
        order = perm_from_pivots(piv)
    else:
        order = jax.vmap(perm_from_pivots)(piv.reshape((-1, piv.shape[-1]))).reshape(
            piv.shape[:-1] + (m,)
        )
    P = jax.nn.one_hot(order, m, dtype=x.dtype)
    P = jnp.swapaxes(P, -1, -2)
    return P, L, U


@defop("diag_embed", tensor_method="diag_embed")
def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    n = x.shape[-1] + abs(offset)
    out = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = jnp.arange(x.shape[-1])
    r = idx + max(-offset, 0)
    c = idx + max(offset, 0)
    out = out.at[..., r, c].set(x)
    nd = out.ndim
    d1, d2 = dim1 % nd, dim2 % nd
    if (d1, d2) != (nd - 2, nd - 1):
        perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
        dst = sorted((d1, d2))
        perm.insert(dst[0], nd - 2)
        perm.insert(dst[1], nd - 1)
        out = jnp.transpose(out, perm)
    return out


def _diag_len(rows, cols, offset):
    # non-square aware: offset>=0 walks right (cols-offset), offset<0 walks
    # down (rows+offset)
    return max(0, min(rows, cols - offset) if offset >= 0 else min(rows + offset, cols))


@defop("fill_diagonal", tensor_method="fill_diagonal", inplace_method="fill_diagonal_")
def fill_diagonal(x, value=0.0, offset=0, wrap=False):
    idx = jnp.arange(_diag_len(x.shape[-2], x.shape[-1], offset))
    r = idx + max(-offset, 0)
    c = idx + max(offset, 0)
    return x.at[..., r, c].set(jnp.asarray(value, x.dtype))


@defop("fill_diagonal_tensor", tensor_method="fill_diagonal_tensor")
def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1):
    nd = x.ndim
    d1, d2 = dim1 % nd, dim2 % nd
    perm = [i for i in range(nd) if i not in (d1, d2)] + [d1, d2]
    xt = jnp.transpose(x, perm)
    idx = jnp.arange(_diag_len(xt.shape[-2], xt.shape[-1], offset))
    r = idx + max(-offset, 0)
    c = idx + max(offset, 0)
    xt = xt.at[..., r, c].set(jnp.asarray(y, x.dtype))
    inv = np.argsort(perm)
    return jnp.transpose(xt, inv)


def tril_indices(row, col=None, offset=0, dtype="int64", name=None):
    col = row if col is None else col
    r, c = np.tril_indices(int(row), int(offset), int(col))
    return Tensor(jnp.asarray(np.stack([r, c]), convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64", name=None):
    col = row if col is None else col
    r, c = np.triu_indices(int(row), int(offset), int(col))
    return Tensor(jnp.asarray(np.stack([r, c]), convert_dtype(dtype)))


@defop("reduce_as", tensor_method="reduce_as")
def reduce_as(x, target):
    """Sum-reduce ``x`` to ``target``'s broadcast shape (ref ``reduce_as``)."""
    tshape = target.shape
    out = x
    while out.ndim > len(tshape):
        out = out.sum(axis=0)
    for i, (a, b) in enumerate(zip(out.shape, tshape)):
        if b == 1 and a != 1:
            out = out.sum(axis=i, keepdims=True)
    return out


squared_l2_norm = defop("squared_l2_norm", tensor_method=None)(
    lambda x: jnp.sum(jnp.square(x)).reshape((1,))
)
l1_norm = defop("l1_norm", tensor_method=None)(lambda x: jnp.sum(jnp.abs(x)))


@defop("frobenius_norm", tensor_method=None)
def frobenius_norm(x, axis=None, keepdim=False):
    axis = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdim))


@defop("p_norm", tensor_method=None)
def p_norm(x, porder=2.0, axis=-1, epsilon=1e-12, keepdim=False, asvector=False):
    if asvector:
        x = x.reshape(-1)
        axis = 0
    if porder == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if porder == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    s = jnp.sum(jnp.power(jnp.abs(x), porder), axis=axis, keepdims=keepdim)
    return jnp.power(s + epsilon, 1.0 / porder)


# ---- distances -------------------------------------------------------------
# ref: python-level paddle.pdist / paddle.cdist over dist kernels


@defop("pdist", tensor_method=None)
def pdist(x, p=2.0):
    n = x.shape[0]
    d = _pairwise_dist(x, x, p)
    r, c = np.triu_indices(n, 1)
    return d[r, c]


def _pairwise_dist(a, b, p):
    diff = jnp.abs(a[..., :, None, :] - b[..., None, :, :])
    if p == float("inf"):
        return jnp.max(diff, axis=-1)
    if p == 0:
        return jnp.sum((diff != 0).astype(a.dtype), axis=-1)
    if p == 2.0:
        return jnp.sqrt(jnp.sum(jnp.square(diff), axis=-1))
    return jnp.power(jnp.sum(jnp.power(diff, p), axis=-1), 1.0 / p)


@defop("cdist", tensor_method=None)
def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary"):
    if p == 2.0 and "use_mm" in str(compute_mode):
        # MXU path: |a-b|^2 = |a|^2 + |b|^2 - 2ab
        x2 = jnp.sum(jnp.square(x), axis=-1, keepdims=True)
        y2 = jnp.sum(jnp.square(y), axis=-1, keepdims=True)
        sq = x2 + jnp.swapaxes(y2, -1, -2) - 2.0 * (x @ jnp.swapaxes(y, -1, -2))
        return jnp.sqrt(jnp.maximum(sq, 0.0))
    return _pairwise_dist(x, y, p)


# ---- manipulation ----------------------------------------------------------
# ref ops.yaml: fill (inplace), is_empty, reverse, view_dtype/view_shape,
# tensor_unfold; python-level index_fill


@defop("index_fill", tensor_method="index_fill", inplace_method="index_fill_")
def index_fill(x, index, axis, value):
    idx = jnp.asarray(index, jnp.int32)
    moved = jnp.moveaxis(x, axis, 0)
    moved = moved.at[idx].set(jnp.asarray(value, x.dtype))
    return jnp.moveaxis(moved, 0, axis)


@defop("tensor_unfold", tensor_method="unfold")
def tensor_unfold(x, axis, size, step):
    """Sliding windows along ``axis`` (ref ``tensor_unfold``; torch-style
    ``Tensor.unfold``): output appends a trailing window dim of ``size``."""
    length = x.shape[axis]
    n = (length - size) // step + 1
    starts = jnp.arange(n) * step
    moved = jnp.moveaxis(x, axis, 0)

    def win(s):
        return jax.lax.dynamic_slice_in_dim(moved, s, size, axis=0)

    wins = jax.vmap(win)(starts)  # [n, size, ...rest]
    wins = jnp.moveaxis(wins, 1, -1)  # [n, ...rest, size]
    return jnp.moveaxis(wins, 0, axis)


@defop("fill", tensor_method="fill", inplace_method="fill_")
def fill(x, value):
    return jnp.full_like(x, value)


@defop("is_empty", tensor_method="is_empty")
def is_empty(x):
    return jnp.asarray(x.size == 0)


@defop("reverse", tensor_method=None)
def reverse(x, axis):
    axis = [axis] if isinstance(axis, int) else list(axis)
    return jnp.flip(x, axis=axis)


@defop("view_dtype", tensor_method=None)
def view_dtype(x, dtype):
    return jax.lax.bitcast_convert_type(x, convert_dtype(dtype))


@defop("view_shape", tensor_method=None)
def view_shape(x, shape):
    return x.reshape(tuple(shape))


def shape(x, name=None):
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jnp.asarray(np.asarray(arr.shape, np.int32)))


# ---- losses ----------------------------------------------------------------
# ref ops.yaml: hinge_loss, huber_loss, identity_loss,
# sigmoid_cross_entropy_with_logits


@defop("hinge_loss", tensor_method=None)
def hinge_loss(logits, labels):
    return jnp.maximum(0.0, 1.0 - (2.0 * labels - 1.0) * logits)


@defop("huber_loss", tensor_method=None)
def huber_loss(input, label, delta=1.0):  # noqa: A002
    r = input - label
    a = jnp.abs(r)
    return jnp.where(a <= delta, 0.5 * r * r, delta * (a - 0.5 * delta))


@defop("identity_loss", tensor_method=None)
def identity_loss(x, reduction="none"):
    red = {0: "sum", 1: "mean", 2: "none"}.get(reduction, reduction)
    if red == "mean":
        return jnp.mean(x)
    if red == "sum":
        return jnp.sum(x)
    return x


@defop("sigmoid_cross_entropy_with_logits", tensor_method=None)
def sigmoid_cross_entropy_with_logits(x, label, normalize=False, ignore_index=-100):
    loss = jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    mask = (label != ignore_index).astype(x.dtype)
    loss = loss * mask
    if normalize:
        loss = loss / jnp.maximum(mask.sum(), 1.0)
    return loss


# ---- decode / sampling -----------------------------------------------------
# ref ops.yaml: top_p_sampling, gather_tree, viterbi_decode


def top_p_sampling(x, ps, threshold=None, seed=None, name=None):
    """Nucleus sampling (ref ``top_p_sampling`` kernel): keep the smallest
    prefix of the sorted distribution with cumulative prob >= p, renormalize,
    sample. Returns (values, ids) like the reference."""
    probs = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    p = ps._data if isinstance(ps, Tensor) else jnp.asarray(ps)
    key = jax.random.PRNGKey(int(seed)) if seed not in (None, -1) else _rng.next_key()
    sort_idx = jnp.argsort(-probs, axis=-1)
    sorted_p = jnp.take_along_axis(probs, sort_idx, axis=-1)
    cum = jnp.cumsum(sorted_p, axis=-1)
    keep = cum - sorted_p < p.reshape((-1,) + (1,) * (probs.ndim - 1))
    keep = keep.at[..., 0].set(True)
    filt = jnp.where(keep, sorted_p, 0.0)
    filt = filt / jnp.sum(filt, axis=-1, keepdims=True)
    pick = jax.random.categorical(key, jnp.log(jnp.maximum(filt, 1e-38)), axis=-1)
    ids = jnp.take_along_axis(sort_idx, pick[..., None], axis=-1)
    vals = jnp.take_along_axis(probs, ids, axis=-1)
    return Tensor(vals), Tensor(ids.astype(jnp.int64))


@defop("gather_tree", tensor_method=None)
def gather_tree(ids, parents):
    """Beam-search backtrace (ref ``gather_tree``): ids/parents
    ``[T, batch, beam]`` -> full sequences per final beam."""
    T = ids.shape[0]

    def step(beam_idx, t):
        t_ids = jnp.take_along_axis(ids[t], beam_idx, axis=-1)
        t_parents = jnp.take_along_axis(parents[t], beam_idx, axis=-1)
        return t_parents, t_ids

    final = jnp.broadcast_to(
        jnp.arange(ids.shape[2], dtype=parents.dtype), ids.shape[1:]
    )
    _, out = jax.lax.scan(step, final, jnp.arange(T - 1, -1, -1))
    return jnp.flip(out, axis=0)


@defop("viterbi_decode", tensor_method=None)
def viterbi_decode(potentials, transition_params, lengths=None, include_bos_eos_tag=True):
    """Viterbi decoding (ref ``viterbi_decode`` kernel): max-sum DP over the
    tag lattice via ``lax.scan``. potentials ``[B, T, N]``, transition
    ``[N(+2), N(+2)]``. Returns (scores, paths ``[B, T]``)."""
    B, T, N = potentials.shape
    trans = transition_params
    if include_bos_eos_tag:
        start, stop = trans[-2, :N], trans[:N, -1]
        trans = trans[:N, :N]
        alpha0 = potentials[:, 0] + start[None, :]
    else:
        alpha0 = potentials[:, 0]
    lens = (
        jnp.full((B,), T, jnp.int32) if lengths is None
        else jnp.asarray(lengths if not hasattr(lengths, "_data") else lengths._data, jnp.int32).reshape(-1)
    )

    def step(alpha, inp):
        emit, tix = inp
        scores = alpha[:, :, None] + trans[None, :, :]  # [B, from, to]
        best = jnp.argmax(scores, axis=1)
        new_alpha = jnp.max(scores, axis=1) + emit
        # padded timesteps (tix >= length): freeze alpha, identity backpointer
        active = (tix < lens)[:, None]
        alpha = jnp.where(active, new_alpha, alpha)
        ident = jnp.broadcast_to(jnp.arange(N)[None, :], best.shape)
        best = jnp.where(active, best, ident)
        return alpha, best

    alpha, backp = jax.lax.scan(
        step, alpha0, (jnp.swapaxes(potentials[:, 1:], 0, 1), jnp.arange(1, T))
    )
    if include_bos_eos_tag:
        alpha = alpha + stop[None, :]
    last = jnp.argmax(alpha, axis=-1)
    score = jnp.max(alpha, axis=-1)

    def back(tag, bp):
        prev = jnp.take_along_axis(bp, tag[:, None], axis=-1)[:, 0]
        return prev, tag

    # reverse scan emits tags for times 1..T-1 (forward-ordered); the final
    # carry is the time-0 tag
    first, path = jax.lax.scan(back, last, backp, reverse=True)
    path = jnp.concatenate([first[:, None], jnp.swapaxes(path, 0, 1)], axis=1)
    return score, path.astype(jnp.int64)


# ---- segment / graph message passing ---------------------------------------
# ref ops.yaml: segment_pool, send_u_recv, send_ue_recv, send_uv (graph
# kernels under paddle/phi/kernels/gpu/graph_send_*); jax segment ops map
# these directly


def _segment_reduce(data, ids, pool_type, num_segments):
    pool = pool_type.upper()
    if pool == "SUM":
        return jax.ops.segment_sum(data, ids, num_segments)
    if pool == "MEAN":
        s = jax.ops.segment_sum(data, ids, num_segments)
        c = jax.ops.segment_sum(jnp.ones((data.shape[0],), data.dtype), ids, num_segments)
        return s / jnp.maximum(c, 1.0).reshape((-1,) + (1,) * (data.ndim - 1))
    if pool == "MAX":
        return jax.ops.segment_max(data, ids, num_segments)
    if pool == "MIN":
        return jax.ops.segment_min(data, ids, num_segments)
    raise ValueError(f"unknown pool_type {pool_type}")


@defop("segment_pool", tensor_method=None)
def segment_pool(x, segment_ids, pooltype="SUM"):
    n = int(segment_ids[-1]) + 1 if segment_ids.shape[0] else 0
    return _segment_reduce(x, segment_ids.astype(jnp.int32), pooltype, n)


@defop("send_u_recv", tensor_method=None)
def send_u_recv(x, src_index, dst_index, reduce_op="SUM", out_size=None):
    n = int(out_size) if out_size else x.shape[0]
    return _segment_reduce(
        x[src_index.astype(jnp.int32)], dst_index.astype(jnp.int32), reduce_op, n
    )


@defop("send_ue_recv", tensor_method=None)
def send_ue_recv(x, y, src_index, dst_index, message_op="ADD", reduce_op="SUM", out_size=None):
    msg = x[src_index.astype(jnp.int32)]
    e = y
    if msg.ndim > e.ndim:
        e = e.reshape(e.shape + (1,) * (msg.ndim - e.ndim))
    msg = msg + e if message_op.upper() == "ADD" else msg * e
    n = int(out_size) if out_size else x.shape[0]
    return _segment_reduce(msg, dst_index.astype(jnp.int32), reduce_op, n)


@defop("send_uv", tensor_method=None)
def send_uv(x, y, src_index, dst_index, message_op="ADD"):
    a = x[src_index.astype(jnp.int32)]
    b = y[dst_index.astype(jnp.int32)]
    return a + b if message_op.upper() == "ADD" else a * b


# ---- vision / spatial ------------------------------------------------------
# ref ops.yaml: grid_sample, affine_grid, temporal_shift, affine_channel,
# lp_pool2d, unpool, nms, box_coder, roi_align


@defop("grid_sample", tensor_method=None)
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True):
    """2-D grid sampling (ref ``grid_sample_kernel``): x [N,C,H,W], grid
    [N,Ho,Wo,2] in [-1, 1]. Gather + lerp — fuses into a handful of XLA ops."""
    N, C, H, W = x.shape

    def unnorm(g, size):
        if align_corners:
            return (g + 1.0) * 0.5 * (size - 1)
        return ((g + 1.0) * size - 1.0) * 0.5

    gx = unnorm(grid[..., 0], W)
    gy = unnorm(grid[..., 1], H)

    def sample_at(ix, iy):
        inb = (ix >= 0) & (ix <= W - 1) & (iy >= 0) & (iy <= H - 1)
        cx = jnp.clip(ix, 0, W - 1).astype(jnp.int32)
        cy = jnp.clip(iy, 0, H - 1).astype(jnp.int32)
        v = x[jnp.arange(N)[:, None, None], :, cy, cx]  # [N,Ho,Wo,C]
        if padding_mode == "zeros":
            v = v * inb[..., None].astype(x.dtype)
        return v

    if mode == "nearest":
        out = sample_at(jnp.round(gx), jnp.round(gy))
    else:
        x0, y0 = jnp.floor(gx), jnp.floor(gy)
        x1, y1 = x0 + 1, y0 + 1
        wa = (x1 - gx) * (y1 - gy)
        wb = (gx - x0) * (y1 - gy)
        wc = (x1 - gx) * (gy - y0)
        wd = (gx - x0) * (gy - y0)
        out = (
            sample_at(x0, y0) * wa[..., None]
            + sample_at(x1, y0) * wb[..., None]
            + sample_at(x0, y1) * wc[..., None]
            + sample_at(x1, y1) * wd[..., None]
        )
    return jnp.moveaxis(out, -1, 1)  # [N,C,Ho,Wo]


@defop("affine_grid", tensor_method=None)
def affine_grid(theta, out_shape, align_corners=True):
    """ref ``affine_grid_kernel``: theta [N,2,3] -> grid [N,H,W,2]."""
    _, _, H, W = [int(s) for s in out_shape]

    def lin(n):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, n)
        return (jnp.arange(n, dtype=jnp.float32) * 2 + 1) / n - 1.0

    ys, xs = jnp.meshgrid(lin(H), lin(W), indexing="ij")
    base = jnp.stack([xs, ys, jnp.ones_like(xs)], axis=-1)  # [H,W,3]
    return jnp.einsum("hwk,nck->nhwc", base, theta)


@defop("temporal_shift", tensor_method=None)
def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    if data_format != "NCHW":
        x = jnp.moveaxis(x, -1, 1)
    NT, C, H, W = x.shape
    x5 = x.reshape(NT // seg_num, seg_num, C, H, W)
    c1 = int(C * shift_ratio)
    c2 = int(C * 2 * shift_ratio)
    back = jnp.pad(x5[:, 1:, :c1], ((0, 0), (0, 1), (0, 0), (0, 0), (0, 0)))
    fwd = jnp.pad(x5[:, :-1, c1:c2], ((0, 0), (1, 0), (0, 0), (0, 0), (0, 0)))
    keep = x5[:, :, c2:]
    out = jnp.concatenate([back, fwd, keep], axis=2).reshape(NT, C, H, W)
    if data_format != "NCHW":
        out = jnp.moveaxis(out, 1, -1)
    return out


@defop("affine_channel", tensor_method=None)
def affine_channel(x, scale, bias, data_format="NCHW"):
    if data_format == "NCHW":
        return x * scale.reshape(1, -1, 1, 1) + bias.reshape(1, -1, 1, 1)
    return x * scale + bias


@defop("lp_pool2d", tensor_method=None)
def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False,
              data_format="NCHW"):
    ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
    st = ks if stride is None else (
        (stride, stride) if isinstance(stride, int) else tuple(stride)
    )
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    if data_format != "NCHW":
        x = jnp.moveaxis(x, -1, 1)
    p = float(norm_type)
    xp = jnp.power(jnp.abs(x), p)
    xp = jnp.pad(xp, ((0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])))
    s = jax.lax.reduce_window(
        xp, 0.0, jax.lax.add, (1, 1) + ks, (1, 1) + st, "VALID"
    )
    out = jnp.power(s, 1.0 / p)
    if data_format != "NCHW":
        out = jnp.moveaxis(out, 1, -1)
    return out


@defop("unpool", tensor_method=None)
def unpool(x, indices, kernel_size=2, stride=None, padding=0, output_size=None,
           data_format="NCHW"):
    """Max-unpooling 2d (ref ``unpool_kernel``): scatter pooled values back
    to the flat-index positions recorded by max_pool(return_mask=True)."""
    N, C, H, W = x.shape
    if output_size is not None:
        Ho, Wo = int(output_size[-2]), int(output_size[-1])
    else:
        ks = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
        st = ks if stride is None else (stride if isinstance(stride, int) else stride[0])
        Ho, Wo = (H - 1) * st - 2 * padding + ks, (W - 1) * st - 2 * padding + ks
    flat = jnp.zeros((N, C, Ho * Wo), x.dtype)
    out = jax.vmap(
        jax.vmap(lambda f, v, i: f.at[i].set(v))
    )(flat, x.reshape(N, C, -1), indices.reshape(N, C, -1).astype(jnp.int32))
    return out.reshape(N, C, Ho, Wo)


@defop("nms", tensor_method=None)
def nms(boxes, threshold=0.3, scores=None):
    """Greedy hard-NMS (ref ``nms_kernel`` / ``paddle.vision.ops.nms``):
    boxes [N, 4]. Without ``scores`` the boxes are assumed pre-sorted by the
    caller's score order; with ``scores`` [N] they are sorted internally
    (descending) and the returned indices map back into the ORIGINAL box
    order, highest score first. Fixed-trip fori_loop — static shapes for
    XLA; suppressed tail entries are -1."""
    order = None
    if scores is not None:
        order = jnp.argsort(-jnp.asarray(scores))
        boxes = jnp.asarray(boxes)[order]
    n = boxes.shape[0]
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    areas = jnp.maximum(x2 - x1, 0.0) * jnp.maximum(y2 - y1, 0.0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0.0) * jnp.maximum(iy2 - iy1, 0.0)
    iou = inter / jnp.maximum(areas[:, None] + areas[None, :] - inter, 1e-10)

    def body(i, keep):
        sup = jnp.logical_and(keep[i], iou[i] > threshold)
        sup = sup & (jnp.arange(n) > i)
        return keep & ~sup

    keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    kept = jnp.nonzero(keep, size=n, fill_value=-1)[0]
    if order is not None:  # map sorted-space indices back to the caller's
        kept = jnp.where(kept >= 0, order[kept], -1)
    return kept


@defop("box_coder", tensor_method=None)
def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0):
    """ref ``box_coder_kernel``: encode/decode boxes against priors."""
    pw = prior_box[:, 2] - prior_box[:, 0] + (0.0 if box_normalized else 1.0)
    ph = prior_box[:, 3] - prior_box[:, 1] + (0.0 if box_normalized else 1.0)
    pcx = prior_box[:, 0] + pw * 0.5
    pcy = prior_box[:, 1] + ph * 0.5
    var = prior_box_var if prior_box_var is not None else jnp.ones((4,), target_box.dtype)
    if code_type.startswith("encode"):
        tw = target_box[:, 2] - target_box[:, 0] + (0.0 if box_normalized else 1.0)
        th = target_box[:, 3] - target_box[:, 1] + (0.0 if box_normalized else 1.0)
        tcx = target_box[:, 0] + tw * 0.5
        tcy = target_box[:, 1] + th * 0.5
        out = jnp.stack(
            [
                (tcx[:, None] - pcx[None, :]) / pw[None, :],
                (tcy[:, None] - pcy[None, :]) / ph[None, :],
                jnp.log(tw[:, None] / pw[None, :]),
                jnp.log(th[:, None] / ph[None, :]),
            ],
            axis=-1,
        )
        return out / jnp.reshape(var, (1, -1, 4) if var.ndim == 2 else (1, 1, 4))
    # decode: target [N, M, 4] deltas against priors broadcast on `axis`
    t = target_box
    v = jnp.reshape(var, (1, -1, 4) if var.ndim == 2 else (1, 1, 4))
    d = t * v
    shp = (1, -1) if axis == 1 else (-1, 1)
    cx = d[..., 0] * pw.reshape(shp) + pcx.reshape(shp)
    cy = d[..., 1] * ph.reshape(shp) + pcy.reshape(shp)
    w = jnp.exp(d[..., 2]) * pw.reshape(shp)
    h = jnp.exp(d[..., 3]) * ph.reshape(shp)
    off = 0.0 if box_normalized else 1.0
    return jnp.stack(
        [cx - w * 0.5, cy - h * 0.5, cx + w * 0.5 - off, cy + h * 0.5 - off], axis=-1
    )


@defop("roi_align", tensor_method=None)
def roi_align(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """ref ``roi_align_kernel``: bilinear-sampled ROI pooling. x [N,C,H,W]
    with N==1 (detection-head usage), boxes [R, 4]."""
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    C, H, W = x.shape[1:]
    feat = x[0]  # [C, H, W]
    off = 0.5 if aligned else 0.0
    ratio = 2 if sampling_ratio <= 0 else sampling_ratio

    def one_roi(box):
        bx1 = box[0] * spatial_scale - off
        by1 = box[1] * spatial_scale - off
        bw = jnp.maximum(box[2] * spatial_scale - off - bx1, 1e-3 if aligned else 1.0)
        bh = jnp.maximum(box[3] * spatial_scale - off - by1, 1e-3 if aligned else 1.0)
        cell_h, cell_w = bh / oh, bw / ow
        iy = jnp.arange(oh)[:, None, None, None]
        ix = jnp.arange(ow)[None, :, None, None]
        sy = jnp.arange(ratio)[None, None, :, None]
        sx = jnp.arange(ratio)[None, None, None, :]
        yy = by1 + (iy + (sy + 0.5) / ratio) * cell_h
        xx = bx1 + (ix + (sx + 0.5) / ratio) * cell_w

        def bilinear(yy, xx):
            y0 = jnp.clip(jnp.floor(yy), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, W - 1)
            y1 = jnp.clip(y0 + 1, 0, H - 1)
            x1 = jnp.clip(x0 + 1, 0, W - 1)
            ly, lx = yy - y0, xx - x0
            iy0, ix0, iy1, ix1 = (a.astype(jnp.int32) for a in (y0, x0, y1, x1))
            v = (
                feat[:, iy0, ix0] * ((1 - ly) * (1 - lx))
                + feat[:, iy0, ix1] * ((1 - ly) * lx)
                + feat[:, iy1, ix0] * (ly * (1 - lx))
                + feat[:, iy1, ix1] * (ly * lx)
            )
            return v

        vals = bilinear(yy, xx)  # [C, oh, ow, r, r]
        return vals.mean(axis=(-1, -2))

    return jax.vmap(one_roi)(boxes)  # [R, C, oh, ow]


@defop("unpool3d", tensor_method=None)
def unpool3d(x, indices, kernel_size=2, stride=None, padding=0, output_size=None,
             data_format="NCDHW"):
    N, C, D, H, W = x.shape
    if output_size is not None:
        Do, Ho, Wo = (int(s) for s in output_size[-3:])
    else:
        ks = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
        st = ks if stride is None else (stride if isinstance(stride, int) else stride[0])
        Do = (D - 1) * st - 2 * padding + ks
        Ho = (H - 1) * st - 2 * padding + ks
        Wo = (W - 1) * st - 2 * padding + ks
    flat = jnp.zeros((N, C, Do * Ho * Wo), x.dtype)
    out = jax.vmap(jax.vmap(lambda f, v, i: f.at[i].set(v)))(
        flat, x.reshape(N, C, -1), indices.reshape(N, C, -1).astype(jnp.int32)
    )
    return out.reshape(N, C, Do, Ho, Wo)


@defop("roi_pool", tensor_method=None)
def roi_pool(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0):
    """ref ``roi_pool_kernel``: hard max-pool over quantized ROI bins."""
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    C, H, W = x.shape[1:]
    feat = x[0]

    def one_roi(box):
        x1 = jnp.round(box[0] * spatial_scale)
        y1 = jnp.round(box[1] * spatial_scale)
        x2 = jnp.round(box[2] * spatial_scale)
        y2 = jnp.round(box[3] * spatial_scale)
        bh = jnp.maximum(y2 - y1 + 1, 1.0)
        bw = jnp.maximum(x2 - x1 + 1, 1.0)
        ys = jnp.arange(H, dtype=jnp.float32)
        xs = jnp.arange(W, dtype=jnp.float32)
        # bin index of each pixel (pixels outside the roi -> -1)
        by = jnp.floor((ys - y1) * oh / bh)
        bx = jnp.floor((xs - x1) * ow / bw)
        by = jnp.where((ys >= y1) & (ys <= y2), jnp.clip(by, 0, oh - 1), -1.0)
        bx = jnp.where((xs >= x1) & (xs <= x2), jnp.clip(bx, 0, ow - 1), -1.0)
        bin_id = by[:, None] * ow + bx[None, :]
        bin_id = jnp.where((by[:, None] >= 0) & (bx[None, :] >= 0), bin_id, oh * ow)
        one_hot = jax.nn.one_hot(bin_id.astype(jnp.int32), oh * ow + 1, dtype=x.dtype)
        neg = jnp.finfo(x.dtype).min
        masked = feat[:, :, :, None] * one_hot[None] + neg * (1.0 - one_hot[None])
        pooled = jnp.max(masked, axis=(1, 2))[:, : oh * ow]
        return jnp.where(pooled == neg, 0.0, pooled).reshape(C, oh, ow)

    return jax.vmap(one_roi)(boxes)


@defop("box_clip", tensor_method=None)
def box_clip(input, im_info):  # noqa: A002
    """ref ``box_clip_kernel``: clip boxes to image bounds [h, w, scale]."""
    h, w = im_info[..., 0] / im_info[..., 2], im_info[..., 1] / im_info[..., 2]
    x1 = jnp.clip(input[..., 0], 0, w - 1)
    y1 = jnp.clip(input[..., 1], 0, h - 1)
    x2 = jnp.clip(input[..., 2], 0, w - 1)
    y2 = jnp.clip(input[..., 3], 0, h - 1)
    return jnp.stack([x1, y1, x2, y2], axis=-1)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """ref ``prior_box_kernel``: SSD anchor generation — pure arithmetic."""
    feat = input._data if isinstance(input, Tensor) else jnp.asarray(input)
    img = image._data if isinstance(image, Tensor) else jnp.asarray(image)
    fh, fw = feat.shape[-2], feat.shape[-1]
    ih, iw = img.shape[-2], img.shape[-1]
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw
    ars = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - a) > 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    boxes = []
    for ms_i, ms in enumerate(min_sizes):
        sizes = [(float(ms) * math.sqrt(ar), float(ms) / math.sqrt(ar)) for ar in ars]
        if max_sizes:
            big = math.sqrt(float(ms) * float(max_sizes[ms_i]))
            sizes.insert(1, (big, big))
        boxes.extend(sizes)
    cy = (np.arange(fh) + offset) * step_h
    cx = (np.arange(fw) + offset) * step_w
    cyx = np.stack(np.meshgrid(cy, cx, indexing="ij"), axis=-1)  # [fh, fw, 2]
    out = np.zeros((fh, fw, len(boxes), 4), np.float32)
    for k, (bw, bh) in enumerate(boxes):
        out[..., k, 0] = (cyx[..., 1] - bw / 2) / iw
        out[..., k, 1] = (cyx[..., 0] - bh / 2) / ih
        out[..., k, 2] = (cyx[..., 1] + bw / 2) / iw
        out[..., k, 3] = (cyx[..., 0] + bh / 2) / ih
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32), out.shape).copy()
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(var))


@defop("matrix_nms", tensor_method=None)
def matrix_nms(bboxes, scores, score_threshold=0.05, post_threshold=0.0,
               nms_top_k=-1, keep_top_k=-1, use_gaussian=False, gaussian_sigma=2.0):
    """ref ``matrix_nms_kernel`` (SOLOv2): fully-parallel soft-NMS — the decay
    for each box is computed from the IoU matrix with no sequential loop, so
    it maps onto the TPU directly. Single-class form; returns decayed scores."""
    x1, y1, x2, y2 = bboxes[:, 0], bboxes[:, 1], bboxes[:, 2], bboxes[:, 3]
    areas = jnp.maximum(x2 - x1, 0.0) * jnp.maximum(y2 - y1, 0.0)
    order = jnp.argsort(-scores)
    b = bboxes[order]
    s = scores[order]
    a = areas[order]
    ix1 = jnp.maximum(b[:, None, 0], b[None, :, 0])
    iy1 = jnp.maximum(b[:, None, 1], b[None, :, 1])
    ix2 = jnp.minimum(b[:, None, 2], b[None, :, 2])
    iy2 = jnp.minimum(b[:, None, 3], b[None, :, 3])
    inter = jnp.maximum(ix2 - ix1, 0.0) * jnp.maximum(iy2 - iy1, 0.0)
    iou = inter / jnp.maximum(a[:, None] + a[None, :] - inter, 1e-10)
    lower = jnp.tril(jnp.ones_like(iou, dtype=bool), -1)  # j < i (higher score)
    iou = jnp.where(lower, iou, 0.0)
    # compensate_j: the IoU box j itself suffered from its own suppressors
    comp = jnp.max(iou, axis=1)
    if use_gaussian:
        ratio = jnp.exp(-(jnp.square(iou) - jnp.square(comp[None, :])) / gaussian_sigma)
    else:
        ratio = (1.0 - iou) / jnp.maximum(1.0 - comp[None, :], 1e-10)
    decay = jnp.min(jnp.where(lower, ratio, 1.0), axis=1)
    out = s * decay * (s > score_threshold)
    if post_threshold > 0:
        out = out * (out > post_threshold)
    return out, order


@defop("clip_by_norm", tensor_method=None)
def clip_by_norm(x, max_norm):
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return x * (max_norm / jnp.maximum(norm, max_norm))


def edit_distance(hyps, refs, hyps_length=None, refs_length=None, normalized=True,
                  ignored_tokens=None, name=None):
    """ref ``edit_distance_kernel``: Levenshtein DP — the row recurrence runs
    as a ``lax.scan`` over the reference sequence (static shapes)."""
    h = hyps._data if isinstance(hyps, Tensor) else jnp.asarray(hyps)
    r = refs._data if isinstance(refs, Tensor) else jnp.asarray(refs)
    hl = (hyps_length._data if isinstance(hyps_length, Tensor) else hyps_length)
    rl = (refs_length._data if isinstance(refs_length, Tensor) else refs_length)
    B, M = h.shape
    N = r.shape[1]
    hl = jnp.full((B,), M, jnp.int32) if hl is None else jnp.asarray(hl, jnp.int32).reshape(-1)
    rl = jnp.full((B,), N, jnp.int32) if rl is None else jnp.asarray(rl, jnp.int32).reshape(-1)

    def one(hrow, rrow, m, n):
        row0 = jnp.arange(M + 1, dtype=jnp.float32)
        big = jnp.float32(M + N + 1)
        row0 = jnp.where(jnp.arange(M + 1) <= m, row0, big)

        def step(prev, j):
            jn = j.astype(jnp.float32) + 1.0
            sub = prev[:-1] + (hrow != rrow[j]).astype(jnp.float32)
            dele = prev[1:] + 1.0

            def inner(carry, k):
                cur_k = jnp.minimum(jnp.minimum(sub[k], dele[k]), carry + 1.0)
                return cur_k, cur_k

            _, rest = jax.lax.scan(inner, jn, jnp.arange(M))
            cur = jnp.concatenate([jn[None], rest])
            cur = jnp.where(j < n, cur, prev)
            return cur, None

        last, _ = jax.lax.scan(step, row0, jnp.arange(N))
        return last[m]

    d = jax.vmap(one)(h, r, hl, rl)
    seq_num = jnp.asarray(B)
    if normalized:
        d = d / jnp.maximum(rl.astype(jnp.float32), 1.0)
    return Tensor(d.reshape(-1, 1)), Tensor(seq_num)


@defop("add_position_encoding", tensor_method=None)
def add_position_encoding(x, alpha=1.0, beta=1.0):
    """ref ``add_position_encoding_kernel``: sinusoidal PE added in place."""
    B, T, E = x.shape
    half = E // 2
    pos = jnp.arange(T, dtype=jnp.float32)[:, None]
    div = jnp.power(10000.0, jnp.arange(half, dtype=jnp.float32) / half)
    pe = jnp.concatenate([jnp.sin(pos / div), jnp.cos(pos / div)], axis=-1)
    return alpha * x + beta * pe[None, :, :E].astype(x.dtype)


def spectral_norm(weight, n_power_iterations=1, eps=1e-12, dim=0, name=None):
    """ref ``spectral_norm op``: W / sigma_max(W) via power iteration."""
    w = weight._data if isinstance(weight, Tensor) else jnp.asarray(weight)
    mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
    key = jax.random.PRNGKey(0)
    u = jax.random.normal(key, (mat.shape[0],), mat.dtype)
    for _ in range(max(1, int(n_power_iterations))):
        v = mat.T @ u
        v = v / jnp.maximum(jnp.linalg.norm(v), eps)
        u = mat @ v
        u = u / jnp.maximum(jnp.linalg.norm(u), eps)
    sigma = u @ mat @ v
    return Tensor(w / jnp.maximum(sigma, eps))


def bind_missing_tensor_methods() -> list:
    """Tensor-method parity (VERDICT r4 Weak #7: ``Tensor.unique`` absent
    while ``paddle.unique`` exists): bind module-level functions that the
    reference also exposes as Tensor methods. Called from
    ``paddle_tpu/__init__`` once all op modules are loaded; returns the list
    of names bound (the audit test asserts the full set is present)."""
    import paddle_tpu as _p

    bound = []
    for name in (
        "unique", "unique_consecutive", "nonzero", "median", "nanmedian",
        "kthvalue", "mode", "histogram", "bincount", "isin", "trace",
        "cumsum", "cumprod", "diff", "diag", "flatten", "roll", "rot90",
        "nan_to_num", "unbind", "masked_fill", "index_put",
    ):
        fn = getattr(_p, name, None)
        if fn is None or hasattr(Tensor, name):
            continue
        register_tensor_method(name, fn)
        bound.append(name)
    return bound
