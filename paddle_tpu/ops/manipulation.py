"""Shape / layout manipulation ops (reference ``python/paddle/tensor/manipulation.py``
over PHI kernels like ``concat``, ``split``, ``gather``, ``scatter``, ``pad``)."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.registry import defop

__all__ = [
    "reshape",
    "flatten",
    "squeeze",
    "unsqueeze",
    "concat",
    "stack",
    "split",
    "chunk",
    "tile",
    "expand",
    "expand_as",
    "broadcast_to",
    "broadcast_tensors",
    "flip",
    "roll",
    "gather",
    "gather_nd",
    "scatter",
    "scatter_nd_add",
    "index_select",
    "index_add",
    "index_put",
    "take_along_axis",
    "put_along_axis",
    "masked_select",
    "masked_fill",
    "unbind",
    "unstack",
    "repeat_interleave",
    "pad",
    "slice",
    "strided_slice",
    "crop",
    "unique",
    "unique_consecutive",
    "rot90",
    "as_strided",
    "view",
    "view_as",
    "moveaxis",
    "swapaxes",
    "atleast_1d",
    "atleast_2d",
    "atleast_3d",
    "tensor_split",
    "hsplit",
    "vsplit",
    "dsplit",
    "hstack",
    "vstack",
    "dstack",
    "column_stack",
    "row_stack",
    "shard_index",
]


def _norm_shape(shape: Any) -> Sequence[int]:
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy().reshape(-1))
    return tuple(int(s) for s in shape)


@defop("reshape", inplace_method="reshape_")
def reshape(x, shape):
    return jnp.reshape(x, _norm_shape(shape))


@defop("flatten")
def flatten(x, start_axis=0, stop_axis=-1):
    ndim = x.ndim
    start = start_axis % ndim if ndim else 0
    stop = stop_axis % ndim if ndim else 0
    new_shape = (
        tuple(x.shape[:start]) + (-1,) + tuple(x.shape[stop + 1 :]) if ndim else (-1,)
    )
    return jnp.reshape(x, new_shape)


@defop("squeeze", inplace_method="squeeze_")
def squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, (list, tuple)):
        axes = tuple(a % x.ndim for a in axis if x.shape[a % x.ndim] == 1)
        return jnp.squeeze(x, axis=axes) if axes else x
    axis = axis % x.ndim
    return jnp.squeeze(x, axis=axis) if x.shape[axis] == 1 else x


@defop("unsqueeze", inplace_method="unsqueeze_")
def unsqueeze(x, axis):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return jnp.expand_dims(x, tuple(axes))


@defop("concat")
def concat(x, axis=0):
    return jnp.concatenate(list(x), axis=int(axis))


@defop("stack")
def stack(x, axis=0):
    return jnp.stack(list(x), axis=axis)


@defop("split", tensor_method=None)
def _split_op(x, num_or_sections, axis=0):
    axis = int(axis)
    if isinstance(num_or_sections, int):
        return tuple(jnp.split(x, num_or_sections, axis=axis))
    sizes = list(num_or_sections)
    total = x.shape[axis]
    if -1 in sizes:
        known = sum(s for s in sizes if s != -1)
        sizes[sizes.index(-1)] = total - known
    offsets = np.cumsum(sizes)[:-1].tolist()
    return tuple(jnp.split(x, offsets, axis=axis))


def split(x, num_or_sections, axis=0, name=None):
    return list(_split_op(x, num_or_sections, axis=axis))


from paddle_tpu.core.tensor import register_tensor_method

register_tensor_method("split", split)


@defop("chunk", tensor_method=None)
def _chunk_op(x, chunks, axis=0):
    return tuple(jnp.array_split(x, chunks, axis=int(axis)))


def chunk(x, chunks, axis=0, name=None):
    return list(_chunk_op(x, chunks, axis=axis))


register_tensor_method("chunk", chunk)


@defop("tile")
def tile(x, repeat_times):
    return jnp.tile(x, _norm_shape(repeat_times))


@defop("expand")
def expand(x, shape):
    shape = list(_norm_shape(shape))
    # paddle semantics: -1 means keep that dim
    offset = len(shape) - x.ndim
    for i, s in enumerate(shape):
        if s == -1:
            shape[i] = x.shape[i - offset]
    return jnp.broadcast_to(x, tuple(shape))


@defop("expand_as")
def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


@defop("broadcast_to")
def broadcast_to(x, shape):
    return jnp.broadcast_to(x, _norm_shape(shape))


def broadcast_tensors(inputs, name=None):
    arrays = [t._data if isinstance(t, Tensor) else jnp.asarray(t) for t in inputs]
    shape = jnp.broadcast_shapes(*[a.shape for a in arrays])
    return [Tensor(jnp.broadcast_to(a, shape)) for a in arrays]


@defop("flip")
def flip(x, axis):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return jnp.flip(x, axis=tuple(axes))


@defop("roll")
def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


@defop("gather")
def gather(x, index, axis=0):
    idx = index.reshape(-1) if index.ndim > 1 else index
    return jnp.take(x, idx, axis=int(axis))


@defop("gather_nd")
def gather_nd(x, index):
    index_depth = index.shape[-1]
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx] if index_depth == x.ndim else x[idx]


@defop("scatter")
def scatter(x, index, updates, overwrite=True):
    idx = index.reshape(-1)
    if overwrite:
        return x.at[idx].set(updates)
    # accumulate-mode: zero out target rows then add
    zeroed = x.at[idx].set(jnp.zeros_like(updates))
    return zeroed.at[idx].add(updates)


@defop("scatter_nd_add")
def scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


@defop("index_select")
def index_select(x, index, axis=0):
    return jnp.take(x, index.reshape(-1), axis=int(axis))


@defop("index_add")
def index_add(x, index, axis, value):
    moved = jnp.moveaxis(x, axis, 0)
    moved = moved.at[index].add(jnp.moveaxis(value, axis, 0))
    return jnp.moveaxis(moved, 0, axis)


@defop("index_put")
def index_put(x, indices, value, accumulate=False):
    idx = tuple(indices)
    if accumulate:
        return x.at[idx].add(value)
    return x.at[idx].set(value)


@defop("take_along_axis")
def take_along_axis(x, indices, axis, broadcast=True):
    return jnp.take_along_axis(x, indices, axis=int(axis))


@defop("put_along_axis")
def put_along_axis(x, indices, values, axis, reduce="assign"):  # noqa: A002
    if reduce == "assign":
        return jnp.put_along_axis(x, indices, values, axis=int(axis), inplace=False)
    if reduce in ("add", "sum"):
        # scatter-add along axis
        moved = jnp.moveaxis(x, axis, -1)
        idx = jnp.moveaxis(jnp.broadcast_to(indices, x.shape), axis, -1)
        vals = jnp.moveaxis(jnp.broadcast_to(values, x.shape), axis, -1)
        flat = moved.reshape(-1, moved.shape[-1])
        fidx = idx.reshape(-1, idx.shape[-1])
        fval = vals.reshape(-1, vals.shape[-1])
        rows = jnp.arange(flat.shape[0])[:, None]
        out = flat.at[rows, fidx].add(fval)
        return jnp.moveaxis(out.reshape(moved.shape), -1, axis)
    if reduce in ("mul", "multiply"):
        moved = jnp.moveaxis(x, axis, -1)
        idx = jnp.moveaxis(jnp.broadcast_to(indices, x.shape), axis, -1)
        vals = jnp.moveaxis(jnp.broadcast_to(values, x.shape), axis, -1)
        flat = moved.reshape(-1, moved.shape[-1])
        fidx = idx.reshape(-1, idx.shape[-1])
        fval = vals.reshape(-1, vals.shape[-1])
        rows = jnp.arange(flat.shape[0])[:, None]
        out = flat.at[rows, fidx].multiply(fval)
        return jnp.moveaxis(out.reshape(moved.shape), -1, axis)
    raise ValueError(f"unsupported reduce mode {reduce!r}")


@defop("masked_select")
def masked_select(x, mask):
    # Dynamic output shape: eager-only (cannot be jitted) — same restriction
    # class as the reference's dynamic-shape ops under CINN.
    return x[mask]


@defop("masked_fill", inplace_method="masked_fill_")
def masked_fill(x, mask, value):
    v = value if not hasattr(value, "dtype") else value.astype(x.dtype)
    return jnp.where(mask, jnp.asarray(v, x.dtype), x)


@defop("unbind", tensor_method=None)
def _unbind_op(x, axis=0):
    axis = int(axis)
    moved = jnp.moveaxis(x, axis, 0)
    return tuple(moved[i] for i in range(moved.shape[0]))


def unbind(x, axis=0):
    return list(_unbind_op(x, axis=axis))


register_tensor_method("unbind", unbind)


def unstack(x, axis=0, num=None):
    return unbind(x, axis=axis)


register_tensor_method("unstack", unstack)


@defop("repeat_interleave")
def repeat_interleave(x, repeats, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    r = repeats._data if isinstance(repeats, Tensor) else repeats
    return jnp.repeat(x, r, axis=int(axis))


@defop("pad")
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):  # noqa: A002
    pad = list(_norm_shape(pad)) if not isinstance(pad, (list, tuple)) else list(pad)
    if len(pad) == 2 * x.ndim:
        # full-form [before0, after0, before1, after1, ...]? paddle uses per-dim pairs
        width = [(pad[2 * i], pad[2 * i + 1]) for i in range(x.ndim)]
    else:
        # partial form pads the trailing dims (paddle NCHW convention pads spatial dims)
        n_spatial = len(pad) // 2
        width = [(0, 0)] * (x.ndim - n_spatial) + [
            (pad[2 * i], pad[2 * i + 1]) for i in range(n_spatial)
        ]
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, width, mode=jmode, constant_values=value)
    return jnp.pad(x, width, mode=jmode)


@defop("slice", tensor_method=None)
def slice(x, axes, starts, ends):  # noqa: A001
    idx = [jnp.s_[:]] * x.ndim
    for ax, s, e in zip(axes, starts, ends):
        idx[ax] = jnp.s_[int(s) : int(e)]
    return x[tuple(idx)]


@defop("strided_slice", tensor_method=None)
def strided_slice(x, axes, starts, ends, strides):
    idx = [jnp.s_[:]] * x.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        idx[ax] = jnp.s_[int(s) : int(e) : int(st)]
    return x[tuple(idx)]


@defop("crop")
def crop(x, shape=None, offsets=None):
    shape = _norm_shape(shape)
    offsets = _norm_shape(offsets) if offsets is not None else [0] * x.ndim
    idx = tuple(jnp.s_[o : o + s] for o, s in zip(offsets, shape))
    return x[idx]


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    """Eager-only (dynamic shape)."""
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    res = np.unique(
        arr, return_index=return_index, return_inverse=return_inverse, return_counts=return_counts, axis=axis
    )
    if not isinstance(res, tuple):
        return Tensor(res)
    return tuple(Tensor(r) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, name=None):
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    if axis is None:
        arr = arr.reshape(-1)
        axis = 0
    keep = np.ones(arr.shape[axis], dtype=bool)
    moved = np.moveaxis(arr, axis, 0)
    keep[1:] = np.any(
        moved[1:].reshape(moved.shape[0] - 1, -1) != moved[:-1].reshape(moved.shape[0] - 1, -1),
        axis=1,
    )
    out = np.moveaxis(moved[keep], 0, axis)
    results = [Tensor(out)]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        results.append(Tensor(inv.astype(np.int64)))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, arr.shape[axis]))
        results.append(Tensor(counts.astype(np.int64)))
    return results[0] if len(results) == 1 else tuple(results)


@defop("rot90")
def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=tuple(axes))


@defop("as_strided", tensor_method=None)
def as_strided(x, shape, stride, offset=0):
    # Layout is XLA-owned; emulate with gather over computed indices.
    flat = x.reshape(-1)
    indices = jnp.zeros(tuple(shape), jnp.int32) + offset
    for d, (s, st) in enumerate(zip(shape, stride)):
        r = jnp.arange(s) * st
        r = r.reshape([-1 if i == d else 1 for i in range(len(shape))])
        indices = indices + r
    return flat[indices.reshape(-1)].reshape(tuple(shape))


@defop("view", tensor_method=None)
def view(x, shape_or_dtype):
    if isinstance(shape_or_dtype, (list, tuple)):
        return jnp.reshape(x, tuple(shape_or_dtype))
    from paddle_tpu.core.dtypes import convert_dtype

    return x.view(convert_dtype(shape_or_dtype)) if hasattr(x, "view") else x.astype(shape_or_dtype)


@defop("view_as", tensor_method=None)
def view_as(x, other):
    return jnp.reshape(x, other.shape)


@defop("moveaxis")
def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


@defop("swapaxes")
def swapaxes(x, axis0, axis1):
    return jnp.swapaxes(x, axis0, axis1)


@defop("atleast_1d", tensor_method=None)
def atleast_1d(x):
    return jnp.atleast_1d(x)


@defop("atleast_2d", tensor_method=None)
def atleast_2d(x):
    return jnp.atleast_2d(x)


@defop("atleast_3d", tensor_method=None)
def atleast_3d(x):
    return jnp.atleast_3d(x)


def tensor_split(x, num_or_indices, axis=0, name=None):
    if isinstance(num_or_indices, int):
        return [Tensor(a) for a in jnp.array_split(x._data, num_or_indices, axis=axis)]
    return [Tensor(a) for a in jnp.split(x._data, list(num_or_indices), axis=axis)]


def hsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


@defop("hstack", tensor_method=None)
def hstack(x):
    return jnp.hstack(list(x))


@defop("vstack", tensor_method=None)
def vstack(x):
    return jnp.vstack(list(x))


@defop("dstack", tensor_method=None)
def dstack(x):
    return jnp.dstack(list(x))


@defop("column_stack", tensor_method=None)
def column_stack(x):
    return jnp.column_stack(list(x))


row_stack = vstack


@defop("shard_index")
def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):  # noqa: A002
    """Map global ids to shard-local ids (reference ``ops.yaml`` shard_index,
    used by distributed embedding)."""
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (input // shard_size) == shard_id
    return jnp.where(in_shard, input % shard_size, ignore_value)
