"""Logical & bitwise ops (reference ``python/paddle/tensor/logic.py``, ``math.py`` bitwise)."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.ops.registry import defop

__all__ = [
    "logical_and",
    "logical_or",
    "logical_xor",
    "logical_not",
    "bitwise_and",
    "bitwise_or",
    "bitwise_xor",
    "bitwise_not",
    "bitwise_left_shift",
    "bitwise_right_shift",
]


@defop("logical_and")
def logical_and(x, y):
    return jnp.logical_and(x, y)


@defop("logical_or")
def logical_or(x, y):
    return jnp.logical_or(x, y)


@defop("logical_xor")
def logical_xor(x, y):
    return jnp.logical_xor(x, y)


@defop("logical_not")
def logical_not(x):
    return jnp.logical_not(x)


@defop("bitwise_and")
def bitwise_and(x, y):
    return jnp.bitwise_and(x, y)


@defop("bitwise_or")
def bitwise_or(x, y):
    return jnp.bitwise_or(x, y)


@defop("bitwise_xor")
def bitwise_xor(x, y):
    return jnp.bitwise_xor(x, y)


@defop("bitwise_not")
def bitwise_not(x):
    return jnp.bitwise_not(x)


@defop("bitwise_left_shift")
def bitwise_left_shift(x, y):
    return jnp.left_shift(x, y)


@defop("bitwise_right_shift")
def bitwise_right_shift(x, y):
    return jnp.right_shift(x, y)
