"""Random sampling ops.

Counterpart of the reference's RNG kernels (``paddle/phi/kernels/*/uniform_*``,
``gaussian_*``; ``phi::Generator`` seeds). Each call draws a fresh subkey from
the global :class:`~paddle_tpu.core.rng.Generator` — stateful-API surface over
JAX's splittable PRNG.

Note: under ``paddle_tpu.jit`` capture, keys are materialized at trace time, so
a traced program replays the same draw; use eager mode (or functional dropout
with explicit seeds) when fresh per-step randomness is required inside a
compiled step. Training dropout handles this via seed plumbing in
``nn.functional.dropout``.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import rng as _rng
from paddle_tpu.core.dtypes import convert_dtype
from paddle_tpu.core.tensor import Tensor, register_tensor_method
from paddle_tpu.ops.registry import defop

__all__ = [
    "uniform",
    "normal",
    "standard_normal",
    "randn",
    "rand",
    "randint",
    "randint_like",
    "randperm",
    "bernoulli",
    "multinomial",
    "poisson",
    "exponential_",
    "normal_",
    "uniform_",
]


def _shape(shape: Any) -> Sequence[int]:
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy().reshape(-1))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def uniform(shape, dtype="float32", min=-1.0, max=1.0, seed=0, name=None):  # noqa: A002
    key = jax.random.PRNGKey(seed) if seed else _rng.next_key()
    return Tensor(
        jax.random.uniform(key, _shape(shape), convert_dtype(dtype), minval=min, maxval=max)
    )


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        out_shape = jnp.broadcast_shapes(
            getattr(m, "shape", ()), getattr(s, "shape", ())
        )
        noise = jax.random.normal(_rng.next_key(), out_shape, jnp.float32)
        return Tensor(m + s * noise)
    if shape is None:
        shape = [1]
    noise = jax.random.normal(_rng.next_key(), _shape(shape), jnp.float32)
    return Tensor(mean + std * noise)


def standard_normal(shape, dtype="float32", name=None):
    return Tensor(jax.random.normal(_rng.next_key(), _shape(shape), convert_dtype(dtype)))


def randn(shape, dtype="float32", name=None):
    return standard_normal(shape, dtype)


def rand(shape, dtype="float32", name=None):
    return Tensor(jax.random.uniform(_rng.next_key(), _shape(shape), convert_dtype(dtype)))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(
        jax.random.randint(_rng.next_key(), _shape(shape), low, high, convert_dtype(dtype))
    )


def randint_like(x, low=0, high=None, dtype=None, name=None):
    return randint(low, high, shape=x.shape, dtype=dtype or x.dtype)


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(_rng.next_key(), int(n)).astype(convert_dtype(dtype)))


def bernoulli(x, name=None):
    data = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    draw = jax.random.uniform(_rng.next_key(), data.shape, jnp.float32)
    return Tensor((draw < data).astype(data.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    data = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    key = _rng.next_key()
    logits = jnp.log(jnp.maximum(data, 1e-30))
    if replacement:
        out = jax.random.categorical(key, logits, axis=-1, shape=(*data.shape[:-1], num_samples) if data.ndim > 1 else (num_samples,))
        if data.ndim > 1:
            out = out.reshape(*data.shape[:-1], num_samples)
        return Tensor(out.astype(jnp.int64))
    # without replacement: Gumbel top-k trick
    g = jax.random.gumbel(key, data.shape)
    _, idx = jax.lax.top_k(logits + g, num_samples)
    return Tensor(idx.astype(jnp.int64))


def poisson(x, name=None):
    data = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.poisson(_rng.next_key(), data).astype(data.dtype))


# -- in-place random initializers (used by nn.initializer) --------------------
def normal_(x: Tensor, mean=0.0, std=1.0) -> Tensor:
    x.set_value(mean + std * jax.random.normal(_rng.next_key(), tuple(x.shape), jnp.float32))
    return x


def uniform_(x: Tensor, min=-1.0, max=1.0) -> Tensor:  # noqa: A002
    x.set_value(jax.random.uniform(_rng.next_key(), tuple(x.shape), jnp.float32, minval=min, maxval=max))
    return x


def exponential_(x: Tensor, lam=1.0) -> Tensor:
    x.set_value(jax.random.exponential(_rng.next_key(), tuple(x.shape)) / lam)
    return x


register_tensor_method("normal_", normal_)
register_tensor_method("uniform_", uniform_)
register_tensor_method("exponential_", exponential_)
