"""Reduction ops (reference ``paddle/phi/kernels/*/reduce_*`` + ``python/paddle/tensor/math.py`` reductions)."""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import jax.numpy as jnp

from paddle_tpu.core.dtypes import convert_dtype
from paddle_tpu.ops.registry import defop

__all__ = [
    "sum",
    "mean",
    "max",
    "min",
    "amax",
    "amin",
    "prod",
    "all",
    "any",
    "logsumexp",
    "nansum",
    "nanmean",
    "std",
    "var",
    "median",
    "nanmedian",
    "quantile",
    "count_nonzero",
    "numel",
]


def _axis(axis: Any) -> Any:
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


@defop("sum")
def sum(x, axis=None, dtype=None, keepdim=False):  # noqa: A001
    dt = convert_dtype(dtype) if dtype else None
    if dt is None and jnp.issubdtype(jnp.dtype(x.dtype), jnp.bool_):
        dt = jnp.int64
    return jnp.sum(x, axis=_axis(axis), dtype=dt, keepdims=keepdim)


@defop("mean")
def mean(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=_axis(axis), keepdims=keepdim)


@defop("max")
def max(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.max(x, axis=_axis(axis), keepdims=keepdim)


@defop("min")
def min(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.min(x, axis=_axis(axis), keepdims=keepdim)


@defop("amax")
def amax(x, axis=None, keepdim=False):
    return jnp.max(x, axis=_axis(axis), keepdims=keepdim)


@defop("amin")
def amin(x, axis=None, keepdim=False):
    return jnp.min(x, axis=_axis(axis), keepdims=keepdim)


@defop("prod")
def prod(x, axis=None, keepdim=False, dtype=None):
    dt = convert_dtype(dtype) if dtype else None
    return jnp.prod(x, axis=_axis(axis), dtype=dt, keepdims=keepdim)


@defop("all")
def all(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.all(x, axis=_axis(axis), keepdims=keepdim)


@defop("any")
def any(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.any(x, axis=_axis(axis), keepdims=keepdim)


@defop("logsumexp")
def logsumexp(x, axis=None, keepdim=False):
    import jax

    return jax.scipy.special.logsumexp(x, axis=_axis(axis), keepdims=keepdim)


@defop("nansum")
def nansum(x, axis=None, dtype=None, keepdim=False):
    dt = convert_dtype(dtype) if dtype else None
    return jnp.nansum(x, axis=_axis(axis), dtype=dt, keepdims=keepdim)


@defop("nanmean")
def nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=_axis(axis), keepdims=keepdim)


@defop("std")
def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim)


@defop("var")
def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim)


@defop("median")
def median(x, axis=None, keepdim=False):
    return jnp.median(x, axis=_axis(axis), keepdims=keepdim)


@defop("nanmedian")
def nanmedian(x, axis=None, keepdim=False):
    return jnp.nanmedian(x, axis=_axis(axis), keepdims=keepdim)


@defop("quantile")
def quantile(x, q, axis=None, keepdim=False):
    return jnp.quantile(x, jnp.asarray(q), axis=_axis(axis), keepdims=keepdim)


@defop("count_nonzero")
def count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=_axis(axis), keepdims=keepdim)


@defop("numel")
def numel(x):
    import numpy as np

    return jnp.asarray(int(np.prod(x.shape)) if x.shape else 1, jnp.int64)
