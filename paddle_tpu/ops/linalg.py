"""Linear algebra ops (reference ``python/paddle/tensor/linalg.py`` over PHI
matmul/blas kernels — on TPU these are MXU-native via XLA dot_general)."""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import register_tensor_method
from paddle_tpu.ops.registry import defop

__all__ = [
    "matmul",
    "mm",
    "bmm",
    "mv",
    "dot",
    "t",
    "transpose",
    "norm",
    "dist",
    "cross",
    "einsum",
    "histogram",
    "bincount",
    "cholesky",
    "qr",
    "svd",
    "inv",
    "pinv",
    "solve",
    "triangular_solve",
    "lstsq",
    "cholesky_solve",
    "det",
    "slogdet",
    "matrix_power",
    "matrix_rank",
    "eig",
    "eigh",
    "eigvals",
    "eigvalsh",
    "lu",
    "multi_dot",
    "cond",
    "corrcoef",
    "cov",
    "trace",
    "diagonal",
]


@defop("matmul")
def matmul(x, y, transpose_x=False, transpose_y=False):
    """MXU matmul. The reference dispatches to cuBLAS
    (``paddle/phi/kernels/impl/matmul_kernel_impl.h``); here XLA ``dot_general``
    tiles directly onto the systolic array."""
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


@defop("mm")
def mm(input, mat2):  # noqa: A002
    return jnp.matmul(input, mat2)


@defop("bmm")
def bmm(x, y):
    return jnp.matmul(x, y)


@defop("mv")
def mv(x, vec):
    return jnp.matmul(x, vec)


@defop("dot")
def dot(x, y):
    return jnp.sum(x * y, axis=-1)


@defop("t")
def t(input):  # noqa: A002
    if input.ndim < 2:
        return input
    return input.T


@defop("transpose")
def transpose(x, perm):
    return jnp.transpose(x, tuple(perm))


@defop("norm", tensor_method=None)
def _norm_op(x, p="fro", axis=None, keepdim=False):
    if axis is None and p in ("fro", 2):
        return jnp.sqrt(jnp.sum(jnp.square(x)))
    if p == "fro":
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdim))
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    pv = float(p)
    return jnp.power(jnp.sum(jnp.power(jnp.abs(x), pv), axis=axis, keepdims=keepdim), 1.0 / pv)


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return _norm_op(x, p=p, axis=axis, keepdim=keepdim)


register_tensor_method("norm", norm)


@defop("dist", tensor_method=None)
def _dist_op(x, y, p=2.0):
    d = x - y
    if p == float("inf"):
        return jnp.max(jnp.abs(d))
    if p == float("-inf"):
        return jnp.min(jnp.abs(d))
    if p == 0:
        return jnp.sum((d != 0).astype(d.dtype))
    return jnp.power(jnp.sum(jnp.power(jnp.abs(d), p)), 1.0 / p)


def dist(x, y, p=2.0, name=None):
    return _dist_op(x, y, p=float(p))


register_tensor_method("dist", dist)


@defop("cross")
def cross(x, y, axis=-1):
    return jnp.cross(x, y, axis=axis)


@defop("einsum", tensor_method=None)
def _einsum_op(equation, *operands):
    return jnp.einsum(equation, *operands)


def einsum(equation, *operands):
    return _einsum_op(equation, *operands)


@defop("histogram", tensor_method=None)
def _histogram_op(input, bins=100, min=0, max=0, weight=None, density=False):  # noqa: A002
    lo, hi = (min, max) if (min != 0 or max != 0) else (jnp.min(input), jnp.max(input))
    hist, _ = jnp.histogram(
        input.reshape(-1), bins=bins, range=(lo, hi), weights=weight, density=density
    )
    return hist


def histogram(input, bins=100, min=0, max=0, weight=None, density=False, name=None):  # noqa: A002
    return _histogram_op(input, bins=bins, min=min, max=max, weight=weight, density=density)


register_tensor_method("histogram", histogram)


@defop("cholesky")
def cholesky(x, upper=False):
    l = jnp.linalg.cholesky(x)
    return jnp.swapaxes(l, -1, -2) if upper else l


@defop("qr", tensor_method=None)
def _qr_op(x, mode="reduced"):
    return jnp.linalg.qr(x, mode=mode)


def qr(x, mode="reduced", name=None):
    return _qr_op(x, mode=mode)


register_tensor_method("qr", qr)


@defop("svd", tensor_method=None)
def _svd_op(x, full_matrices=False):
    u, s, vh = jnp.linalg.svd(x, full_matrices=full_matrices)
    return u, s, jnp.swapaxes(vh, -1, -2)  # paddle returns V, not V^H


def svd(x, full_matrices=False, name=None):
    return _svd_op(x, full_matrices=full_matrices)


register_tensor_method("svd", svd)


@defop("inv")
def inv(x):
    return jnp.linalg.inv(x)


@defop("pinv")
def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@defop("solve")
def solve(x, y):
    return jnp.linalg.solve(x, y)


@defop("triangular_solve")
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular
    )


@defop("cholesky_solve")
def cholesky_solve(x, y, upper=False):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


@defop("det")
def det(x):
    return jnp.linalg.det(x)


@defop("slogdet", tensor_method=None)
def _slogdet_op(x):
    sign, logdet = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logdet])


def slogdet(x, name=None):
    return _slogdet_op(x)


register_tensor_method("slogdet", slogdet)


@defop("matrix_power")
def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


@defop("matrix_rank")
def matrix_rank(x, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(x, rtol=tol)


@defop("eig", tensor_method=None)
def _eig_op(x):
    return jnp.linalg.eig(x)


def eig(x, name=None):
    return _eig_op(x)


@defop("eigh", tensor_method=None)
def _eigh_op(x, UPLO="L"):  # noqa: N803
    return jnp.linalg.eigh(x, UPLO=UPLO)


def eigh(x, UPLO="L", name=None):  # noqa: N803
    return _eigh_op(x, UPLO=UPLO)


@defop("eigvals")
def eigvals(x):
    return jnp.linalg.eigvals(x)


@defop("eigvalsh")
def eigvalsh(x, UPLO="L"):  # noqa: N803
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@defop("lu", tensor_method=None)
def _lu_op(x, pivot=True):
    lu_, piv = jax.scipy.linalg.lu_factor(x)
    return lu_, piv.astype(jnp.int32) + 1  # paddle returns 1-based pivots


def lu(x, pivot=True, get_infos=False, name=None):
    res = _lu_op(x, pivot=pivot)
    if get_infos:
        from paddle_tpu.ops.creation import zeros

        return res[0], res[1], zeros([1], "int32")
    return res


register_tensor_method("lu", lu)


@defop("multi_dot", tensor_method=None)
def _multi_dot_op(x):
    return jnp.linalg.multi_dot(list(x))


def multi_dot(x, name=None):
    return _multi_dot_op(x)


@defop("cond", tensor_method=None)
def _cond_op(x, p=None):
    return jnp.linalg.cond(x, p=p)


def cond(x, p=None, name=None):
    return _cond_op(x, p=p)


@defop("corrcoef")
def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


@defop("cov")
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0, fweights=fweights, aweights=aweights)


@defop("trace")
def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@defop("diagonal")
def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


def bincount(x, weights=None, minlength=0, name=None):
    """Count occurrences of each value (reference ``ops.yaml`` bincount).
    Output length is value-dependent (max(x)+1), so it is eager-only like
    ``unique``; integer counts record no tape. Negative values raise, like
    the reference."""
    from paddle_tpu.core.tensor import Tensor

    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    n = int(arr.size)
    if n and int(jnp.min(arr)) < 0:
        raise ValueError("bincount: input must be non-negative")
    length = int(minlength) if n == 0 else max(int(jnp.max(arr)) + 1, int(minlength))
    w = None
    if weights is not None:
        w = weights._data if isinstance(weights, Tensor) else jnp.asarray(weights)
        w = w.reshape(-1)
    return Tensor(jnp.bincount(arr.reshape(-1), weights=w, length=length))


register_tensor_method("bincount", bincount)


def lstsq(x, y, rcond=None, driver=None, name=None):
    """Least-squares solve (reference ``linalg.lstsq``): returns
    (solution, residuals, rank, singular_values). Residuals are empty for
    underdetermined systems (m <= n), matching numpy/the reference; for tall
    rank-deficient systems (data-dependent rank < n, which static shapes
    cannot express) the computed residual vector is returned instead of the
    reference's empty tensor."""
    from paddle_tpu.core.dispatch import call_op

    if driver not in (None, "gels", "gelsy", "gelsd", "gelss"):
        raise ValueError(f"unknown lstsq driver {driver!r}")

    m = x.shape[-2]
    n = x.shape[-1]

    def fn(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        if m <= n:
            res = jnp.zeros((0,), sol.dtype)
        return sol, res, rank.astype(jnp.int32), sv

    return call_op("lstsq", fn, x, y)


register_tensor_method("lstsq", lstsq)
