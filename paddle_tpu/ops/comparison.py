"""Comparison ops (reference ``python/paddle/tensor/logic.py`` comparison family)."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.ops.registry import defop

__all__ = [
    "equal",
    "not_equal",
    "less_than",
    "less_equal",
    "greater_than",
    "greater_equal",
    "equal_all",
    "allclose",
    "isclose",
]


@defop("equal")
def equal(x, y):
    return jnp.equal(x, y)


@defop("not_equal")
def not_equal(x, y):
    return jnp.not_equal(x, y)


@defop("less_than")
def less_than(x, y):
    return jnp.less(x, y)


@defop("less_equal")
def less_equal(x, y):
    return jnp.less_equal(x, y)


@defop("greater_than")
def greater_than(x, y):
    return jnp.greater(x, y)


@defop("greater_equal")
def greater_equal(x, y):
    return jnp.greater_equal(x, y)


@defop("equal_all")
def equal_all(x, y):
    return jnp.array_equal(x, y)


@defop("allclose")
def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@defop("isclose")
def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)
