"""Elementwise & scalar math ops.

Counterpart of the reference's elementwise/activation PHI kernels
(``paddle/phi/kernels/*/elementwise_*``, ``activation_kernel.*``; declared in
``paddle/phi/ops/yaml/ops.yaml``). Every op lowers to jnp/lax and fuses under
XLA — there is no hand-written kernel needed for elementwise math on TPU.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from paddle_tpu.ops.registry import defop

__all__ = []  # populated below


def _export(name: str) -> None:
    __all__.append(name)


def _unary(name: str, jfn, method: Optional[str] = None, inplace: Optional[str] = None):
    op = defop(name, tensor_method=method or name, inplace_method=inplace)(lambda x: jfn(x))
    globals()[name] = op
    _export(name)
    return op


def _binary(name: str, jfn, method: Optional[str] = None, inplace: Optional[str] = None):
    def fn(x, y):
        return jfn(x, y)

    fn.__name__ = name
    op = defop(name, tensor_method=method or name, inplace_method=inplace)(fn)
    globals()[name] = op
    _export(name)
    return op


# ---- unary ------------------------------------------------------------------
_unary("abs", jnp.abs, inplace="abs_")
_unary("acos", jnp.arccos)
_unary("acosh", jnp.arccosh)
_unary("asin", jnp.arcsin)
_unary("asinh", jnp.arcsinh)
_unary("atan", jnp.arctan)
_unary("atanh", jnp.arctanh)
_unary("ceil", jnp.ceil, inplace="ceil_")
_unary("conj", jnp.conj)
_unary("cos", jnp.cos)
_unary("cosh", jnp.cosh)
_unary("digamma", jax.scipy.special.digamma)
_unary("erf", jax.scipy.special.erf)
_unary("erfinv", jax.scipy.special.erfinv)
_unary("exp", jnp.exp, inplace="exp_")
_unary("expm1", jnp.expm1)
_unary("floor", jnp.floor, inplace="floor_")
_unary("frac", lambda x: x - jnp.trunc(x))
_unary("imag", jnp.imag)
_unary("lgamma", jax.scipy.special.gammaln)
_unary("log", jnp.log)
_unary("log10", jnp.log10)
_unary("log1p", jnp.log1p)
_unary("log2", jnp.log2)
_unary("logit", jax.scipy.special.logit)
_unary("neg", jnp.negative)
_unary("real", jnp.real)
_unary("reciprocal", jnp.reciprocal, inplace="reciprocal_")
_unary("round", jnp.round, inplace="round_")
_unary("rsqrt", jax.lax.rsqrt, inplace="rsqrt_")
_unary("sigmoid", jax.nn.sigmoid)
_unary("sign", jnp.sign)
_unary("sin", jnp.sin)
_unary("sinh", jnp.sinh)
_unary("sqrt", jnp.sqrt, inplace="sqrt_")
_unary("square", jnp.square)
_unary("tan", jnp.tan)
_unary("tanh", jnp.tanh, inplace="tanh_")
_unary("trunc", jnp.trunc)
_unary("isfinite", jnp.isfinite)
_unary("isinf", jnp.isinf)
_unary("isnan", jnp.isnan)
_unary("i0", lambda x: jax.scipy.special.i0(x))

# ---- binary -----------------------------------------------------------------
_binary("add", jnp.add, inplace="add_")
_binary("subtract", jnp.subtract, inplace="subtract_")
_binary("multiply", jnp.multiply, inplace="multiply_")
_binary("divide", jnp.true_divide, inplace="divide_")
_binary("floor_divide", jnp.floor_divide)
_binary("remainder", jnp.remainder, inplace="remainder_")
_binary("mod", jnp.remainder, method="mod")
_binary("pow", jnp.power, method="pow")
_binary("maximum", jnp.maximum)
_binary("minimum", jnp.minimum)
_binary("fmax", jnp.fmax)
_binary("fmin", jnp.fmin)
_binary("atan2", jnp.arctan2)
_binary("logaddexp", jnp.logaddexp)
_binary("heaviside", jnp.heaviside)
_binary("gcd", jnp.gcd)
_binary("lcm", jnp.lcm)
_binary("nextafter", jnp.nextafter)
_binary("hypot", jnp.hypot)
_binary("copysign", jnp.copysign)
_binary("ldexp", jnp.ldexp)
_binary("inner", jnp.inner)
_binary("outer", jnp.outer)
_binary("kron", jnp.kron)


# ---- composite / parameterized ---------------------------------------------
@defop("scale", inplace_method="scale_")
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
    """y = scale*x + bias (reference ``ops.yaml`` scale op)."""
    if bias_after_scale:
        out = x * jnp.asarray(scale, x.dtype) + jnp.asarray(bias, x.dtype)
    else:
        out = (x + jnp.asarray(bias, x.dtype)) * jnp.asarray(scale, x.dtype)
    return out


_export("scale")


@defop("clip", inplace_method="clip_")
def clip(x, min=None, max=None):  # noqa: A002
    return jnp.clip(x, min, max)


_export("clip")


@defop("lerp")
def lerp(x, y, weight):
    return x + weight * (y - x)


_export("lerp")


@defop("stanh")
def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


_export("stanh")


@defop("multiplex")
def multiplex(inputs, index):
    stacked = jnp.stack(inputs, axis=0)  # [n, batch, ...]
    idx = index.reshape(-1)
    return stacked[idx, jnp.arange(idx.shape[0])]


_export("multiplex")


@defop("add_n")
def add_n(inputs):
    """Sum a list of tensors (reference ``sum`` / add_n op)."""
    out = inputs[0]
    for t in inputs[1:]:
        out = out + t
    return out


_export("add_n")


@defop("addmm")
def addmm(input, x, y, beta=1.0, alpha=1.0):  # noqa: A002
    return beta * input + alpha * (x @ y)


_export("addmm")


@defop("cumsum")
def cumsum(x, axis=None, dtype=None):
    from paddle_tpu.core.dtypes import convert_dtype

    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jnp.cumsum(x, axis=axis, dtype=convert_dtype(dtype) if dtype else None)


_export("cumsum")


@defop("cumprod")
def cumprod(x, dim=None, dtype=None):
    from paddle_tpu.core.dtypes import convert_dtype

    if dim is None:
        x = x.reshape(-1)
        dim = 0
    return jnp.cumprod(x, axis=dim, dtype=convert_dtype(dtype) if dtype else None)


_export("cumprod")


@defop("cummax")
def cummax(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    values = jax.lax.associative_scan(jnp.maximum, x, axis=axis)
    return values, _scan_argextreme(x, axis, jnp.greater_equal)


@defop("cummin")
def cummin(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    values = jax.lax.associative_scan(jnp.minimum, x, axis=axis)
    return values, _scan_argextreme(x, axis, jnp.less_equal)


def _scan_argextreme(x, axis, cmp):
    idx = jnp.arange(x.shape[axis])
    idx = idx.reshape([-1 if i == (axis % x.ndim) else 1 for i in range(x.ndim)])
    idx = jnp.broadcast_to(idx, x.shape)

    def combine(a, b):
        av, ai = a
        bv, bi = b
        take_b = cmp(bv, av)
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

    _, indices = jax.lax.associative_scan(combine, (x, idx), axis=axis)
    return indices


_export("cummax")
_export("cummin")


@defop("logcumsumexp")
def logcumsumexp(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jax.lax.associative_scan(jnp.logaddexp, x, axis=axis)


_export("logcumsumexp")


@defop("nan_to_num")
def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


_export("nan_to_num")


@defop("diff")
def diff(x, n=1, axis=-1):
    return jnp.diff(x, n=n, axis=axis)


_export("diff")


@defop("trapezoid")
def trapezoid(y, x=None, dx=None, axis=-1):
    if dx is None and x is None:
        dx = 1.0
    return jnp.trapezoid(y, x=x, dx=dx if dx is not None else 1.0, axis=axis)


_export("trapezoid")


@defop("deg2rad")
def deg2rad(x):
    return jnp.deg2rad(x)


@defop("rad2deg")
def rad2deg(x):
    return jnp.rad2deg(x)


_export("deg2rad")
_export("rad2deg")


@defop("angle")
def angle(x):
    return jnp.angle(x)


_export("angle")


@defop("increment")
def increment(x, value=1.0):
    return x + jnp.asarray(value, x.dtype)


_export("increment")


# ---- r4 coverage additions (reference ops.yaml parity) ----------------------
_unary("positive", jnp.positive)
_unary("negative", jnp.negative)
_unary("signbit", jnp.signbit)
_binary("isin", lambda x, t: jnp.isin(x, t))


@defop("vander", tensor_method=None)
def vander(x, n=None, increasing=False):
    return jnp.vander(x, N=n, increasing=increasing)


_export("vander")


@defop("tensordot", tensor_method=None)
def tensordot(x, y, axes=2):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(a) if isinstance(a, (list, tuple)) else a for a in axes)
    return jnp.tensordot(x, y, axes=axes)


_export("tensordot")


@defop("renorm", tensor_method="renorm")
def renorm(x, p, axis, max_norm):
    # per-slice p-norm along every dim EXCEPT axis, clamped to max_norm
    red = tuple(i for i in range(x.ndim) if i != axis)
    norms = jnp.sum(jnp.abs(x) ** p, axis=red, keepdims=True) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / jnp.maximum(norms, 1e-12), 1.0)
    return x * factor


_export("renorm")


@defop("take", tensor_method="take")
def take(x, index, mode="raise"):
    idx = index.reshape(-1)
    flat = x.reshape(-1)
    n = flat.shape[0]
    if mode == "wrap":
        idx = idx % n
    elif mode == "clip":
        idx = jnp.clip(idx, 0, n - 1)
    else:  # 'raise' cannot raise inside a traced program; paddle clamps too
        idx = jnp.clip(jnp.where(idx < 0, idx + n, idx), 0, n - 1)
    return flat[idx].reshape(index.shape)


_export("take")
