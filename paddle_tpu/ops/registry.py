"""Op registry: the single-schema keystone (SURVEY §1).

Each ``OpDef`` carries the pure-jax implementation plus metadata; registration
generates the functional entry (eager dispatch through the autograd tape), the
Tensor method binding, and exposes abstract-eval (shape/dtype inference —
the ``infermeta`` analog) via ``infer_meta``. SPMD sharding propagation (the
``spmd_rule:`` analog, ``paddle/phi/infermeta/spmd_rules/``) is delegated to
GSPMD: because every op is a pure jax function, sharding rules follow from the
XLA sharding propagation pass rather than hand-written per-op C++ rules.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax

from paddle_tpu.core.dispatch import call_op
from paddle_tpu.core.tensor import Tensor, register_tensor_method
from paddle_tpu.errors import AlreadyExistsError


@dataclass
class OpDef:
    name: str
    fn: Callable  # pure function over jax arrays
    tensor_method: Optional[str] = None  # method name to bind on Tensor (None = don't)
    inplace_method: Optional[str] = None  # e.g. "add_" — rebinds self to result
    doc: str = ""
    tags: Sequence[str] = field(default_factory=tuple)


REGISTRY: Dict[str, OpDef] = {}


def register(opdef: OpDef) -> Callable:
    """Register an op; returns the eager functional entry point."""
    if opdef.name in REGISTRY:
        raise AlreadyExistsError(f"op '{opdef.name}' already registered")
    REGISTRY[opdef.name] = opdef

    import functools

    @functools.wraps(opdef.fn)
    def entry(*args: Any, **kwargs: Any) -> Any:
        kwargs.pop("name", None)  # paddle API compat: trailing name= arg
        return call_op(opdef.name, opdef.fn, *args, **kwargs)

    entry.__name__ = opdef.name
    entry.__qualname__ = opdef.name
    if opdef.doc:
        entry.__doc__ = opdef.doc
    entry.__paddle_tpu_op__ = opdef.name  # type: ignore[attr-defined]
    entry.raw_fn = opdef.fn  # type: ignore[attr-defined]

    if opdef.tensor_method:
        register_tensor_method(opdef.tensor_method, entry)
    if opdef.inplace_method:

        def inplace(self: Tensor, *args: Any, **kwargs: Any) -> Tensor:
            new = entry(self, *args, **kwargs)
            self._replace_(new)
            return self

        inplace.__name__ = opdef.inplace_method
        register_tensor_method(opdef.inplace_method, inplace)
    return entry


def defop(
    name: str,
    tensor_method: Optional[str] = None,
    inplace_method: Optional[str] = None,
    doc: str = "",
    tags: Sequence[str] = (),
) -> Callable[[Callable], Callable]:
    """Decorator form of :func:`register`."""

    def deco(fn: Callable) -> Callable:
        return register(
            OpDef(
                name=name,
                fn=fn,
                tensor_method=tensor_method if tensor_method is not None else name,
                inplace_method=inplace_method,
                doc=doc or (fn.__doc__ or ""),
                tags=tags,
            )
        )

    return deco


def infer_meta(name: str, *args: Any, **kwargs: Any) -> Any:
    """Abstract eval (shape/dtype inference) for a registered op — the
    ``infermeta`` analog (reference ``paddle/phi/infermeta/``), via
    ``jax.eval_shape`` so no device compute happens."""
    opdef = REGISTRY[name]

    def unwrapped(*a: Any, **k: Any) -> Any:
        return opdef.fn(*a, **k)

    spec_args = [
        jax.ShapeDtypeStruct(tuple(a.shape), a.dtype) if isinstance(a, Tensor) else a
        for a in args
    ]
    return jax.eval_shape(unwrapped, *spec_args, **kwargs)


def get_op(name: str) -> OpDef:
    return REGISTRY[name]


def list_ops() -> List[str]:
    return sorted(REGISTRY)
