"""Functional op layer.

Counterpart of the reference's PHI op library + YAML op registry
(``paddle/phi/ops/yaml/ops.yaml``, 466 ops): every op is declared through
``paddle_tpu.ops.registry`` which registers (1) the functional API, (2) the
autograd rule (implicitly, via jax.vjp over the pure function), (3) abstract
eval / shape inference (via jax.eval_shape on the same function — the
infermeta analog), and (4) Tensor-method binding.
"""

from paddle_tpu.ops import registry  # noqa: F401
