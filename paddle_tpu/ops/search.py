"""Search / sort / selection ops (reference ``python/paddle/tensor/search.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor, register_tensor_method
from paddle_tpu.ops.registry import defop

__all__ = [
    "argmax",
    "argmin",
    "argsort",
    "sort",
    "topk",
    "where",
    "nonzero",
    "searchsorted",
    "kthvalue",
    "mode",
    "index_sample",
    "bucketize",
]


@defop("argmax")
def argmax(x, axis=None, keepdim=False, dtype="int64"):
    from paddle_tpu.core.dtypes import convert_dtype

    if axis is None:
        x = x.reshape(-1)
        axis = 0
    out = jnp.argmax(x, axis=int(axis), keepdims=keepdim)
    return out.astype(convert_dtype(dtype))


@defop("argmin")
def argmin(x, axis=None, keepdim=False, dtype="int64"):
    from paddle_tpu.core.dtypes import convert_dtype

    if axis is None:
        x = x.reshape(-1)
        axis = 0
    out = jnp.argmin(x, axis=int(axis), keepdims=keepdim)
    return out.astype(convert_dtype(dtype))


@defop("argsort")
def argsort(x, axis=-1, descending=False, stable=False):
    out = jnp.argsort(x, axis=int(axis), stable=bool(stable), descending=bool(descending))
    return out.astype(jnp.int64)


@defop("sort")
def sort(x, axis=-1, descending=False, stable=False):
    out = jnp.sort(x, axis=int(axis), stable=bool(stable), descending=bool(descending))
    return out


@defop("topk", tensor_method=None)
def _topk_op(x, k, axis=-1, largest=True, sorted=True):  # noqa: A002
    axis = int(axis) % x.ndim
    src = x if largest else -x
    moved = jnp.moveaxis(src, axis, -1)
    values, indices = jax.lax.top_k(moved, k)
    if not largest:
        values = -values
    values = jnp.moveaxis(values, -1, axis)
    indices = jnp.moveaxis(indices, -1, axis)
    return values, indices.astype(jnp.int64)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):  # noqa: A002
    return _topk_op(x, int(k), axis=axis, largest=largest, sorted=sorted)


register_tensor_method("topk", topk)


@defop("where")
def where(condition, x=None, y=None):
    if x is None and y is None:
        raise ValueError("where(condition) without x/y: use paddle_tpu.nonzero")
    return jnp.where(condition, x, y)


def nonzero(x, as_tuple=False):
    """Eager-only (dynamic output shape)."""
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    res = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(r.astype(np.int64)) for r in res)
    return Tensor(np.stack(res, axis=1).astype(np.int64))


register_tensor_method("nonzero", nonzero)


@defop("searchsorted")
def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    side = "right" if right else "left"
    if sorted_sequence.ndim == 1:
        out = jnp.searchsorted(sorted_sequence, values, side=side)
    else:
        flat_seq = sorted_sequence.reshape(-1, sorted_sequence.shape[-1])
        flat_val = values.reshape(-1, values.shape[-1])
        out = jax.vmap(lambda s, v: jnp.searchsorted(s, v, side=side))(flat_seq, flat_val)
        out = out.reshape(values.shape)
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


@defop("bucketize")
def bucketize(x, sorted_sequence, out_int32=False, right=False):
    side = "right" if right else "left"
    out = jnp.searchsorted(sorted_sequence, x, side=side)
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


@defop("kthvalue", tensor_method=None)
def _kthvalue_op(x, k, axis=-1, keepdim=False):
    axis = int(axis) % x.ndim
    sorted_vals = jnp.sort(x, axis=axis)
    sorted_idx = jnp.argsort(x, axis=axis)
    vals = jnp.take(sorted_vals, k - 1, axis=axis)
    idx = jnp.take(sorted_idx, k - 1, axis=axis)
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idx = jnp.expand_dims(idx, axis)
    return vals, idx.astype(jnp.int64)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    return _kthvalue_op(x, int(k), axis=axis, keepdim=keepdim)


register_tensor_method("kthvalue", kthvalue)


@defop("mode", tensor_method=None)
def _mode_op(x, axis=-1, keepdim=False):
    axis = int(axis) % x.ndim
    moved = jnp.moveaxis(x, axis, -1)
    sorted_v = jnp.sort(moved, axis=-1)
    # count runs of equal values; mode = value with max run length
    n = sorted_v.shape[-1]
    eq = jnp.concatenate(
        [jnp.ones(sorted_v.shape[:-1] + (1,), bool), sorted_v[..., 1:] == sorted_v[..., :-1]],
        axis=-1,
    )
    run_id = jnp.cumsum(~eq, axis=-1)
    # one-hot accumulate run lengths
    counts = jax.nn.one_hot(run_id, n, dtype=jnp.int32).sum(axis=-2)
    best_run = jnp.argmax(counts, axis=-1)
    first_of_run = jnp.argmax(run_id == best_run[..., None], axis=-1)
    values = jnp.take_along_axis(sorted_v, first_of_run[..., None], axis=-1)[..., 0]
    # index: last occurrence in the original array
    match = moved == values[..., None]
    idx = (moved.shape[-1] - 1) - jnp.argmax(jnp.flip(match, axis=-1), axis=-1)
    if keepdim:
        values = jnp.expand_dims(values, -1)
        idx = jnp.expand_dims(idx, -1)
    values = jnp.moveaxis(values, -1, axis) if keepdim else values
    idx = jnp.moveaxis(idx, -1, axis) if keepdim else idx
    return values, idx.astype(jnp.int64)


def mode(x, axis=-1, keepdim=False, name=None):
    return _mode_op(x, axis=axis, keepdim=keepdim)


register_tensor_method("mode", mode)


@defop("index_sample")
def index_sample(x, index):
    rows = jnp.arange(x.shape[0])[:, None]
    return x[rows, index]
