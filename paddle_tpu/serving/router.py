"""Prefix-affinity replica router with health-gated failover.

One :class:`~paddle_tpu.serving.frontend.ServingFrontend` is one box; this
is the layer-7 router that serves N of them and survives any one dying
mid-storm — the ROADMAP's "Cluster-scale serving" item, the reference
fork's ``fleet``/elastic stack shaped for in-process replicas:

- **prefix affinity** — requests route by the prompt's prefix-chain hash
  (the SAME rolling blake2b the engine's prefix cache keys chain nodes by,
  computed over the first ``affinity_blocks`` block-aligned segments), so a
  tenant's shared system prompt lands on the replica already holding its KV
  chains. Replica choice is rendezvous (highest-random-weight) hashing:
  adding or losing a replica remaps only that replica's share of keys,
  never reshuffles the survivors' warm caches.
- **death is a routing event** — a probe loop over each frontend's
  ``health_snapshot()`` (engine ``broken`` flag, pump liveness, failure
  reason, queue/overload gauges) drives UP → DEGRADED → DEAD transitions.
  On DEAD, results the dead engine already finished are salvaged
  (``drain_finished()`` via the frontend's fail path) and delivered; the
  rest are re-dispatched to the next replica in the hash ring — bounded
  retries (``max_redispatch``), exponential backoff, and deadline-aware: a
  re-dispatched request keeps its ORIGINAL deadline and is shed
  (``deadline_failover``) the moment it can no longer make it. Exhausted
  budgets shed with the explicit terminal ``replica_failure`` — under a
  replica death nothing is ever lost *silently*.
- **drain** — administrative :meth:`ReplicaRouter.drain` stops intake to a
  replica (its ring share remaps instantly), lets its live slots finish,
  and records ``replica_drained`` when empty; :meth:`resume` reopens it.
- **cross-replica shedding** — an affinity target in SHEDDING (or whose
  bounded queue rejects) spills to the least-loaded healthy replica rather
  than queueing, trading cache warmth for latency. Every routing decision
  increments exactly one ``serving_router_route_total{route}`` cell
  (``affinity`` / ``spill`` / ``failover`` / ``round_robin``) and one
  routing-log entry, so the counters reconcile exactly with the monotonic
  dispatch count (the log itself is a bounded recent window).

Observability: replica state transitions are flight-recorder events and a
per-replica state gauge; a failed-over request's trace carries a
``router.failover`` span parented into its root, so the trace shows BOTH
replicas; "all replicas dead" dumps the black box
(``router_all_replicas_dead``).

Threading model mirrors the frontend: ``submit``/``cancel`` are
thread-safe; drive everything inline with :meth:`pump` (tests/bench), or
:meth:`start` pump threads per replica plus a router supervisor thread
(probe + failover + token forwarding). Lock order is router → frontend →
engine, never the reverse. Every blocking wait carries a timeout (RB502)
and every retry loop consults a bounded budget (RB503).

Replicas must serve the SAME model weights: failover re-dispatch replays
the prompt on the new replica and relies on greedy-decode determinism to
regenerate the tokens already streamed (delivery dedups on token count).
"""

from __future__ import annotations

import hashlib
import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass
from queue import Empty, Queue
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.inference.engine import InferenceRequest
from paddle_tpu.inference.prefix_cache import chain_digest
from paddle_tpu.observability import flight_recorder as _flight
from paddle_tpu.observability import tracing as _tracing
from paddle_tpu.observability.serving import (
    priority_name,
    router_metrics,
    serving_metrics,
)
from paddle_tpu.serving.cluster import (
    REPLICA_DEAD,
    REPLICA_DEGRADED,
    REPLICA_DRAINING,
    REPLICA_UP,
    STATE_CODES,
    Replica,
    ReplicaCluster,
)
from paddle_tpu.serving.errors import Overloaded
from paddle_tpu.serving.frontend import SHEDDING, Priority, ServingRequest
from paddle_tpu.testing.faults import InjectedFault, fault_point

__all__ = [
    "ROUTE_AFFINITY",
    "ROUTE_FAILOVER",
    "ROUTE_ROUND_ROBIN",
    "ROUTE_SPILL",
    "ReplicaRouter",
    "RouterConfig",
    "RouterRequest",
    "rendezvous_rank",
]

ROUTE_AFFINITY = "affinity"
ROUTE_SPILL = "spill"
ROUTE_FAILOVER = "failover"
ROUTE_ROUND_ROBIN = "round_robin"


def rendezvous_rank(key: bytes, names: Sequence[str]) -> List[str]:
    """Highest-random-weight (rendezvous) order of ``names`` for ``key``:
    each (key, name) pair hashes to a weight and names sort by it, so every
    key has its own stable preference list. Removing a name promotes each of
    its keys to their SECOND choice and changes nothing for keys it did not
    own — the minimal-remap property that keeps the other replicas' prefix
    caches warm across membership changes."""
    return sorted(
        names,
        key=lambda n: hashlib.blake2b(
            key + b"\x00" + n.encode("utf-8"), digest_size=8
        ).digest(),
        reverse=True,
    )


@dataclass
class RouterConfig:
    """Router policy knobs."""

    # block-aligned prefix segments hashed into the affinity key: the shared
    # system prompt's span, NOT the whole prompt (divergent user tails must
    # not scatter a tenant's traffic across replicas)
    affinity_blocks: int = 2
    # "affinity" (prefix-hash rendezvous) or "round_robin" (the A/B baseline
    # the affinity speedup is measured against)
    policy: str = "affinity"
    # failover budget: re-dispatch attempts per request before the explicit
    # replica_failure terminal
    max_redispatch: int = 2
    # base re-dispatch backoff; doubles per attempt, always deadline-capped
    redispatch_backoff_s: float = 0.01
    # supervisor-thread cadence (threaded mode); inline pump() probes every call
    probe_interval_s: float = 0.05
    # consecutive failing probes before a replica is declared DEAD
    probe_failures_to_dead: int = 3
    # consecutive inline pump failures before the replica frontend is failed
    # (mirrors the frontend pump thread's own escalation)
    pump_failures_to_dead: int = 3
    # default wait for RouterRequest.stream()/result()
    default_wait_s: float = 60.0
    # bounded routing log (reconciliation surface for the route counters)
    routing_log_size: int = 4096

    def __post_init__(self) -> None:
        if self.policy not in (ROUTE_AFFINITY, ROUTE_ROUND_ROBIN):
            raise ValueError(
                f"policy must be 'affinity' or 'round_robin', got {self.policy!r}"
            )
        if self.max_redispatch < 0:
            raise ValueError("max_redispatch must be >= 0")


_END = None  # token-stream terminal sentinel (same protocol as the frontend)


class RouterRequest:
    """Cluster-level request handle: one client-visible stream that survives
    replica failover. ``outcome`` is ``"ok"``, a frontend shed reason passed
    through (``deadline_queued`` / ``deadline_decode`` / ...), or a
    router-originated terminal: ``replica_failure`` (re-dispatch budget
    exhausted) / ``deadline_failover`` (original deadline unmakeable after a
    death) / ``cancelled``.

    Failover token continuity: delivery dedups on count — the re-dispatched
    replica regenerates deterministically and only tokens past what was
    already streamed are forwarded."""

    def __init__(
        self,
        rid: int,
        prompt: np.ndarray,
        max_new_tokens: int,
        eos_token_id: Optional[int],
        priority: int,
        tenant: str,
        deadline: Optional[float],
        affinity_key: bytes,
        submit_time: float,
        default_wait_s: float,
    ) -> None:
        self.id = rid
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.priority = int(priority)
        self.tenant = str(tenant)
        self.deadline = deadline  # absolute perf_counter instant; ORIGINAL,
        # carried unchanged across every re-dispatch
        self.affinity_key = affinity_key
        self.submit_time = submit_time
        self.trace_ctx: Optional[_tracing.TraceContext] = None
        # routing state (mutated only under the router lock)
        self.replica: Optional[str] = None  # current owner name
        self.inner: Optional[ServingRequest] = None  # current frontend handle
        self.redispatches = 0
        self.routes: List[Tuple[str, str]] = []  # (route_kind, replica_name)
        self.outcome: Optional[str] = None
        self.finish_time: Optional[float] = None
        self.first_token_time: Optional[float] = None
        # failover bookkeeping
        self._not_before = 0.0  # backoff gate for the next re-dispatch
        self._failover_from: Optional[str] = None
        self._death_ts: Optional[float] = None
        self._terminal_inner: Optional[InferenceRequest] = None
        # stream state
        self._default_wait_s = float(default_wait_s)
        self._q: Queue = Queue()
        self._done = threading.Event()
        self._delivered: List[int] = []
        self._n_delivered = 0

    @property
    def finished(self) -> bool:
        return self._done.is_set()

    @property
    def degraded(self) -> bool:
        inner = self.inner
        return bool(inner is not None and inner.degraded)

    @property
    def met_deadline(self) -> bool:
        """Finished normally inside the ORIGINAL deadline (vacuously true
        with none) — failover never relaxes the SLO."""
        if self.outcome != "ok":
            return False
        if self.deadline is None:
            return True
        return self.finish_time is not None and self.finish_time <= self.deadline

    @property
    def traceparent(self) -> Optional[str]:
        if self.trace_ctx is None:
            return None
        return _tracing.format_traceparent(self.trace_ctx)

    def tokens(self) -> List[int]:
        """Tokens delivered to this handle (deduped across failover)."""
        return list(self._delivered)

    def stream(self, timeout: Optional[float] = None) -> Iterator[int]:
        """Yield tokens as the router forwards them; returns at end of
        stream (check ``outcome``). ``timeout`` bounds the wait for EACH
        token — a stalled cluster raises instead of parking a worker."""
        wait = self._default_wait_s if timeout is None else float(timeout)
        while True:
            try:
                item = self._q.get(timeout=wait)
            except Empty:
                raise TimeoutError(
                    f"request {self.id}: no token within {wait}s "
                    "(cluster stalled?)"
                ) from None
            if item is _END:
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> Optional[InferenceRequest]:
        """Block until terminal; returns the engine-side request of the
        replica the terminal came from (None only if the request was shed
        before any replica ever accepted it)."""
        wait = self._default_wait_s if timeout is None else float(timeout)
        if not self._done.wait(timeout=wait):
            raise TimeoutError(f"request {self.id} not finished within {wait}s")
        return self._terminal_inner


class ReplicaRouter:
    """See module docstring. Construct over a
    :class:`~paddle_tpu.serving.cluster.ReplicaCluster`."""

    def __init__(
        self,
        cluster: ReplicaCluster,
        config: Optional[RouterConfig] = None,
    ) -> None:
        self.cluster = cluster
        self.config = config or RouterConfig()
        # affinity keys hash block-aligned segments: all replicas share one
        # engine geometry, so the first replica's block size is THE block size
        first = next(iter(cluster))
        self.block_size = int(first.frontend.engine.block_size)
        self._lock = threading.RLock()
        self._live: Dict[int, RouterRequest] = {}
        self._redispatch_q: List[RouterRequest] = []
        self._pending_finished: List[RouterRequest] = []
        self._ids = itertools.count()
        self._rr_index = 0  # round_robin rotation
        self._metrics = router_metrics()
        self._serving_metrics = serving_metrics()
        # host-side accounting (always on — reconciliation must not depend
        # on the metrics flag): route counters mirror the metric family
        self._counters: Dict[str, int] = {
            ROUTE_AFFINITY: 0, ROUTE_SPILL: 0,
            ROUTE_FAILOVER: 0, ROUTE_ROUND_ROBIN: 0,
        }
        self._shed_counts: Dict[str, int] = {}
        self._salvaged = 0
        self._dispatches = 0  # monotonic: the reconciliation surface
        self._routing_log: deque = deque(maxlen=int(self.config.routing_log_size))
        self._failover_latencies: deque = deque(maxlen=4096)
        # cluster-truth SLO accounting (always on — the burn-rate monitor
        # must work with metrics off, like the overload controller): every
        # terminal counts exactly once in _finalize_locked
        self._terminals = 0
        self._ok = 0
        self._ok_in_slo = 0
        self._redispatch_count = 0
        # recent cluster-level TTFTs as (instant, value): bounded by count
        # AND pruned by age at sample time — a storm's latencies must age
        # out of the p99 on the clock, not only after 512 fresh requests
        # displace them (a quiet cluster would otherwise hold WARN/PAGE for
        # many multiples of the monitor's slow window after recovery)
        self._ttfts: deque = deque(maxlen=512)
        self._ttft_window_s = 60.0  # the observer aligns this to its config
        # fleet observer (observability.aggregate.ClusterObserver): driven
        # from this probe loop; None = PR 11 behavior exactly
        self._observer: Optional[Any] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        for replica in cluster:
            self._metrics["replica_state"].labels(replica=replica.name).set(
                STATE_CODES[replica.state]
            )

    # -- intake ---------------------------------------------------------------
    def submit(
        self,
        prompt_ids: Any,
        max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
        priority: int = Priority.STANDARD,
        tenant: str = "default",
        ttl_s: Optional[float] = None,
        traceparent: Optional[str] = None,
    ) -> RouterRequest:
        """Route one request to a replica. Raises a typed ``IntakeError``
        (malformed input — identical on every replica, so no retry),
        :class:`Overloaded` when no replica can take it (cluster-wide
        overload, or ``reason="no_replicas"`` when nothing is routable),
        and never silently queues on a shedding replica: the affinity
        target in SHEDDING spills to the least-loaded healthy one."""
        fault_point("router.dispatch")
        now = time.perf_counter()
        trace_ctx = None
        if _tracing.tracing_enabled():
            trace_ctx = _tracing.GLOBAL_TRACER.start_trace(traceparent)
        prompt = np.asarray(
            prompt_ids._data if hasattr(prompt_ids, "_data") else prompt_ids,
            np.int32,
        ).reshape(-1)
        key = chain_digest(prompt, self.block_size, self.config.affinity_blocks)
        with self._lock:
            rr = RouterRequest(
                next(self._ids), prompt, max_new_tokens, eos_token_id,
                int(priority), tenant,
                None if ttl_s is None else now + float(ttl_s),
                key, now, self.config.default_wait_s,
            )
            rr.trace_ctx = trace_ctx
            try:
                self._submit_locked(rr, now)
            except Exception as exc:
                # a sampled request refused at the door still gets a
                # terminal root span — a trace must never just vanish
                # (same invariant as the frontend's shed-at-intake span)
                self._emit_refused_trace_locked(rr, exc, now)
                raise
            self._live[rr.id] = rr
            self._update_gauges_locked()
            return rr

    def _submit_locked(self, rr: RouterRequest, now: float) -> None:
        """Dispatch one fresh request: the routing policy's pick first, the
        spill target on refusal — resolved LAZILY, so the common accepted
        path never pays the per-replica load probes."""
        routable = [r for r in self.cluster if r.routable]
        if not routable:
            self._count_shed_locked("no_replicas")
            raise Overloaded(
                "no routable replicas (all dead or draining)",
                retry_after=1.0, reason="no_replicas",
            )
        if self.config.policy == ROUTE_ROUND_ROBIN:
            pick = routable[self._rr_index % len(routable)]
            self._rr_index += 1
            plan = [(pick, ROUTE_ROUND_ROBIN)]
        else:
            ranked = rendezvous_rank(rr.affinity_key, [r.name for r in routable])
            primary = {r.name: r for r in routable}[ranked[0]]
            plan = [(primary, ROUTE_AFFINITY)]
            if (
                primary.frontend.controller.level >= SHEDDING
                and len(routable) > 1
            ):
                # the affinity target is shedding: trade cache warmth for
                # latency rather than queueing behind an overloaded replica
                spill = self._least_loaded_locked(
                    [r for r in routable if r is not primary]
                )
                if spill is not None:
                    plan.insert(0, (spill, ROUTE_SPILL))
        last_overload: Optional[Overloaded] = None
        idx = 0
        while idx < len(plan):  # the plan may grow ONE lazy spill candidate
            replica, route = plan[idx]
            idx += 1
            try:
                self._dispatch_locked(rr, replica, route, now)
                return
            except Overloaded as exc:
                last_overload = exc
            except RuntimeError as exc:
                # the replica died between probe and submit: suspect it
                # (the probe loop will classify) and fall through to spill
                replica.probe_failures += 1
                last_overload = Overloaded(
                    f"replica {replica.name} failed at intake: {exc}",
                    retry_after=1.0, reason="replica_failure",
                )
            if (
                route == ROUTE_AFFINITY
                and len(routable) > 1
                and not any(r2 == ROUTE_SPILL for _, r2 in plan)
            ):
                # the primary refused: NOW resolve the spill target (the
                # uncommon path pays the load probes, not every submit)
                spill = self._least_loaded_locked(
                    [r for r in routable if r is not replica]
                )
                if spill is not None:
                    plan.append((spill, ROUTE_SPILL))
        raise last_overload  # every candidate refused

    def _emit_refused_trace_locked(
        self, rr: RouterRequest, exc: Exception, now: float
    ) -> None:
        ctx = rr.trace_ctx
        if ctx is None or not ctx.sampled:
            return
        reason = getattr(exc, "reason", None) or type(exc).__name__
        _tracing.GLOBAL_TRACER.add_span(
            "router.request", trace_id=ctx.trace_id, span_id=ctx.span_id,
            parent_id=ctx.parent_id, start_s=rr.submit_time, end_s=now,
            attrs={"req_id": rr.id, "priority": priority_name(rr.priority),
                   "tenant": rr.tenant, "outcome": f"refused:{reason}"},
            status=f"shed:{reason}",
        )

    def _least_loaded_locked(
        self, replicas: List[Replica]
    ) -> Optional[Replica]:
        if not replicas:
            return None
        def load(r: Replica) -> Tuple[int, int]:
            snap = r.frontend.health_snapshot()
            return (snap["level"], snap["queue_depth"] + snap["live_requests"])
        return min(replicas, key=load)

    def _dispatch_locked(
        self, rr: RouterRequest, replica: Replica, route: str, now: float
    ) -> None:
        """One accepted routing decision: submit to the replica's frontend
        and account it exactly once (route counter + routing log)."""
        ttl = None
        if rr.deadline is not None:
            # the ORIGINAL deadline travels: the replica sees only what's left
            ttl = max(rr.deadline - now, 1e-6)
        rr.inner = replica.frontend.submit(
            rr.prompt,
            max_new_tokens=rr.max_new_tokens,
            eos_token_id=rr.eos_token_id,
            priority=rr.priority,
            tenant=rr.tenant,
            ttl_s=ttl,
            traceparent=self._child_traceparent(rr),
        )
        if rr.deadline is not None and rr.inner.inner.deadline is not None:
            # absolute-deadline fidelity: the frontend restamps the ttl from
            # its own clock, which lands a hair past the original — clamp so
            # no replica ever honors more than the request's true deadline
            rr.inner.inner.deadline = min(rr.inner.inner.deadline, rr.deadline)
        rr.replica = replica.name
        rr.routes.append((route, replica.name))
        self._counters[route] += 1
        self._dispatches += 1
        self._metrics["route"].labels(route=route).inc()
        self._routing_log.append(
            {"req_id": rr.id, "replica": replica.name, "route": route,
             "redispatch": rr.redispatches}
        )

    @staticmethod
    def _child_traceparent(rr: RouterRequest) -> Optional[str]:
        if rr.trace_ctx is None:
            return None
        return _tracing.format_traceparent(rr.trace_ctx)

    # -- lifecycle ------------------------------------------------------------
    def cancel(self, req_id: int, reason: str = "cancelled") -> bool:
        """Shed one routed request wherever it lives (on a replica, or
        pending re-dispatch). Returns False for unknown/terminal ids."""
        with self._lock:
            rr = self._live.get(req_id)
            if rr is None:
                return False
            now = time.perf_counter()
            if rr.inner is not None:
                replica = self.cluster.get(rr.replica) if rr.replica else None
                if replica is not None:
                    replica.frontend.cancel(rr.inner.id, reason=reason)
                self._forward_locked(rr, now)
            self._finalize_locked(rr, reason, now, deliver=False)
            self._update_gauges_locked()
            return True

    def drain(self, name: str) -> None:
        """Administrative drain: stop intake to ``name`` (its hash-ring
        share remaps to the survivors immediately), let its live slots
        finish, record ``replica_drained`` once empty. No request is shed."""
        with self._lock:
            replica = self.cluster.replicas[name]
            if replica.state == REPLICA_DEAD:
                raise RuntimeError(f"replica {name!r} is dead; revive, don't drain")
            if replica.state != REPLICA_DRAINING:
                self._transition_locked(replica, REPLICA_DRAINING, time.perf_counter())

    def resume(self, name: str) -> None:
        """Reopen a DRAINING replica for intake."""
        with self._lock:
            replica = self.cluster.replicas[name]
            if replica.state != REPLICA_DRAINING:
                raise RuntimeError(
                    f"replica {name!r} is {replica.state}, not draining"
                )
            replica.drained_logged = False
            self._transition_locked(replica, REPLICA_UP, time.perf_counter())

    def revive(self, name: str) -> Replica:
        """Rebuild a DEAD replica through the cluster factory; it rejoins
        the ring (reclaiming exactly its old key share) as UP."""
        with self._lock:
            replica = self.cluster.revive(name)
            replica.drained_logged = False
            _flight.record_event(
                "replica_state", replica=name,
                **{"from": REPLICA_DEAD, "to": REPLICA_UP,
                   "generation": replica.generation},
            )
            self._metrics["replica_state"].labels(replica=name).set(
                STATE_CODES[REPLICA_UP]
            )
            if self._thread is not None and self._thread.is_alive():
                replica.frontend.start()
            self._update_gauges_locked()
            return replica

    # -- the pump (inline driver) ---------------------------------------------
    def pump(self) -> List[RouterRequest]:
        """One cluster iteration: pump every live replica's frontend, probe
        health (state transitions, failover), retry pending re-dispatches,
        forward tokens, finalize terminals. Returns handles that reached a
        terminal state during this call."""
        with self._lock:
            for replica in self.cluster:
                self._pump_replica_locked(replica)
            return self._tick_locked()

    def _pump_replica_locked(self, replica: Replica) -> None:
        if replica.state == REPLICA_DEAD:
            return
        try:
            replica.frontend.pump()
            replica.pump_failures = 0
        except Exception as exc:  # classify like the pump thread: transient failures retry, permanent ones fail the replica below
            replica.pump_failures += 1
            if (
                replica.frontend.engine.broken
                or replica.pump_failures > self.config.pump_failures_to_dead
            ):
                # permanent: salvage + explicit terminals now; the probe
                # pass turns this into the failover routing event
                replica.frontend.fail(f"{type(exc).__name__}: {exc}")

    def _tick_locked(self) -> List[RouterRequest]:
        now = time.perf_counter()
        self._probe_locked(now)
        self._retry_redispatch_locked(now)
        for rr in list(self._live.values()):
            self._forward_locked(rr, now)
            if rr.inner is not None and rr.inner.finished and not rr.finished:
                self._on_inner_terminal_locked(rr, now)
        if self._observer is not None:
            # the fleet observer rides the probe loop: burn-rate sampling
            # and PAGE-entry incident snapshots happen here, after this
            # tick's terminals have been accounted
            self._observer.on_tick_locked(now)
        self._update_gauges_locked()
        out, self._pending_finished = self._pending_finished, []
        return out

    # -- health probing -------------------------------------------------------
    def _probe_locked(self, now: float) -> None:
        for replica in self.cluster:
            if replica.state == REPLICA_DEAD:
                continue
            try:
                fault_point("replica.kill")
            except InjectedFault:
                # the fault site models a whole-replica death: flip the
                # frontend to permanent failure; the probe below observes it
                replica.kill("injected replica.kill")
            snap = None
            try:
                fault_point("router.health_probe")
                snap = replica.frontend.health_snapshot()
                replica.probe_failures = 0
            except Exception:  # a failing probe suspects the replica, never kills the router
                replica.probe_failures += 1
            new_state = self._classify_locked(replica, snap)
            if new_state != replica.state:
                self._transition_locked(replica, new_state, now)
            elif (
                replica.state == REPLICA_DRAINING
                and snap is not None
                and snap["live_requests"] == 0
                and snap["queue_depth"] == 0
                and not replica.drained_logged
            ):
                replica.drained_logged = True
                _flight.record_event("replica_drained", replica=replica.name)

    def _classify_locked(
        self, replica: Replica, snap: Optional[Dict[str, Any]]
    ) -> str:
        if snap is not None and (
            snap["broken"]
            or snap["failed"] is not None
            or snap["pump_alive"] is False
        ):
            return REPLICA_DEAD
        if snap is None:
            if replica.probe_failures >= self.config.probe_failures_to_dead:
                return REPLICA_DEAD
            # a flaky probe demotes; DRAINING stays draining while suspect
            return (
                REPLICA_DRAINING
                if replica.state == REPLICA_DRAINING
                else REPLICA_DEGRADED
            )
        if replica.state == REPLICA_DRAINING:
            return REPLICA_DRAINING
        if snap["level"] >= SHEDDING:
            return REPLICA_DEGRADED  # sustained overload: routable, reported
        return REPLICA_UP

    def _transition_locked(self, replica: Replica, to: str, now: float) -> None:
        frm = replica.state
        replica.state = to
        _flight.record_event(
            "replica_state", replica=replica.name, **{"from": frm, "to": to}
        )
        self._metrics["replica_state"].labels(replica=replica.name).set(
            STATE_CODES[to]
        )
        if to == REPLICA_DEAD:
            replica.death_ts = now
            self._failover_replica_locked(replica, now)
            if not any(r.alive for r in self.cluster):
                # the whole cluster is down: this is the postmortem moment
                _flight.record_event(
                    "all_replicas_dead", replicas=len(self.cluster),
                    live_requests=len(self._live),
                    pending_redispatch=len(self._redispatch_q),
                )
                _flight.safe_dump(
                    "router_all_replicas_dead",
                    extra={"replicas": self.cluster.names()},
                )
        if self._observer is not None:
            # after the failover machinery ran, so an incident snapshot on a
            # death transition captures the salvage/re-dispatch state too
            self._observer.on_transition_locked(replica, frm, to, now)

    # -- failover -------------------------------------------------------------
    def _failover_replica_locked(self, replica: Replica, now: float) -> None:
        """Replica death as a routing event: salvage what its engine already
        finished, re-dispatch the rest, pass through terminals it reached
        before dying. Nothing owned by the dead replica is lost silently."""
        # idempotent: organic deaths already failed themselves; a probed
        # death (e.g. pump thread gone) still needs salvage + terminals
        replica.frontend.fail("replica declared dead by router health probe")
        for rr in list(self._live.values()):
            if rr.replica != replica.name or rr.finished:
                continue
            if rr.inner is None:
                # pending re-dispatch merely TARGETED at this replica (never
                # dispatched): it is already queued, and _retry_redispatch
                # re-picks a routable target at dispatch time — re-enqueueing
                # it here would double-dispatch one request
                continue
            self._forward_locked(rr, now)  # tokens truly generated are kept
            out = rr.inner.outcome
            if out == "ok":
                # the dead engine had finished this one: salvaged delivery
                self._salvaged += 1
                self._metrics["salvaged"].inc()
                self._finalize_locked(rr, "ok", now)
            elif out in (None, "engine_failure"):
                self._schedule_redispatch_locked(rr, replica.name, now, now)
            else:
                # terminal before the death (deadline/cancel): pass through
                self._finalize_locked(rr, out, now)

    def _schedule_redispatch_locked(
        self, rr: RouterRequest, from_name: str, death_ts: float, now: float
    ) -> None:
        rr._failover_from = from_name
        rr._death_ts = death_ts
        if rr.inner is not None:
            # keep the dead replica's engine-side request reachable from
            # result() in case this request sheds before any re-accept
            rr._terminal_inner = rr.inner.inner
        rr.inner = None
        self._backoff_or_shed_locked(rr, now)

    def _backoff_or_shed_locked(self, rr: RouterRequest, now: float) -> None:
        """Burn one re-dispatch attempt: budget-bounded, deadline-aware."""
        rr.redispatches += 1
        self._redispatch_count += 1
        self._metrics["redispatch"].inc()
        if rr.redispatches > self.config.max_redispatch:
            self._shed_locked(rr, "replica_failure", now)
            return
        backoff = self.config.redispatch_backoff_s * (2 ** (rr.redispatches - 1))
        if rr.deadline is not None and now + backoff >= rr.deadline:
            # the original deadline is unmakeable: shed now, don't burn a
            # healthy replica's prefill on a request that cannot land
            self._shed_locked(rr, "deadline_failover", now)
            return
        rr._not_before = now + backoff
        # ownership invariant: the victim is re-owned by its failover target
        # immediately (re-validated at dispatch time)
        target = self._failover_target_locked(rr)
        rr.replica = target.name if target is not None else None
        self._redispatch_q.append(rr)

    def _failover_target_locked(self, rr: RouterRequest) -> Optional[Replica]:
        """The next replica in the hash ring for this request's key (the
        dead owner is no longer routable, so the ring order IS the failover
        order); round_robin mode rotates instead."""
        routable = [r for r in self.cluster if r.routable]
        if not routable:
            return None
        if self.config.policy == ROUTE_ROUND_ROBIN:
            pick = routable[self._rr_index % len(routable)]
            self._rr_index += 1
            return pick
        ranked = rendezvous_rank(rr.affinity_key, [r.name for r in routable])
        by_name = {r.name: r for r in routable}
        return by_name[ranked[0]]

    def _retry_redispatch_locked(self, now: float) -> None:
        if not self._redispatch_q:
            return
        pending, self._redispatch_q = self._redispatch_q, []
        still: List[RouterRequest] = []
        for rr in pending:
            if rr.finished:
                continue  # cancelled/shed while waiting out the backoff
            if rr.deadline is not None and now >= rr.deadline:
                self._shed_locked(rr, "deadline_failover", now)
                continue
            if not any(r.alive for r in self.cluster):
                self._shed_locked(rr, "replica_failure", now)
                continue
            if rr._not_before > now:
                still.append(rr)
                continue
            target = self._failover_target_locked(rr)
            if target is None:
                # alive but nothing routable (all draining): hold; the
                # deadline/all-dead gates above bound the wait
                still.append(rr)
                continue
            try:
                fault_point("router.dispatch")
                self._dispatch_locked(rr, target, ROUTE_FAILOVER, now)
            except (Overloaded, InjectedFault, RuntimeError):
                # refused or died under us: burn one bounded attempt
                self._backoff_or_shed_locked(rr, now)
                continue
            # re-accepted on a healthy replica: failover latency is death
            # detection -> re-accept (what the bench reports at p99)
            lat = now - (rr._death_ts if rr._death_ts is not None else now)
            self._failover_latencies.append(lat)
            self._metrics["failover_latency"].observe(lat)
            ctx = rr.trace_ctx
            if ctx is not None and ctx.sampled:
                # the failed-over request's trace shows BOTH replicas: the
                # two frontend span trees plus this bridge span
                _tracing.GLOBAL_TRACER.add_span(
                    "router.failover", trace_id=ctx.trace_id,
                    parent_id=ctx.span_id,
                    start_s=rr._death_ts if rr._death_ts is not None else now,
                    end_s=now,
                    attrs={
                        "from_replica": rr._failover_from,
                        "to_replica": target.name,
                        "redispatch": rr.redispatches,
                    },
                )
        self._redispatch_q.extend(still)

    # -- delivery -------------------------------------------------------------
    def _forward_locked(self, rr: RouterRequest, now: float) -> None:
        inner = rr.inner
        if inner is None:
            return
        # append-only list, read without the frontend lock: a torn length is
        # impossible under the GIL and a short read just forwards next tick.
        # The length is captured ONCE — re-reading it after the slice could
        # mark a token appended in between as delivered without forwarding it
        gen = inner.inner.generated
        n = len(gen)
        if n <= rr._n_delivered:
            return  # nothing new (or a re-dispatch still catching up)
        fresh = gen[rr._n_delivered:n]
        if rr.first_token_time is None:
            rr.first_token_time = now
            self._ttfts.append((now, now - rr.submit_time))
        for tok in fresh:
            rr._q.put(tok)
            rr._delivered.append(tok)
        rr._n_delivered = n

    def _on_inner_terminal_locked(self, rr: RouterRequest, now: float) -> None:
        out = rr.inner.outcome
        if out == "ok":
            self._finalize_locked(rr, "ok", now)
        elif out == "engine_failure":
            # the replica failed itself (organic pump death) before the
            # probe saw it: same routing event as a probed death
            replica = self.cluster.get(rr.replica) if rr.replica else None
            death_ts = (
                replica.death_ts
                if replica is not None and replica.death_ts is not None
                else now
            )
            self._schedule_redispatch_locked(
                rr, rr.replica or "unknown", death_ts, now
            )
        else:
            # frontend-level terminal (deadline_queued / deadline_decode /
            # stream_timeout / ...): passes through; the frontend already
            # counted its shed
            self._finalize_locked(rr, out, now)

    def _shed_locked(self, rr: RouterRequest, reason: str, now: float) -> None:
        self._count_shed_locked(reason)
        self._finalize_locked(rr, reason, now)

    def _count_shed_locked(self, reason: str) -> None:
        self._shed_counts[reason] = self._shed_counts.get(reason, 0) + 1
        self._serving_metrics["shed"].labels(reason=reason).inc()

    def _finalize_locked(
        self, rr: RouterRequest, outcome: str, now: float, deliver: bool = True
    ) -> None:
        if rr.finished:
            return  # terminal exactly once, cluster-wide
        rr.outcome = outcome
        rr.finish_time = now
        self._terminals += 1
        if outcome == "ok":
            self._ok += 1
            if rr.met_deadline:
                self._ok_in_slo += 1
        if rr.inner is not None:
            rr._terminal_inner = rr.inner.inner
        self._live.pop(rr.id, None)
        rr._done.set()
        rr._q.put(_END)
        ctx = rr.trace_ctx
        if ctx is not None and ctx.sampled:
            _tracing.GLOBAL_TRACER.add_span(
                "router.request", trace_id=ctx.trace_id, span_id=ctx.span_id,
                parent_id=ctx.parent_id, start_s=rr.submit_time, end_s=now,
                attrs={
                    "req_id": rr.id,
                    "routes": [f"{kind}:{name}" for kind, name in rr.routes],
                    "redispatches": rr.redispatches,
                    "outcome": outcome,
                    "priority": priority_name(rr.priority),
                    "tenant": rr.tenant,
                    "n_delivered": rr._n_delivered,
                },
                status="ok" if outcome == "ok" else f"shed:{outcome}",
            )
        if deliver:
            self._pending_finished.append(rr)

    def _update_gauges_locked(self) -> None:
        self._metrics["routable"].set(
            sum(1 for r in self.cluster if r.routable)
        )

    # -- supervisor thread (threaded mode) ------------------------------------
    def start(self) -> "ReplicaRouter":
        """Start every live replica's pump thread plus the router
        supervisor (probe + failover + forwarding) until :meth:`stop`."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            for replica in self.cluster:
                if replica.alive:
                    replica.frontend.start()
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run_loop, daemon=True, name="replica-router"
            )
            self._thread.start()
        return self

    def _run_loop(self) -> None:
        while not self._stop.is_set():
            try:
                with self._lock:
                    # replicas pump themselves in threaded mode; the tick's
                    # finished list is drained here (terminal state already
                    # landed on the handles)
                    self._tick_locked()
            except Exception as exc:  # the supervisor must outlive any single bad tick — a failed probe round is a flight event, not a router death
                _flight.record_event(
                    "router_tick_failed",
                    error=f"{type(exc).__name__}: {exc}"[:200],
                )
            self._stop.wait(timeout=self.config.probe_interval_s)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        with self._lock:
            if self._thread is t:
                self._thread = None
        for replica in self.cluster:
            replica.frontend.stop()

    # -- fleet observer -------------------------------------------------------
    def attach_observer(self, observer: Any) -> None:
        """Attach a fleet observer (``observability.aggregate.
        ClusterObserver``): its ``on_tick_locked(now)`` runs every probe
        tick and ``on_transition_locked(replica, frm, to, now)`` on every
        replica state transition — both UNDER the router lock (lock order
        router -> frontend -> engine still holds for anything they read).
        One observer at a time; detach with None."""
        with self._lock:
            self._observer = observer

    @property
    def observer(self) -> Optional[Any]:
        with self._lock:
            return self._observer

    def set_ttft_window(self, window_s: float) -> None:
        """Age horizon for the TTFT p99 the SLO monitor samples (the
        observer aligns it to its slow burn window at attach)."""
        with self._lock:
            self._ttft_window_s = float(window_s)

    def slo_sample(self) -> Dict[str, float]:
        """Cumulative cluster-truth counters for the burn-rate monitor (the
        public form; the observer reads the locked form from the probe
        loop). Host-side accounting — valid with metrics off."""
        with self._lock:
            return self._slo_sample_locked(time.perf_counter())

    def _slo_sample_locked(self, now: float) -> Dict[str, float]:
        horizon = now - self._ttft_window_s
        while self._ttfts and self._ttfts[0][0] < horizon:
            self._ttfts.popleft()
        if self._ttfts:
            ordered = sorted(v for _, v in self._ttfts)
            p99 = ordered[min(len(ordered) - 1, int(0.99 * len(ordered)))]
        else:
            p99 = 0.0
        return {
            "terminals": float(self._terminals),
            "ok": float(self._ok),
            "ok_in_slo": float(self._ok_in_slo),
            "dispatches": float(self._dispatches),
            "redispatches": float(self._redispatch_count),
            "ttft_p99_s": float(p99),
        }

    # -- introspection --------------------------------------------------------
    def has_work(self) -> bool:
        with self._lock:
            if self._live or self._redispatch_q:
                return True
            return any(
                r.alive and r.frontend.engine.has_work() for r in self.cluster
            )

    def routing_counters(self) -> Dict[str, int]:
        """Route-kind counters (affinity/spill/failover/round_robin); their
        sum equals :meth:`dispatch_count` exactly. The routing LOG is a
        bounded recent window (``routing_log_size``) — reconcile counters
        against the monotonic count, not the log length."""
        with self._lock:
            return dict(self._counters)

    def dispatch_count(self) -> int:
        """Monotonic count of accepted routing decisions — what the route
        counters sum to, regardless of how much log the window retains."""
        with self._lock:
            return self._dispatches

    def shed_counters(self) -> Dict[str, int]:
        """Router-originated sheds by reason (replica-frontend sheds are
        counted by the frontends)."""
        with self._lock:
            return dict(self._shed_counts)

    def salvaged_count(self) -> int:
        with self._lock:
            return self._salvaged

    def routing_log(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._routing_log)

    def failover_latencies(self) -> List[float]:
        with self._lock:
            return list(self._failover_latencies)

    def live_requests(self) -> List[RouterRequest]:
        with self._lock:
            return list(self._live.values())

    def pending_redispatch(self) -> List[RouterRequest]:
        with self._lock:
            return list(self._redispatch_q)

    def snapshot(self) -> Dict[str, Any]:
        """Cluster health view (the multi-replica /healthz payload)."""
        with self._lock:
            return {
                "replicas": {
                    r.name: {
                        "state": r.state,
                        "generation": r.generation,
                        "probe_failures": r.probe_failures,
                    }
                    for r in self.cluster
                },
                "routable_replicas": sum(1 for r in self.cluster if r.routable),
                "live_requests": len(self._live),
                "pending_redispatch": len(self._redispatch_q),
                "routes": dict(self._counters),
                "sheds": dict(self._shed_counts),
                "salvaged": self._salvaged,
            }
