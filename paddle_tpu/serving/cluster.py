"""Replica lifecycle for cluster-scale serving.

One :class:`~paddle_tpu.serving.frontend.ServingFrontend` is one box; the
router in :mod:`paddle_tpu.serving.router` serves N of them. This module
owns what a replica *is* above the single-process serving stack — the
reference fork's ``fleet``/elastic process-lifecycle layer, shaped for
in-process replicas:

- **health states** — ``UP`` → ``DEGRADED`` (probe failures or sustained
  overload; still routable) → ``DEAD`` (engine permanently failed, pump
  thread died, or probes exhausted; never routable again on this
  generation). ``DRAINING`` is the administrative sibling: intake stops,
  live work finishes, the replica's hash-ring share remaps — all without a
  single shed.
- **kill** — :meth:`Replica.kill` models a whole-replica death the way the
  engine's permanent-failure seam does: the engine is marked broken and the
  frontend fails every live stream explicitly (salvaging results the engine
  already finished via ``drain_finished()``). The ``replica.kill`` fault
  site in the router's probe loop drives this path deterministically on CPU
  CI.
- **revive** — a DEAD replica's engine lost its KV state for good; revival
  builds a FRESH frontend through the cluster's factory (a new process in
  the real deployment), bumping the replica's ``generation`` so stale
  handles can never be confused with the new instance. The replica's name —
  and therefore its rendezvous-hash share — is stable across generations.

The router drives all state *transitions* (it owns the probe loop, the
flight-recorder events and the failover machinery); this module only holds
the state and the lifecycle verbs.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional

from paddle_tpu.serving.frontend import ServingFrontend

__all__ = [
    "REPLICA_DEAD",
    "REPLICA_DEGRADED",
    "REPLICA_DRAINING",
    "REPLICA_UP",
    "Replica",
    "ReplicaCluster",
]

REPLICA_UP = "up"
REPLICA_DEGRADED = "degraded"
REPLICA_DRAINING = "draining"
REPLICA_DEAD = "dead"

# gauge encoding for serving_router_replica_state{replica}
STATE_CODES = {
    REPLICA_UP: 0,
    REPLICA_DEGRADED: 1,
    REPLICA_DRAINING: 2,
    REPLICA_DEAD: 3,
}


class Replica:
    """One named serving replica: a frontend plus router-side health state.

    All mutable fields are owned by the router and mutated only under the
    router's lock; the frontend beneath does its own locking.

    Under tensor parallelism the replica IS the shard group: its engine owns
    a whole ``['tp']`` mesh, so the health unit, the kill/revive unit and the
    failover unit are all ``tp_degree`` chips at once — replica death takes
    the mesh out of rotation in one routing event, and ``revive`` rebuilds
    the sharded pools through the factory (``distributed/launch`` + elastic
    own the real process lifecycle in a multi-host deployment)."""

    def __init__(self, name: str, frontend: ServingFrontend) -> None:
        self.name = str(name)
        self.frontend = frontend
        self.state = REPLICA_UP
        self.generation = 0
        # consecutive probe failures (health_snapshot raised) and pump
        # failures (inline pump raised); reset on any success
        self.probe_failures = 0
        self.pump_failures = 0
        # perf_counter instant the router marked this replica DEAD (the
        # failover-latency anchor); None while not dead
        self.death_ts: Optional[float] = None
        # once-only marker for the replica_drained flight event
        self.drained_logged = False

    @property
    def routable(self) -> bool:
        """New intake may be routed here (DRAINING keeps serving what it
        already accepted, but takes nothing new)."""
        return self.state in (REPLICA_UP, REPLICA_DEGRADED)

    @property
    def alive(self) -> bool:
        return self.state != REPLICA_DEAD

    @property
    def tp_degree(self) -> int:
        """Chips in this replica's shard group (1 = single-chip engine)."""
        return getattr(self.frontend.engine, "tp_degree", 1)

    def kill(self, why: str = "replica killed") -> None:
        """Model a whole-replica death: the engine is permanently failed and
        the frontend salvages/fails every live stream (idempotent). The
        router's next probe observes ``broken`` and runs the
        death-as-routing-event path (salvage delivery + re-dispatch)."""
        self.frontend.engine.mark_failed(why)
        self.frontend.fail(why)

    def __repr__(self) -> str:
        return (
            f"Replica({self.name!r}, state={self.state!r}, "
            f"gen={self.generation})"
        )


class ReplicaCluster:
    """A named set of replicas built from one factory.

    ``factory(name)`` must return a fresh :class:`ServingFrontend` (its own
    engine; replicas must serve the SAME model weights or failover
    re-generation would not be deterministic). The factory is retained so
    :meth:`revive` can rebuild a DEAD replica's frontend in place."""

    def __init__(
        self,
        factory: Callable[[str], ServingFrontend],
        names: Iterable[str],
    ) -> None:
        self._factory = factory
        self.replicas: Dict[str, Replica] = {}
        for name in names:
            if name in self.replicas:
                raise ValueError(f"duplicate replica name {name!r}")
            self.replicas[name] = Replica(name, self._build(name))
        if not self.replicas:
            raise ValueError("a cluster needs at least one replica")

    def _build(self, name: str) -> ServingFrontend:
        """Build one replica frontend and bind its observability scope —
        resolved exactly once here, so every metric series, flight event and
        sampled span the replica ever emits is attributable to ``name``."""
        frontend = self._factory(name)
        frontend.set_replica_scope(name)
        return frontend

    def __iter__(self):
        return iter(self.replicas.values())

    def __len__(self) -> int:
        return len(self.replicas)

    def get(self, name: str) -> Optional[Replica]:
        return self.replicas.get(name)

    def names(self) -> List[str]:
        return list(self.replicas)

    def revive(self, name: str) -> Replica:
        """Rebuild a DEAD replica's frontend through the factory (a fresh
        process in a real deployment): same name — same rendezvous share —
        new generation, state back to UP. Raises on a replica that is not
        DEAD (live state must never be silently discarded)."""
        replica = self.replicas[name]
        if replica.state != REPLICA_DEAD:
            raise RuntimeError(
                f"replica {name!r} is {replica.state}, not dead; "
                "drain it before rebuilding"
            )
        replica.frontend = self._build(name)
        replica.generation += 1
        replica.state = REPLICA_UP
        replica.probe_failures = 0
        replica.pump_failures = 0
        replica.death_ts = None
        replica.drained_logged = False
        return replica
