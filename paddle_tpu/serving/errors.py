"""Serving-layer error taxonomy.

Intake *validation* failures are the engine's typed :class:`IntakeError`
subclasses (re-exported here) — they mean the request itself is malformed
and map to HTTP 4xx. :class:`Overloaded` means the request was fine but the
system is shedding load — HTTP 429 with a ``Retry-After`` hint; the client
should back off and retry, not fix anything.
"""

from __future__ import annotations

from paddle_tpu.inference.engine import (  # noqa: F401  (re-export for HTTP mapping)
    EmptyPromptError,
    IntakeError,
    InvalidTokenBudgetError,
    PromptTooLongError,
    RequestTooLongError,
    RequestUnservableError,
)

__all__ = [
    "Overloaded",
    "ServingError",
    "IntakeError",
    "EmptyPromptError",
    "InvalidTokenBudgetError",
    "PromptTooLongError",
    "RequestTooLongError",
    "RequestUnservableError",
]


class ServingError(RuntimeError):
    """Base class for serving-frontend errors that are NOT intake validation."""


class Overloaded(ServingError):
    """The frontend refused intake to protect itself (bounded queue full, or
    the overload controller is shedding this priority class).

    ``retry_after`` is the backoff hint in seconds (also sent as the HTTP
    ``Retry-After`` header); ``reason`` is the shed-accounting label
    (``queue_full`` / ``overload``) the same request was counted under in
    ``serving_shed_total``."""

    def __init__(self, message: str, retry_after: float = 1.0, reason: str = "overload") -> None:
        super().__init__(message)
        self.retry_after = float(retry_after)
        self.reason = str(reason)
