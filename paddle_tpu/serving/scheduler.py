"""Weighted fair admission: priority classes + per-tenant fairness.

Replaces the engine's raw FIFO ``_admit_waiting`` order through the
:class:`~paddle_tpu.inference.engine.AdmissionPolicy` hook. Two layers:

- **across priority classes** — stride scheduling: class ``p`` with weight
  ``w_p`` holds a virtual "pass" that advances by ``1/w_p`` per admission,
  and the class with the smallest pass is served next. Over a sustained
  backlog each class's admission share converges to ``w_p / Σw`` — strict
  enough that interactive traffic keeps flowing under overload, but unlike
  strict priority a starving best-effort class still advances (its pass
  falls behind and eventually wins a turn);
- **within a class, across tenants** — round-robin keyed on the last tenant
  served, so one chatty tenant cannot monopolize its class; within a tenant,
  arrival order (oldest first).

No head-of-line capacity skipping: if the fair-share winner does not fit the
pool's unreserved blocks, admission stops for this boundary (same
no-starvation guarantee as the engine's FIFO default — a large request is
never indefinitely bypassed by smaller ones).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

from paddle_tpu.inference.engine import AdmissionPolicy, InferenceRequest

__all__ = ["WeightedFairPolicy", "DEFAULT_WEIGHTS"]

# priority class -> stride weight (higher weight = larger admission share);
# keys are the Priority.* constants (0 interactive / 1 standard / 2 best_effort)
DEFAULT_WEIGHTS: Dict[int, float] = {0: 4.0, 1: 2.0, 2: 1.0}


class WeightedFairPolicy(AdmissionPolicy):
    def __init__(self, weights: Optional[Dict[int, float]] = None) -> None:
        self.weights = dict(DEFAULT_WEIGHTS if weights is None else weights)
        for p, w in self.weights.items():
            if w <= 0:
                raise ValueError(f"priority {p} weight must be > 0, got {w}")
        self._pass: Dict[int, float] = {}  # priority -> stride pass value
        self._contending: set = set()  # classes waiting at the last select()
        self._last_tenant: Dict[int, str] = {}  # priority -> last tenant served

    def _weight(self, priority: int) -> float:
        return self.weights.get(priority, 1.0)

    def select(
        self,
        waiting: Sequence[InferenceRequest],
        can_fit: Callable[[InferenceRequest], bool],
    ) -> Optional[InferenceRequest]:
        if not waiting:
            return None
        by_prio: Dict[int, list] = {}
        for req in waiting:
            by_prio.setdefault(req.priority, []).append(req)

        # a class joining (or REjoining after idle) starts at the incumbents'
        # minimum pass — it must not burst through a backlog's worth of
        # "missed" turns it was never contending for. Only newly-arrived
        # classes are clamped: a continuously-contending class keeps the low
        # pass it legitimately earned (clamping incumbents would erase the
        # fair-share advantage the stride exists to grant). Incumbent = was
        # waiting at the previous select() AND still is.
        incumbents = self._contending & set(by_prio)
        if incumbents:
            floor = min(self._pass.get(p, 0.0) for p in incumbents)
        else:
            # everything drained and the mix restarts fresh: stale credit
            # from a past regime must not decide the new one
            self._pass.clear()
            floor = 0.0
        for p in by_prio:
            if p not in incumbents:
                self._pass[p] = max(self._pass.get(p, floor), floor)
            else:
                self._pass.setdefault(p, floor)
        self._contending = set(by_prio)

        # smallest pass wins; ties break toward the more important class
        prio = min(by_prio, key=lambda p: (self._pass[p], p))

        # round-robin across the class's tenants, starting after the tenant
        # served last time; within a tenant, arrival (waiting) order
        tenants = sorted({r.tenant for r in by_prio[prio]})
        last = self._last_tenant.get(prio)
        if last is not None and last in tenants:
            i = tenants.index(last) + 1
            tenants = tenants[i:] + tenants[:i]
        elif last is not None:
            tenants = sorted(tenants, key=lambda t: (t <= last, t))
        tenant = tenants[0]
        req = next(r for r in by_prio[prio] if r.tenant == tenant)

        if not can_fit(req):
            return None  # no capacity skipping: wait for blocks to free up
        self._pass[prio] += 1.0 / self._weight(prio)
        self._last_tenant[prio] = tenant
        return req
