"""Open-loop overload bench harness: seeded Poisson arrivals, goodput/SLO
reporting.

Open-loop means arrivals do NOT wait for completions — the generator submits
on its own clock, exactly like independent users do, so when the offered
rate exceeds capacity the backlog grows and the frontend's admission /
shedding / deadline machinery is what is actually being measured (a
closed-loop bench self-throttles and can never produce this regime; the
offline tokens/s bench never exercises admit/evict/finished at all).

Everything is derived from one seed: inter-arrival gaps (exponential at the
offered rate), the tenant/priority class of each arrival (weighted mix), and
prompt/budget sizes — reruns are comparable and a failing campaign replays
from its seed.

:func:`run_open_loop` drives the frontend inline (no pump thread): each
iteration submits every arrival whose scheduled time has come, then pumps
once. The report carries the numbers a deployment lives on — goodput
(tokens of requests that finished inside their SLO), per-class SLO
attainment, shed/deadline counts by reason — plus the 2-compile honesty
check via the recompile watchdog.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu.observability.recompile import GLOBAL_WATCHDOG
from paddle_tpu.observability.serving import priority_name
from paddle_tpu.serving.errors import IntakeError, Overloaded
from paddle_tpu.serving.frontend import Priority, ServingFrontend, ServingRequest

__all__ = ["TrafficClass", "Arrival", "poisson_arrivals", "run_open_loop",
           "run_cluster_open_loop", "measure_sustainable_rate"]


@dataclass(frozen=True)
class TrafficClass:
    """One slice of the offered mix. ``share`` values are relative weights
    (normalized across the mix); ``slo_s`` becomes each request's TTL —
    finishing past it is an SLO miss, shedding at it is deadline enforcement."""

    tenant: str = "default"
    priority: int = Priority.STANDARD
    share: float = 1.0
    prompt_len: tuple = (4, 12)  # inclusive range drawn per request
    max_new_tokens: tuple = (4, 16)
    slo_s: Optional[float] = None


@dataclass
class Arrival:
    t: float  # seconds from bench start
    cls: TrafficClass
    prompt: np.ndarray
    max_new_tokens: int


def poisson_arrivals(
    rate_rps: float,
    n: int,
    mix: Sequence[TrafficClass],
    seed: int,
    vocab_size: int = 1000,
) -> List[Arrival]:
    """``n`` arrivals with Exp(1/rate) inter-arrival gaps; class, prompt and
    budget all drawn from the same seeded generator."""
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    if not mix:
        raise ValueError("traffic mix must not be empty")
    rng = np.random.default_rng(seed)
    shares = np.asarray([c.share for c in mix], np.float64)
    shares = shares / shares.sum()
    out: List[Arrival] = []
    t = 0.0
    for _ in range(int(n)):
        t += float(rng.exponential(1.0 / rate_rps))
        cls = mix[int(rng.choice(len(mix), p=shares))]
        plen = int(rng.integers(cls.prompt_len[0], cls.prompt_len[1] + 1))
        out.append(
            Arrival(
                t=t,
                cls=cls,
                prompt=rng.integers(0, vocab_size, (plen,)).astype(np.int32),
                max_new_tokens=int(
                    rng.integers(cls.max_new_tokens[0], cls.max_new_tokens[1] + 1)
                ),
            )
        )
    return out


@dataclass
class _ClassStats:
    offered: int = 0
    accepted: int = 0
    rejected: int = 0  # Overloaded at intake
    ok_in_slo: int = 0
    ok_late: int = 0
    shed: int = 0  # accepted then shed (deadline/cancel/engine failure)
    goodput_tokens: int = 0
    tokens: int = 0


def run_open_loop(
    frontend: ServingFrontend,
    arrivals: Sequence[Arrival],
    max_wall_s: float = 120.0,
    on_iteration=None,
) -> Dict[str, Any]:
    """Replay ``arrivals`` against ``frontend`` (driven inline) and report.
    ``on_iteration(frontend)``, when given, runs after every pump — the
    overload test uses it to assert bounded-queue/accounting invariants
    while the storm is live."""
    watchdog_before = {
        fn: rec["count"]
        for fn, rec in GLOBAL_WATCHDOG.report().items()
        if fn.startswith("ContinuousBatchingEngine.")
    }
    stats: Dict[str, _ClassStats] = {}
    live: List[ServingRequest] = []
    finished: List[ServingRequest] = []

    def _cls_key(cls: TrafficClass) -> str:
        return f"{cls.tenant}/{priority_name(cls.priority)}"

    pending = list(arrivals)
    pending.reverse()  # pop() from the back == chronological order
    start = time.perf_counter()
    while pending or frontend.engine.has_work() or live:
        now = time.perf_counter() - start
        if now > max_wall_s:
            break
        while pending and pending[-1].t <= now:
            a = pending.pop()
            st = stats.setdefault(_cls_key(a.cls), _ClassStats())
            st.offered += 1
            try:
                handle = frontend.submit(
                    a.prompt,
                    max_new_tokens=a.max_new_tokens,
                    priority=a.cls.priority,
                    tenant=a.cls.tenant,
                    ttl_s=a.cls.slo_s,
                )
            except Overloaded:
                st.rejected += 1
                continue
            except IntakeError:
                st.rejected += 1
                continue
            st.accepted += 1
            handle._cls_key = _cls_key(a.cls)  # bench-local annotation
            live.append(handle)
        for handle in frontend.pump():
            if handle in live:  # ignore leftovers from a prior (calibration) run
                live.remove(handle)
                finished.append(handle)
        if on_iteration is not None:
            on_iteration(frontend)

    wall = time.perf_counter() - start
    for handle in finished:
        st = stats[handle._cls_key]
        ntok = len(handle.inner.generated)
        st.tokens += ntok
        if handle.outcome == "ok":
            if handle.met_deadline:
                st.ok_in_slo += 1
                st.goodput_tokens += ntok
            else:
                st.ok_late += 1
        else:
            st.shed += 1

    watchdog_after = {
        fn: rec["count"]
        for fn, rec in GLOBAL_WATCHDOG.report().items()
        if fn.startswith("ContinuousBatchingEngine.")
    }
    per_class = {}
    for key, st in sorted(stats.items()):
        per_class[key] = {
            "offered": st.offered,
            "accepted": st.accepted,
            "rejected_at_intake": st.rejected,
            "finished_in_slo": st.ok_in_slo,
            "finished_late": st.ok_late,
            "shed_after_accept": st.shed,
            "tokens": st.tokens,
            "goodput_tokens": st.goodput_tokens,
            # SLO attainment over EVERYTHING offered: a rejected or shed
            # request is an SLO failure, not a statistical no-show
            "slo_attainment": round(st.ok_in_slo / st.offered, 4) if st.offered else 0.0,
        }
    total_goodput = sum(st.goodput_tokens for st in stats.values())
    total_tokens = sum(st.tokens for st in stats.values())
    return {
        "wall_s": round(wall, 3),
        "arrivals": len(arrivals),
        "undelivered_arrivals": len(pending) + len(live),  # hit max_wall_s
        "goodput_tokens_per_sec": round(total_goodput / wall, 2) if wall else 0.0,
        "tokens_per_sec": round(total_tokens / wall, 2) if wall else 0.0,
        "per_class": per_class,
        "compiles_during_run": {
            fn: watchdog_after.get(fn, 0) - watchdog_before.get(fn, 0)
            for fn in set(watchdog_before) | set(watchdog_after)
        },
        "compiled_signatures_total": sum(watchdog_after.values()),
        # speculative-decoding acceptance over the run (all-zeros when the
        # engine runs with FLAGS_spec_decode off) — goodput and acceptance
        # rate belong in the same record: speculation only helps goodput
        # when the workload actually accepts drafts
        "spec_decode": frontend.engine.spec_decode_stats(),
    }


def run_cluster_open_loop(
    router,
    arrivals: Sequence[Arrival],
    max_wall_s: float = 120.0,
    on_iteration=None,
) -> Dict[str, Any]:
    """Cluster-level open-loop bench: replay ``arrivals`` against a
    :class:`~paddle_tpu.serving.router.ReplicaRouter` (driven inline) and
    report the numbers a fleet lives on — AGGREGATE goodput and per-class
    SLO attainment across every replica, plus the cluster-only signals:
    routing-decision counters (affinity/spill/failover) that reconcile with
    the monotonic dispatch count, failover latency p99, salvage/re-dispatch
    accounting,
    and the recompile ledger (a replica death must be absorbed by ROUTING,
    never by a surviving engine recompiling).

    ``on_iteration(router, now_s)`` runs after every pump — the kill-mid-
    storm acceptance test uses it to trip the ``replica.kill`` fault site at
    a chosen instant and to assert invariants while the storm is live."""
    from paddle_tpu.serving.router import RouterRequest  # typing/doc only

    watchdog_before = {
        fn: rec["count"]
        for fn, rec in GLOBAL_WATCHDOG.report().items()
        if fn.startswith("ContinuousBatchingEngine.")
    }
    stats: Dict[str, _ClassStats] = {}
    live: List[RouterRequest] = []
    finished: List[RouterRequest] = []

    def _cls_key(cls: TrafficClass) -> str:
        return f"{cls.tenant}/{priority_name(cls.priority)}"

    pending = list(arrivals)
    pending.reverse()  # pop() from the back == chronological order
    start = time.perf_counter()
    while pending or router.has_work() or live:
        now = time.perf_counter() - start
        if now > max_wall_s:
            break
        while pending and pending[-1].t <= now:
            a = pending.pop()
            st = stats.setdefault(_cls_key(a.cls), _ClassStats())
            st.offered += 1
            try:
                handle = router.submit(
                    a.prompt,
                    max_new_tokens=a.max_new_tokens,
                    priority=a.cls.priority,
                    tenant=a.cls.tenant,
                    ttl_s=a.cls.slo_s,
                )
            except (Overloaded, IntakeError):
                st.rejected += 1
                continue
            st.accepted += 1
            handle._cls_key = _cls_key(a.cls)  # bench-local annotation
            live.append(handle)
        for handle in router.pump():
            if handle in live:
                live.remove(handle)
                finished.append(handle)
        if on_iteration is not None:
            on_iteration(router, now)

    wall = time.perf_counter() - start
    for handle in finished:
        st = stats[handle._cls_key]
        ntok = len(handle.tokens())
        st.tokens += ntok
        if handle.outcome == "ok":
            if handle.met_deadline:
                st.ok_in_slo += 1
                st.goodput_tokens += ntok
            else:
                st.ok_late += 1
        else:
            st.shed += 1

    watchdog_after = {
        fn: rec["count"]
        for fn, rec in GLOBAL_WATCHDOG.report().items()
        if fn.startswith("ContinuousBatchingEngine.")
    }
    per_class = {}
    for key, st in sorted(stats.items()):
        per_class[key] = {
            "offered": st.offered,
            "accepted": st.accepted,
            "rejected_at_intake": st.rejected,
            "finished_in_slo": st.ok_in_slo,
            "finished_late": st.ok_late,
            "shed_after_accept": st.shed,
            "tokens": st.tokens,
            "goodput_tokens": st.goodput_tokens,
            "slo_attainment": round(st.ok_in_slo / st.offered, 4) if st.offered else 0.0,
        }
    total_goodput = sum(st.goodput_tokens for st in stats.values())
    total_tokens = sum(st.tokens for st in stats.values())
    lats = sorted(router.failover_latencies())
    p99 = lats[min(len(lats) - 1, int(0.99 * len(lats)))] if lats else 0.0
    routes = router.routing_counters()
    routed = sum(routes.values())
    return {
        "wall_s": round(wall, 3),
        "arrivals": len(arrivals),
        "undelivered_arrivals": len(pending) + len(live),  # hit max_wall_s
        "goodput_tokens_per_sec": round(total_goodput / wall, 2) if wall else 0.0,
        "tokens_per_sec": round(total_tokens / wall, 2) if wall else 0.0,
        "per_class": per_class,
        "routes": routes,
        "dispatches": router.dispatch_count(),
        "affinity_hit_rate": round(routes.get("affinity", 0) / routed, 4) if routed else 0.0,
        "failover_latency_p99_ms": round(p99 * 1e3, 3),
        "failovers": len(lats),
        "salvaged": router.salvaged_count(),
        "router_sheds": router.shed_counters(),
        "replica_states": {r.name: r.state for r in router.cluster},
        "compiles_during_run": {
            fn: watchdog_after.get(fn, 0) - watchdog_before.get(fn, 0)
            for fn in set(watchdog_before) | set(watchdog_after)
        },
        "compiled_signatures_total": sum(watchdog_after.values()),
    }


def measure_sustainable_rate(
    frontend: ServingFrontend,
    n_requests: int,
    seed: int,
    prompt_len: tuple = (4, 12),
    max_new_tokens: tuple = (4, 16),
    vocab_size: int = 1000,
) -> float:
    """Closed-loop calibration: run ``n_requests`` through the engine with
    the queue kept fed and return the completion rate (requests/sec). An
    open-loop bench offering ``2 *`` this rate is guaranteed into overload.
    A two-request warmup runs (and completes) before the timer starts, so
    both engine signatures are compiled outside the measured window — the
    rate reflects steady-state capacity and the overload run that follows
    adds no compiles of its own."""
    rng = np.random.default_rng(seed)
    n = int(n_requests)
    warm = [
        frontend.submit(
            rng.integers(0, vocab_size, (int(prompt_len[0]),)).astype(np.int32),
            max_new_tokens=int(max_new_tokens[0]),
            priority=Priority.STANDARD,
        )
        for _ in range(2)
    ]
    while not all(h.finished for h in warm):
        frontend.pump()
    t0 = time.perf_counter()
    submitted = done = 0
    while done < n:
        while submitted < n:
            try:
                # same INCLUSIVE ranges as poisson_arrivals: calibration must
                # price the same per-request work as the storm it calibrates
                plen = int(rng.integers(prompt_len[0], prompt_len[1] + 1))
                frontend.submit(
                    rng.integers(0, vocab_size, (plen,)).astype(np.int32),
                    max_new_tokens=int(
                        rng.integers(max_new_tokens[0], max_new_tokens[1] + 1)
                    ),
                    priority=Priority.STANDARD,
                )
            except Overloaded:
                break  # bounded intake: drain a little, then keep feeding
            submitted += 1
        done += len(frontend.pump())
    dt = time.perf_counter() - t0
    return n / dt if dt > 0 else float("inf")
