"""SLO-aware serving layer over the continuous-batching engine.

The host-side policy stack between clients and ``engine.step()``:

- :mod:`.frontend` — :class:`ServingFrontend`: bounded intake, per-request
  deadlines/TTLs, priority classes with weighted per-tenant fair admission,
  hysteresis load shedding and graceful degradation;
- :mod:`.scheduler` — :class:`WeightedFairPolicy`, the stride scheduler
  installed as the engine's admission policy;
- :mod:`.http` — the streaming localhost HTTP endpoint
  (``start_serving_server``, ``FLAGS_serving_port``);
- :mod:`.loadgen` — the open-loop Poisson arrival harness behind bench.py's
  ``serving_goodput`` record and the overload acceptance tests;
- :mod:`.errors` — :class:`Overloaded` (429) and the re-exported typed
  :class:`IntakeError` taxonomy (4xx).

See README "Serving & SLOs" for thresholds, status mapping and flags.
"""

from paddle_tpu.serving.errors import (  # noqa: F401
    EmptyPromptError,
    IntakeError,
    InvalidTokenBudgetError,
    Overloaded,
    PromptTooLongError,
    RequestTooLongError,
    RequestUnservableError,
    ServingError,
)
from paddle_tpu.serving.frontend import (  # noqa: F401
    Hysteresis,
    OverloadController,
    Priority,
    ServingConfig,
    ServingFrontend,
    ServingRequest,
)
from paddle_tpu.serving.http import (  # noqa: F401
    start_serving_server,
    stop_serving_server,
)
from paddle_tpu.serving.scheduler import WeightedFairPolicy  # noqa: F401

__all__ = [
    "EmptyPromptError",
    "Hysteresis",
    "IntakeError",
    "InvalidTokenBudgetError",
    "Overloaded",
    "OverloadController",
    "Priority",
    "PromptTooLongError",
    "RequestTooLongError",
    "RequestUnservableError",
    "ServingConfig",
    "ServingError",
    "ServingFrontend",
    "ServingRequest",
    "WeightedFairPolicy",
    "start_serving_server",
    "stop_serving_server",
]
