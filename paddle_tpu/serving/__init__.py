"""SLO-aware serving layer over the continuous-batching engine.

The host-side policy stack between clients and ``engine.step()``:

- :mod:`.frontend` — :class:`ServingFrontend`: bounded intake, per-request
  deadlines/TTLs, priority classes with weighted per-tenant fair admission,
  hysteresis load shedding and graceful degradation;
- :mod:`.scheduler` — :class:`WeightedFairPolicy`, the stride scheduler
  installed as the engine's admission policy;
- :mod:`.http` — the streaming localhost HTTP endpoint
  (``start_serving_server``, ``FLAGS_serving_port``); also serves a
  :class:`ReplicaRouter` for the thin multi-replica mode;
- :mod:`.cluster` / :mod:`.router` — cluster-scale serving:
  :class:`ReplicaCluster` (replica lifecycle: UP/DEGRADED/DRAINING/DEAD,
  kill/revive) and :class:`ReplicaRouter` (rendezvous prefix-affinity
  routing, health-gated failover with salvage + bounded deadline-aware
  re-dispatch, drain, cross-replica spill);
- :mod:`.loadgen` — the open-loop Poisson arrival harness behind bench.py's
  ``serving_goodput`` / ``cluster_goodput`` records and the overload
  acceptance tests;
- :mod:`.errors` — :class:`Overloaded` (429) and the re-exported typed
  :class:`IntakeError` taxonomy (4xx).

See README "Serving & SLOs" and "Cluster serving & failover" for
thresholds, status mapping and flags.
"""

from paddle_tpu.serving.cluster import (  # noqa: F401
    Replica,
    ReplicaCluster,
)
from paddle_tpu.serving.errors import (  # noqa: F401
    EmptyPromptError,
    IntakeError,
    InvalidTokenBudgetError,
    Overloaded,
    PromptTooLongError,
    RequestTooLongError,
    RequestUnservableError,
    ServingError,
)
from paddle_tpu.serving.frontend import (  # noqa: F401
    Hysteresis,
    OverloadController,
    Priority,
    ServingConfig,
    ServingFrontend,
    ServingRequest,
)
from paddle_tpu.serving.http import (  # noqa: F401
    start_serving_server,
    stop_serving_server,
)
from paddle_tpu.serving.router import (  # noqa: F401
    ReplicaRouter,
    RouterConfig,
    RouterRequest,
)
from paddle_tpu.serving.scheduler import WeightedFairPolicy  # noqa: F401

__all__ = [
    "EmptyPromptError",
    "Hysteresis",
    "IntakeError",
    "InvalidTokenBudgetError",
    "Overloaded",
    "OverloadController",
    "Priority",
    "PromptTooLongError",
    "Replica",
    "ReplicaCluster",
    "ReplicaRouter",
    "RequestTooLongError",
    "RequestUnservableError",
    "RouterConfig",
    "RouterRequest",
    "ServingConfig",
    "ServingError",
    "ServingFrontend",
    "ServingRequest",
    "WeightedFairPolicy",
    "start_serving_server",
    "stop_serving_server",
]
