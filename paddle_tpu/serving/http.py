"""Streaming generation endpoint over stdlib ``http.server``.

Same opt-in localhost pattern as ``observability.start_metrics_server``:
nothing listens unless :func:`start_serving_server` is called; with no
explicit port it reads ``FLAGS_serving_port`` (0 = disabled).

Routes:

- ``POST /v1/generate`` — body ``{"prompt": [ids...], "max_new_tokens": n,
  "priority": "interactive"|"standard"|"best_effort"|int, "tenant": str,
  "ttl_s": seconds, "eos_token_id": id, "stream": bool}``. With
  ``stream`` (default true) the response is ``application/x-ndjson``: one
  ``{"token": id}`` line per generated token AS IT IS PRODUCED, then a final
  ``{"done": true, "outcome": ..., "tokens": n}`` line; without it, one JSON
  object after the request finishes.
- ``GET /healthz`` — the frontend's :meth:`snapshot` (overload level, queue
  depth, pool utilization). In multi-replica mode with a
  :class:`~paddle_tpu.observability.aggregate.ClusterObserver` attached to
  the router, this is the observer's fleet view instead (router state,
  per-replica lifecycle + tp_degree + kv-tier + spec acceptance, the SLO
  burn-rate block).
- ``GET /metrics`` — the same replica-labeled Prometheus text exposition
  ``observability.start_metrics_server`` serves (one shared renderer,
  ``render_exposition`` — single- and multi-replica formats agree by
  construction).

Tracing: a ``traceparent`` request header (W3C shape, see
``observability.tracing``) continues the caller's trace through this hop;
the response carries a ``traceparent`` header naming the request's root
span so the client can link its own spans. With ``FLAGS_trace_sample_rate``
at 0 the header is ignored at the cost of one cached-bool read.

Status mapping: malformed body / intake validation → **400** (typed
``IntakeError``, no message string-matching), unknown route → **404**,
shedding → **429** with a ``Retry-After`` header from the
:class:`Overloaded` hint, engine failure mid-request → **500**. A client
that disconnects mid-stream gets its request cancelled — the engine slot is
evicted and its KV blocks reclaimed — so an impatient client cannot leak
pool capacity. Each response counts into
``serving_http_responses_total{code}``.

**Multi-replica mode**: pass a
:class:`~paddle_tpu.serving.router.ReplicaRouter` instead of a frontend —
it exposes the same ``submit``/``cancel``/``snapshot``/``start``/``stop``
surface, so the endpoint serves the whole cluster through one port:
``/healthz`` returns per-replica states plus routing counters, and a
replica death mid-stream fails over transparently (the handler keeps
streaming from the same handle).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from paddle_tpu.flags import GLOBAL_FLAGS
from paddle_tpu.observability.serving import serving_metrics
from paddle_tpu.serving.errors import IntakeError, Overloaded
from paddle_tpu.serving.frontend import Priority, ServingFrontend
from paddle_tpu.testing.faults import InjectedFault, fault_point

__all__ = ["start_serving_server", "stop_serving_server"]

# cached once: families are permanent registry objects; re-resolving all of
# them through the registry lock on every response would be pure waste
_RESPONSES = serving_metrics()["responses"]


class _BadRequest(ValueError):
    pass


def _parse_body(raw: bytes) -> Dict[str, Any]:
    """Validate the request body; returns ``submit()`` kwargs plus
    ``stream``. Anything wrong raises :class:`_BadRequest` → 400."""
    try:
        body = json.loads(raw.decode("utf-8") if raw else "{}")
    except (ValueError, UnicodeDecodeError) as exc:
        raise _BadRequest(f"body is not valid JSON: {exc}") from exc
    if not isinstance(body, dict):
        raise _BadRequest("body must be a JSON object")
    prompt = body.get("prompt")
    if not isinstance(prompt, list) or not all(isinstance(t, int) for t in prompt):
        raise _BadRequest("'prompt' must be a list of token ids (integers)")
    out: Dict[str, Any] = {"prompt_ids": prompt}
    if "max_new_tokens" in body:
        if not isinstance(body["max_new_tokens"], int):
            raise _BadRequest("'max_new_tokens' must be an integer")
        out["max_new_tokens"] = body["max_new_tokens"]
    if "priority" in body:
        try:
            out["priority"] = Priority.parse(body["priority"])
        except ValueError as exc:
            raise _BadRequest(str(exc)) from exc
    if "tenant" in body:
        if (
            not isinstance(body["tenant"], str)
            or not body["tenant"]
            or len(body["tenant"]) > 128
        ):
            raise _BadRequest("'tenant' must be a non-empty string (<= 128 chars)")
        out["tenant"] = body["tenant"]
    if "ttl_s" in body and body["ttl_s"] is not None:
        if not isinstance(body["ttl_s"], (int, float)) or body["ttl_s"] <= 0:
            raise _BadRequest("'ttl_s' must be a positive number of seconds")
        out["ttl_s"] = float(body["ttl_s"])
    if "eos_token_id" in body and body["eos_token_id"] is not None:
        if not isinstance(body["eos_token_id"], int):
            raise _BadRequest("'eos_token_id' must be an integer")
        out["eos_token_id"] = body["eos_token_id"]
    out["stream"] = bool(body.get("stream", True))
    return out


class _ServingHandler(BaseHTTPRequestHandler):
    # set by start_serving_server on the handler subclass: a ServingFrontend
    # or a ReplicaRouter (duck-typed: same submit/cancel/snapshot surface)
    frontend: ServingFrontend = None  # type: ignore[assignment]
    stream_timeout_s: float = 60.0

    # -- plumbing ------------------------------------------------------------
    def log_message(self, *args: Any) -> None:  # silence per-request stderr
        pass

    def _count(self, code: int) -> None:
        _RESPONSES.labels(code=str(code)).inc()

    def _send_json(self, code: int, payload: Dict[str, Any],
                   headers: Optional[Dict[str, str]] = None) -> None:
        body = (json.dumps(payload) + "\n").encode()
        self._count(code)  # BEFORE the write: a client that reads the body
        # and immediately asserts on the counter must never race the handler
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    # -- routes --------------------------------------------------------------
    def do_GET(self) -> None:  # noqa: N802 - http.server API
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            # multi-replica mode with a ClusterObserver attached: the fleet
            # view (router state, per-replica lifecycle + capability blocks,
            # the SLO monitor); otherwise the frontend/router snapshot
            observer = getattr(self.frontend, "observer", None)
            self._send_json(
                200,
                observer.healthz() if observer is not None
                else self.frontend.snapshot(),
            )
            return
        if path == "/metrics":
            # the SAME replica-labeled exposition as start_metrics_server:
            # one renderer, so single- and multi-replica formats agree. An
            # attached observer may carry a non-default registry — honor it.
            from paddle_tpu.observability.exporters import render_exposition

            observer = getattr(self.frontend, "observer", None)
            body = (
                observer.render_metrics()
                if observer is not None
                else render_exposition()
            ).encode()
            self._count(200)
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        self._send_json(
            404,
            {"error": "try POST /v1/generate, GET /healthz or GET /metrics"},
        )

    def do_POST(self) -> None:  # noqa: N802 - http.server API
        if self.path.split("?", 1)[0] != "/v1/generate":
            self._send_json(404, {"error": "try POST /v1/generate"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            kwargs = _parse_body(self.rfile.read(length))
            stream = kwargs.pop("stream")
        except _BadRequest as exc:
            self._send_json(400, {"error": str(exc)})
            return
        # distributed tracing: continue the caller's trace when the header
        # is present (malformed headers are ignored, never a 4xx)
        kwargs["traceparent"] = self.headers.get("traceparent")
        try:
            handle = self.frontend.submit(**kwargs)
        except Overloaded as exc:
            self._send_json(
                429,
                {"error": str(exc), "reason": exc.reason,
                 "retry_after_s": exc.retry_after},
                headers={"Retry-After": f"{exc.retry_after:.3f}"},
            )
            return
        except IntakeError as exc:
            # the typed taxonomy is the whole point: no message matching
            self._send_json(400, {"error": str(exc), "type": type(exc).__name__})
            return
        except RuntimeError as exc:  # engine permanently failed
            self._send_json(500, {"error": str(exc)})
            return
        if stream:
            self._stream_response(handle)
        else:
            self._blocking_response(handle)

    # -- response modes ------------------------------------------------------
    def _stream_response(self, handle) -> None:
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Cache-Control", "no-store")
        if handle.traceparent:
            # the root span's identity: the client can link its own spans
            self.send_header("traceparent", handle.traceparent)
        # no Content-Length: HTTP/1.0 semantics — connection close ends the
        # body; each line is flushed as its token is produced
        self.end_headers()
        n = 0
        try:
            for tok in handle.stream(timeout=self.stream_timeout_s):
                fault_point("serving.respond")
                self.wfile.write((json.dumps({"token": int(tok)}) + "\n").encode())
                self.wfile.flush()
                n += 1
            self.wfile.write(
                (json.dumps(
                    {"done": True, "outcome": handle.outcome, "tokens": n}
                ) + "\n").encode()
            )
            self.wfile.flush()
            self._count(200)
        except TimeoutError:
            # server-side stall (pump stopped?) — not the client's fault,
            # but the slot must still be reclaimed
            self.frontend.cancel(handle.id, reason="stream_timeout")
            self.close_connection = True
        except (BrokenPipeError, ConnectionResetError, OSError, InjectedFault):
            # client went away — or a serving.respond fault modelling it: a
            # sampled campaign's default InjectedFault must take the same
            # cancel path as a real torn connection, so overload x fault
            # interplay reaches the eviction code. Either way the request is
            # evicted and its slot + KV blocks return to the pool.
            self.frontend.cancel(handle.id, reason="client_disconnect")
            self.close_connection = True

    def _blocking_response(self, handle) -> None:
        try:
            inner = handle.result(timeout=self.stream_timeout_s)
        except TimeoutError as exc:
            self.frontend.cancel(handle.id, reason="stream_timeout")
            self._send_json(500, {"error": str(exc)})
            return
        try:
            fault_point("serving.respond")
            self._send_json(
                200,
                {
                    "outcome": handle.outcome,
                    # a router handle shed before any replica accepted it
                    # has no engine-side request to read a reason from
                    "finish_reason": None if inner is None else inner.finish_reason,
                    "tokens": handle.tokens(),
                    "degraded": handle.degraded,
                },
                headers=(
                    {"traceparent": handle.traceparent}
                    if handle.traceparent else None
                ),
            )
        except (BrokenPipeError, ConnectionResetError, OSError, InjectedFault):
            # the request already finished (nothing to evict) — just don't
            # let a torn connection / injected respond fault kill the
            # handler thread loudly
            self.close_connection = True


_server: Optional[ThreadingHTTPServer] = None
_server_lock = threading.Lock()


def start_serving_server(
    frontend: ServingFrontend,
    port: Optional[int] = None,
    stream_timeout_s: float = 60.0,
) -> Optional[ThreadingHTTPServer]:
    """Serve the generation endpoint on 127.0.0.1 and start the frontend's
    pump thread. ``frontend`` may also be a
    :class:`~paddle_tpu.serving.router.ReplicaRouter` (multi-replica mode:
    per-replica pumps plus the router supervisor are started instead).
    ``port=None`` reads ``FLAGS_serving_port`` (<= 0 → disabled,
    returns None); an explicit ``port=0`` binds an ephemeral port
    (``server.server_address[1]`` has it). Idempotent for the same port;
    raises when a different port is requested while one is bound."""
    global _server
    with _server_lock:
        if _server is not None:
            bound = _server.server_address[1]
            if port not in (None, 0) and int(port) != bound:
                raise RuntimeError(
                    f"serving server already bound to port {bound}; "
                    f"stop_serving_server() before requesting port {port}"
                )
            return _server
        if port is None:
            port = int(GLOBAL_FLAGS.get("serving_port"))
            if port <= 0:
                return None
        handler = type(
            "_BoundServingHandler",
            (_ServingHandler,),
            {"frontend": frontend, "stream_timeout_s": float(stream_timeout_s)},
        )
        srv = ThreadingHTTPServer(("127.0.0.1", int(port)), handler)
        srv.daemon_threads = True
        frontend.start()
        t = threading.Thread(target=srv.serve_forever, daemon=True, name="serving-http")
        t.start()
        _server = srv
        return srv


def stop_serving_server(frontend: Optional[ServingFrontend] = None) -> None:
    """Shut the endpoint down; also stops ``frontend``'s pump when given."""
    global _server
    with _server_lock:
        if _server is not None:
            _server.shutdown()
            _server.server_close()
            _server = None
    if frontend is not None:
        frontend.stop()
