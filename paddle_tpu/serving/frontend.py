"""SLO-aware serving front end over :class:`ContinuousBatchingEngine`.

The host-side policy layer a heavy-traffic deployment lives on (ROADMAP:
"millions of users"): the engine turns a request mix into fixed-shape device
steps; this layer decides *which* requests get to become device work at all
when there is more demand than capacity — explicitly, observably, and
without ever wedging or OOMing the pool:

- **bounded intake** — at most ``max_queue`` requests wait; past that,
  intake raises :class:`Overloaded` (HTTP 429) instead of growing host
  memory without bound;
- **deadlines / TTLs** — each request can carry a deadline; the engine sheds
  it from the queue before wasting a prefill, or evicts it mid-decode with
  its KV blocks reclaimed (``serving_deadline_miss_total{stage}``);
- **priority classes + weighted per-tenant fairness** — admission order is
  the :class:`WeightedFairPolicy` stride scheduler, not FIFO;
- **load shedding with hysteresis** — an :class:`OverloadController` watches
  the same signals the observability gauges export (intake queue depth,
  KV-pool utilization from ``pool_stats()``, and a sliding-window TTFT p99)
  and latches between NORMAL → DEGRADED → SHEDDING. Start and stop
  thresholds are distinct, so the system does not flap at the boundary;
- **graceful degradation** — DEGRADED clamps best-effort ``max_new_tokens``;
  SHEDDING additionally rejects best-effort intake with a typed
  :class:`Overloaded` carrying a retry-after hint and clamps standard
  traffic. Interactive traffic is only ever refused by the bounded queue.

Reading the signals from engine truth (``pool_stats()``, the frontend's own
queue count and TTFT window) rather than the metric cells keeps shedding
correct when ``FLAGS_enable_metrics`` is off — the gauges export the same
values when metrics are on.

Threading model: ``submit``/``cancel`` are thread-safe (HTTP handler
threads); all engine interaction happens under one lock, and the engine is
only ever driven by :meth:`pump` — call it from your own loop, or
:meth:`start` a daemon pump thread. Token streams are per-request queues;
every blocking wait in this module carries an explicit timeout (analyzer
check RB502 — an un-timed wait is how a shed request wedges a worker).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from queue import Empty, Queue
from typing import Any, Dict, Iterator, List, Optional, Tuple

from paddle_tpu.inference.engine import ContinuousBatchingEngine, InferenceRequest
from paddle_tpu.observability import flight_recorder as _flight
from paddle_tpu.observability import tracing as _tracing
from paddle_tpu.observability.serving import priority_name, serving_metrics
from paddle_tpu.serving.errors import Overloaded
from paddle_tpu.serving.scheduler import DEFAULT_WEIGHTS, WeightedFairPolicy
from paddle_tpu.testing.faults import fault_point

__all__ = [
    "Hysteresis",
    "OverloadController",
    "Priority",
    "ServingConfig",
    "ServingFrontend",
    "ServingRequest",
]


class Priority:
    """Priority classes (lower = more important). Label values in metrics
    use the names (see ``observability.serving.PRIORITY_NAMES``)."""

    INTERACTIVE = 0
    STANDARD = 1
    BEST_EFFORT = 2

    @staticmethod
    def parse(value: Any) -> int:
        """Accept ints or the class names (the HTTP request format)."""
        if isinstance(value, bool):
            raise ValueError(f"bad priority {value!r}")
        if isinstance(value, int):
            return value
        names = {"interactive": 0, "standard": 1, "best_effort": 2}
        key = str(value).strip().lower()
        if key in names:
            return names[key]
        raise ValueError(
            f"bad priority {value!r} (expected interactive/standard/best_effort "
            "or an integer class)"
        )


class Hysteresis:
    """A latched threshold: turns ON when the signal reaches ``high``, and
    only turns OFF again below ``low`` — distinct start/stop points, so a
    signal hovering at the boundary cannot flap the state per step."""

    def __init__(self, high: float, low: float) -> None:
        if low > high:
            raise ValueError(f"hysteresis low ({low}) must be <= high ({high})")
        self.high, self.low = float(high), float(low)
        self.active = False

    def update(self, value: float) -> bool:
        if self.active:
            if value < self.low:
                self.active = False
        elif value >= self.high:
            self.active = True
        return self.active


@dataclass
class ServingConfig:
    """Frontend policy knobs. Thresholds are ``(start, stop)`` pairs feeding
    :class:`Hysteresis` gates; queue thresholds are fractions of
    ``max_queue``, utilization thresholds are fractions of the KV pool, TTFT
    thresholds are seconds over the sliding-window p99 (None disables the
    TTFT signal at that level)."""

    max_queue: int = 64
    # per-request default TTL (seconds from submit); None = no deadline
    default_ttl_s: Optional[float] = None
    # DEGRADED: clamp best-effort budgets to this many new tokens
    degrade_max_new_tokens: int = 16
    # stride weights per priority class (admission share under backlog)
    weights: Dict[int, float] = field(default_factory=lambda: dict(DEFAULT_WEIGHTS))
    degrade_queue_frac: Tuple[float, float] = (0.5, 0.25)
    shed_queue_frac: Tuple[float, float] = (0.875, 0.5)
    degrade_util: Tuple[float, float] = (0.85, 0.7)
    shed_util: Tuple[float, float] = (0.97, 0.85)
    degrade_ttft_p99_s: Optional[Tuple[float, float]] = None
    shed_ttft_p99_s: Optional[Tuple[float, float]] = None
    # base retry-after hint; scaled up with queue pressure
    retry_after_s: float = 0.5
    # distinct tenant values exported as metric labels; past this many the
    # label collapses to "overflow" — an HTTP client minting a fresh tenant
    # per request must not grow the process-global registry without bound
    max_tenant_labels: int = 64
    # sliding-window sizes for the controller's TTFT/step-time signals
    ttft_window: int = 128
    # default wait used by stream()/result() when the caller gives none
    default_wait_s: float = 60.0
    # idle nap between pump iterations when the engine has no work
    idle_sleep_s: float = 0.002


NORMAL, DEGRADED, SHEDDING = 0, 1, 2
_LEVEL_NAMES = {NORMAL: "normal", DEGRADED: "degraded", SHEDDING: "shedding"}


class OverloadController:
    """Maps (queue depth, KV utilization, TTFT p99) to an overload level
    through per-signal hysteresis gates. A level is active while ANY of its
    signals' gates is latched; SHEDDING implies DEGRADED."""

    def __init__(self, cfg: ServingConfig) -> None:
        def gates(queue_t, util_t, ttft_t):
            out = [("queue", Hysteresis(*queue_t)), ("util", Hysteresis(*util_t))]
            if ttft_t is not None:
                out.append(("ttft", Hysteresis(*ttft_t)))
            return out

        self._degrade = gates(cfg.degrade_queue_frac, cfg.degrade_util, cfg.degrade_ttft_p99_s)
        self._shed = gates(cfg.shed_queue_frac, cfg.shed_util, cfg.shed_ttft_p99_s)
        self.level = NORMAL

    def update(self, queue_frac: float, util: float, ttft_p99: float) -> int:
        signals = {"queue": queue_frac, "util": util, "ttft": ttft_p99}
        # update EVERY gate (no short-circuit: each must see the new value)
        degraded = [g.update(signals[name]) for name, g in self._degrade]
        shedding = [g.update(signals[name]) for name, g in self._shed]
        self.level = SHEDDING if any(shedding) else DEGRADED if any(degraded) else NORMAL
        return self.level

    @property
    def level_name(self) -> str:
        return _LEVEL_NAMES[self.level]


_END = None  # token-stream terminal sentinel


class ServingRequest:
    """Frontend handle for one accepted request: a token stream plus the
    final outcome. ``outcome`` is ``"ok"`` for a normal finish ("stop" /
    "length") or the shed reason otherwise (``deadline_queued`` /
    ``deadline_decode`` / ``client_disconnect`` / ``engine_failure`` /
    ``cancelled``)."""

    def __init__(self, inner: InferenceRequest, submit_time: float,
                 requested_max_new: int, default_wait_s: float) -> None:
        self.inner = inner
        self.id = inner.req_id
        self.priority = inner.priority
        self.tenant = inner.tenant
        # distributed-tracing context for this request's span tree; set by
        # submit() when tracing is enabled (None otherwise). Kept even when
        # unsampled so the trace id still propagates downstream.
        self.trace_ctx: Optional[_tracing.TraceContext] = None
        self.submit_time = submit_time
        self.requested_max_new_tokens = int(requested_max_new)
        self.degraded = requested_max_new != inner.max_new_tokens
        self.outcome: Optional[str] = None
        self.finish_time: Optional[float] = None
        self.first_token_time: Optional[float] = None
        self._default_wait_s = float(default_wait_s)
        self._q: Queue = Queue()
        self._done = threading.Event()
        self._n_pushed = 0  # tokens forwarded from inner.generated so far

    @property
    def finished(self) -> bool:
        return self._done.is_set()

    @property
    def traceparent(self) -> Optional[str]:
        """Outgoing ``traceparent`` header value for this request's root
        span (None when tracing was off at submit)."""
        if self.trace_ctx is None:
            return None
        return _tracing.format_traceparent(self.trace_ctx)

    @property
    def met_deadline(self) -> bool:
        """Finished normally, and inside the deadline (vacuously true with
        no deadline) — the per-request SLO bit goodput accounting uses."""
        if self.outcome != "ok":
            return False
        if self.inner.deadline is None:
            return True
        return self.finish_time is not None and self.finish_time <= self.inner.deadline

    def tokens(self) -> List[int]:
        return list(self.inner.generated)

    def stream(self, timeout: Optional[float] = None) -> Iterator[int]:
        """Yield token ids as the pump produces them; returns at end of
        stream (check ``outcome``). ``timeout`` bounds the wait for EACH
        token; a stalled pump raises ``TimeoutError`` rather than blocking a
        worker forever."""
        wait = self._default_wait_s if timeout is None else float(timeout)
        while True:
            try:
                item = self._q.get(timeout=wait)
            except Empty:
                raise TimeoutError(
                    f"request {self.id}: no token within {wait}s (pump stalled?)"
                ) from None
            if item is _END:
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> InferenceRequest:
        """Block until the request reaches a terminal state; returns the
        engine-side request (tokens + finish_reason)."""
        wait = self._default_wait_s if timeout is None else float(timeout)
        if not self._done.wait(timeout=wait):
            raise TimeoutError(f"request {self.id} not finished within {wait}s")
        return self.inner

    # -- pump-side (called under the frontend lock) --------------------------
    def _push_new(self, now: float) -> int:
        fresh = self.inner.generated[self._n_pushed:]
        if fresh and self.first_token_time is None:
            self.first_token_time = now
        for tok in fresh:
            self._q.put(tok)
        self._n_pushed += len(fresh)
        return len(fresh)

    def _finalize(self, outcome: str, now: float) -> None:
        self.outcome = outcome
        self.finish_time = now
        self._done.set()
        self._q.put(_END)


class ServingFrontend:
    """See module docstring. Construct over an existing engine; the frontend
    installs its :class:`WeightedFairPolicy` as the engine's admission
    policy (replacing FIFO)."""

    def __init__(
        self,
        engine: ContinuousBatchingEngine,
        config: Optional[ServingConfig] = None,
    ) -> None:
        self.engine = engine
        self.config = config or ServingConfig()
        if self.config.max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        self.policy = WeightedFairPolicy(self.config.weights)
        engine.set_admission_policy(self.policy)
        self.controller = OverloadController(self.config)
        self._metrics = serving_metrics()
        self._lock = threading.RLock()
        self._live: Dict[int, ServingRequest] = {}  # id -> handle (not yet terminal)
        self._ttfts: deque = deque(maxlen=int(self.config.ttft_window))
        self._step_times: deque = deque(maxlen=32)
        self._tenant_labels: set = set()  # bounded by max_tenant_labels
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._failed: Optional[str] = None  # set when the engine died for good
        # replica observability scope: unscoped until the cluster layer
        # calls set_replica_scope() at replica construction
        self._flight = _flight.GLOBAL_FLIGHT_RECORDER
        self.replica_name: Optional[str] = None

    # -- intake --------------------------------------------------------------
    def submit(
        self,
        prompt_ids: Any,
        max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
        priority: int = Priority.STANDARD,
        tenant: str = "default",
        ttl_s: Optional[float] = None,
        traceparent: Optional[str] = None,
    ) -> ServingRequest:
        """Accept one request. Raises a typed
        :class:`~paddle_tpu.inference.engine.IntakeError` (→ 4xx) on
        malformed input, :class:`Overloaded` (→ 429) when shedding, and
        ``RuntimeError`` if the engine is permanently failed.

        ``traceparent`` (the W3C-style header) continues an upstream trace;
        with tracing enabled and no header, seeded head sampling against
        ``FLAGS_trace_sample_rate`` decides. With the rate at 0 the entire
        tracing surface of this call is ONE cached-bool read."""
        fault_point("serving.intake")
        priority = int(priority)
        now = time.perf_counter()
        trace_ctx = None
        if _tracing.tracing_enabled():
            trace_ctx = _tracing.GLOBAL_TRACER.start_trace(traceparent)
        with self._lock:
            if self._failed is not None:
                raise RuntimeError(
                    f"serving frontend stopped: {self._failed}; build a new engine"
                )
            try:
                self._shed_gate(priority)
            except Overloaded as exc:
                # a sampled request rejected at intake still gets a terminal
                # root span — a trace must never just vanish at the door
                if trace_ctx is not None and trace_ctx.sampled:
                    _tracing.GLOBAL_TRACER.add_span(
                        "request", trace_id=trace_ctx.trace_id,
                        span_id=trace_ctx.span_id, parent_id=trace_ctx.parent_id,
                        start_s=now, end_s=time.perf_counter(),
                        attrs={"outcome": exc.reason,
                               "priority": priority_name(priority),
                               "tenant": tenant},
                        status=f"shed:{exc.reason}",
                    )
                raise
            effective_max_new = self._degrade_gate(priority, int(max_new_tokens))
            ttl = self.config.default_ttl_s if ttl_s is None else ttl_s
            deadline = None if ttl is None else now + float(ttl)
            inner = self.engine.make_request(
                prompt_ids, effective_max_new, eos_token_id,
                priority=priority, tenant=tenant, deadline=deadline,
            )
            handle = ServingRequest(
                inner, now, int(max_new_tokens), self.config.default_wait_s
            )
            handle.trace_ctx = trace_ctx
            if trace_ctx is not None and trace_ctx.sampled:
                inner.trace = trace_ctx  # engine-side spans attach here
            self.engine.enqueue(inner)
            self._live[inner.req_id] = handle
            self._metrics["requests"].labels(
                tenant=self._tenant_label(tenant),
                priority=priority_name(priority),
            ).inc()
            self._update_gauges()
            return handle

    def set_replica_scope(self, name: str) -> None:
        """Bind this frontend (and its engine, prefix cache and KV tier) to
        a replica observability scope, resolved ONCE: every ``serving_*``/
        ``engine_*`` series records with a ``replica=name`` label, flight
        events land in one per-replica child ring teed into the global
        black box, and sampled spans carry a ``replica`` attribute (the
        cross-replica failover tree is assembled from those). Called by
        :class:`~paddle_tpu.serving.cluster.ReplicaCluster` at replica
        construction and again on revive."""
        from paddle_tpu.observability.metrics import GLOBAL_METRICS

        with self._lock:
            scope = GLOBAL_METRICS.scope(replica=name)
            flight = _flight.GLOBAL_FLIGHT_RECORDER.child(replica=name)
            self.replica_name = str(name)
            self._metrics = scope.bind_all(serving_metrics())
            self._flight = flight
            self.engine.set_replica_scope(name, scope=scope, flight=flight)

    @property
    def flight(self) -> _flight.FlightRecorder:
        """This frontend's flight ring (the replica's own ring when scoped,
        else the process-global recorder) — the incident writer dumps it."""
        with self._lock:
            return self._flight

    def _tenant_label(self, tenant: str) -> str:
        """Metric-label view of a tenant, bounded in cardinality: scheduling
        always uses the real tenant, but label cells are permanent registry
        state, so unseen tenants past ``max_tenant_labels`` export as
        ``"overflow"``."""
        if tenant in self._tenant_labels:
            return tenant
        if len(self._tenant_labels) < self.config.max_tenant_labels:
            self._tenant_labels.add(tenant)
            return tenant
        return "overflow"

    def _shed_gate(self, priority: int) -> None:
        depth = self.engine.queue_depth()
        if depth >= self.config.max_queue:
            self._count_shed("queue_full")
            raise Overloaded(
                f"intake queue full ({depth}/{self.config.max_queue})",
                retry_after=self._retry_after(), reason="queue_full",
            )
        if self.controller.level >= SHEDDING and priority >= Priority.BEST_EFFORT:
            self._count_shed("overload")
            raise Overloaded(
                f"shedding load (level={self.controller.level_name}); "
                f"priority class {priority_name(priority)} is not being admitted",
                retry_after=self._retry_after(), reason="overload",
            )

    def _degrade_gate(self, priority: int, max_new_tokens: int) -> int:
        """Graceful degradation: clamp token budgets under pressure instead
        of failing requests — best-effort from DEGRADED, standard once
        SHEDDING. Interactive budgets are never clamped."""
        lvl = self.controller.level
        clamp = (lvl >= DEGRADED and priority >= Priority.BEST_EFFORT) or (
            lvl >= SHEDDING and priority >= Priority.STANDARD
        )
        if clamp and max_new_tokens > self.config.degrade_max_new_tokens:
            self._metrics["degraded"].labels(action="clamp_max_new_tokens").inc()
            return self.config.degrade_max_new_tokens
        return max_new_tokens

    def _retry_after(self) -> float:
        """Backoff hint: how long the current backlog takes to drain at the
        recently observed step rate, floored at the configured base."""
        step = (sum(self._step_times) / len(self._step_times)) if self._step_times else 0.0
        est = self.engine.queue_depth() * step
        return round(max(self.config.retry_after_s, est), 3)

    def _count_shed(self, reason: str) -> None:
        self._metrics["shed"].labels(reason=reason).inc()

    # -- lifecycle -----------------------------------------------------------
    def cancel(self, req_id: int, reason: str = "cancelled") -> bool:
        """Shed one request wherever it lives (queued or mid-decode; the
        latter's KV blocks are reclaimed immediately). Returns False when the
        id is unknown or already terminal."""
        with self._lock:
            if req_id not in self._live:
                # unknown or already terminal — and, crucially, NOT ours: a
                # direct engine user's request must never be evicted by a
                # frontend id mix-up, so ownership is checked before the
                # engine is touched at all
                return False
            inner = self.engine.cancel_request(req_id, reason=reason)
            if inner is None:
                return False  # finished this boundary: the handle stays
                # live for pump() to finalize through step()'s delivery
            handle = self._live.pop(req_id)
            self._count_shed(reason)
            now = time.perf_counter()
            handle._push_new(now)  # flush tokens produced so far
            handle._finalize(reason, now)
            self._emit_trace(handle, now)
            self._update_gauges()
            return True

    def pump(self) -> List[ServingRequest]:
        """One scheduling iteration: drive the engine a step, stream fresh
        tokens into the per-request queues, finalize finishes/sheds, update
        the overload controller. Returns handles that reached a terminal
        state during this call."""
        finished: List[ServingRequest] = []
        with self._lock:
            # sample pressure at boundary ENTRY: the backlog as offered, not
            # as already drained by this step's admissions — shedding must
            # react to what clients are experiencing, and a deep queue that
            # momentarily empties into slots is still a deep queue
            self._update_controller()
            done_inner: List[InferenceRequest] = []
            if self.engine.has_work():
                t0 = time.perf_counter()
                done_inner = self.engine.step()
                self._step_times.append(time.perf_counter() - t0)
            now = time.perf_counter()
            # stream tokens for everything still holding a slot
            for inner in self.engine.live_requests():
                handle = self._live.get(inner.req_id)
                if handle is not None:
                    self._note_progress(handle, now)
            for inner in done_inner:
                handle = self._live.pop(inner.req_id, None)
                if handle is None:
                    continue  # direct engine user / already cancelled
                self._note_progress(handle, now)
                finished.append(self._finalize(handle, now))
            self._update_controller()
            self._update_gauges()
        return finished

    def _note_progress(self, handle: ServingRequest, now: float) -> None:
        first = handle.first_token_time is None
        pushed = handle._push_new(now)
        if pushed:
            ctx = handle.trace_ctx
            if ctx is not None and ctx.sampled:
                _tracing.GLOBAL_TRACER.add_event(
                    "stream_chunk", ctx=ctx, attrs={"tokens": pushed}
                )
            pr = priority_name(handle.priority)
            self._metrics["tokens"].labels(priority=pr).inc(pushed)
            if first:
                ttft = now - handle.submit_time
                self._ttfts.append(ttft)
                self._metrics["ttft"].labels(priority=pr).observe(ttft)
                if handle.inner.prefill_start is not None:
                    # queue wait ends when the slot is mapped (chunked
                    # prefill then runs across subsequent engine steps)
                    self._metrics["queue_wait"].labels(priority=pr).observe(
                        handle.inner.prefill_start - handle.submit_time
                    )

    def _finalize(self, handle: ServingRequest, now: float) -> ServingRequest:
        reason = handle.inner.finish_reason
        pr = priority_name(handle.priority)
        if reason in ("stop", "length"):
            handle._finalize("ok", now)
            if handle.met_deadline:
                self._metrics["goodput"].labels(priority=pr).inc(
                    len(handle.inner.generated)
                )
        elif reason == "deadline":
            stage = "queued" if handle.inner.prefill_start is None else "decode"
            outcome = f"deadline_{stage}"
            self._count_shed(outcome)
            self._metrics["deadline_miss"].labels(stage=stage).inc()
            handle._finalize(outcome, now)
        else:  # cancel_request reasons arriving via step() are already counted
            handle._finalize(reason or "unknown", now)
        self._emit_trace(handle, now)
        return handle

    def _emit_trace(self, handle: ServingRequest, now: float) -> None:
        """Emit the request's span tree at terminal time, built from the
        lifecycle timestamps the engine/frontend recorded along the way.
        The phases tile [submit, terminal] contiguously — queue_wait →
        (prefill → decode, when admitted) → stream_out — so their durations
        sum to the request's observed end-to-end latency, and every span is
        parented to the root. No-op unless this request was sampled."""
        ctx = handle.trace_ctx
        if ctx is None or not ctx.sampled:
            return
        t = _tracing.GLOBAL_TRACER
        inner = handle.inner
        tid, root = ctx.trace_id, ctx.span_id
        sub = handle.submit_time
        pstart, admit = inner.prefill_start, inner.admit_time
        fin = inner.finish_wall if inner.finish_wall is not None else now
        admitted = pstart is not None and admit is not None
        q_end = pstart if admitted else fin
        t.add_span(
            "request.queue_wait", trace_id=tid, parent_id=root,
            start_s=sub, end_s=q_end,
        )
        if admitted:
            t.add_span(
                "request.prefill", trace_id=tid, parent_id=root,
                start_s=pstart, end_s=admit,
                attrs={"prompt_len": int(inner.prompt.size)},
            )
            t.add_span(
                "request.decode", trace_id=tid, parent_id=root,
                start_s=admit, end_s=fin,
                attrs={
                    "decode_steps": inner.decode_steps,
                    # batched share: this request's even split of every
                    # decode step it rode (see engine.decode_step spans)
                    "batched_share_s": round(inner.decode_share_s, 6),
                },
            )
        t.add_span(
            "request.stream_out", trace_id=tid, parent_id=root,
            start_s=fin, end_s=now, attrs={"tokens": handle._n_pushed},
        )
        attrs = {
            "req_id": handle.id,
            "priority": priority_name(handle.priority),
            "tenant": handle.tenant,
            "outcome": handle.outcome,
            "finish_reason": inner.finish_reason,
            "n_generated": len(inner.generated),
        }
        if self.replica_name is not None:
            # replica attribution: a failed-over request's trace contains
            # one such span per replica that served it — the incident dump
            # CLI assembles them into one cross-replica tree by trace_id
            attrs["replica"] = self.replica_name
        t.add_span(
            "request", trace_id=tid, span_id=root, parent_id=ctx.parent_id,
            start_s=sub, end_s=now,
            attrs=attrs,
            status="ok" if handle.outcome == "ok" else f"shed:{handle.outcome}",
        )

    def _ttft_p99(self) -> float:
        if not self._ttfts:
            return 0.0
        ordered = sorted(self._ttfts)
        return ordered[min(len(ordered) - 1, int(0.99 * len(ordered)))]

    def _update_controller(self) -> int:
        stats = self.engine.pool_stats()
        # blocks the prefix cache retains warm but surrenders under pressure
        # are headroom, not load — counting them would shed traffic a single
        # eviction could have served
        live = stats["allocated"] - stats.get("cached_reusable", 0)
        util = live / stats["total"] if stats["total"] else 0.0
        queue_frac = self.engine.queue_depth() / self.config.max_queue
        prev = self.controller.level
        level = self.controller.update(queue_frac, util, self._ttft_p99())
        if level != prev:
            # overload transitions are rare and postmortem-critical: the
            # black box shows what pressure looked like before a death
            self._flight.record(
                "overload_level",
                **{"from": _LEVEL_NAMES[prev], "to": _LEVEL_NAMES[level],
                   "queue_frac": round(queue_frac, 4), "util": round(util, 4)},
            )
        return level

    def _update_gauges(self) -> None:
        self._metrics["queue_depth"].set(self.engine.queue_depth())
        self._metrics["level"].set(self.controller.level)
        cache = self.engine.prefix_cache_stats()
        if cache.get("enabled"):
            self._metrics["prefix_hit_rate"].set(cache["hit_rate"])

    # -- pump thread ---------------------------------------------------------
    def start(self) -> "ServingFrontend":
        """Run :meth:`pump` on a daemon thread until :meth:`stop`."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run_loop, daemon=True, name="serving-pump"
            )
            self._thread.start()
        return self

    def _run_loop(self) -> None:
        consecutive_failures = 0
        while not self._stop.is_set():
            try:
                self.pump()
                consecutive_failures = 0
            except Exception as exc:  # classify: engine.step() re-raises
                # transient failures with host state rolled back and the
                # engine still usable (caller-retryable contract) — those we
                # retry with backoff; a PERMANENT failure (engine.broken) or
                # a persistent error storm fails every live stream
                # explicitly instead of letting clients hang
                consecutive_failures += 1
                if self.engine.broken or consecutive_failures > 3:
                    self._fail_all(f"{type(exc).__name__}: {exc}")
                    return
                self._stop.wait(timeout=0.05 * consecutive_failures)
                continue
            if not self.engine.has_work():
                self._stop.wait(timeout=self.config.idle_sleep_s)

    def fail(self, why: str) -> None:
        """Declare this frontend permanently failed: stop the pump thread,
        salvage engine-finished results, and fail every other live stream
        explicitly (``engine_failure``). The cluster layer calls this when a
        replica is declared DEAD so its in-flight requests reach a terminal
        state the router can act on (salvage vs re-dispatch); idempotent."""
        self._stop.set()
        self._fail_all(why)

    def _fail_all(self, why: str) -> None:
        now = time.perf_counter()
        with self._lock:
            if self._failed is not None:
                return  # already failed: one death, one dump, one accounting
            self._failed = why
            # the pump thread is dying: black-box line + postmortem dump
            # (safe_dump never raises — failing every stream still happens)
            self._flight.record(
                "pump_death", why=why[:200], live=len(self._live),
                queue_depth=self.engine.queue_depth(),
            )
            self._flight.safe_dump("serving_pump_death", extra={"why": why[:200]})
            # salvage results the engine already finished but never delivered
            salvaged = {r.req_id for r in self.engine.drain_finished()}
            for rid, handle in list(self._live.items()):
                handle._push_new(now)
                if rid in salvaged and handle.inner.finish_reason in ("stop", "length"):
                    self._finalize(handle, now)
                else:
                    self._count_shed("engine_failure")
                    handle._finalize("engine_failure", now)
                    self._emit_trace(handle, now)
                del self._live[rid]
            self._update_gauges()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        # _thread is guarded by the frontend lock (start() mutates it under
        # the lock); the join itself must happen OUTSIDE the lock or a pump
        # iteration waiting on the lock could never finish its last pass
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        with self._lock:
            if self._thread is t:
                self._thread = None

    # -- introspection -------------------------------------------------------
    def health_snapshot(self) -> Dict[str, Any]:
        """Per-replica health view for a cluster router's probe loop: the
        liveness facts (engine ``broken`` flag, pump-thread liveness, the
        failure reason) plus the load signals the router's spill decision
        reads. ``pump_alive`` is None when no pump thread was ever started
        (inline drivers), so a router never mistakes inline mode for death.

        Under tensor parallelism the replica's health unit IS the shard
        group: one engine = one ``['tp']`` mesh, so a dead replica takes its
        whole shard group out of rotation at once — ``tp_degree`` rides
        along so the router's capacity view can weight replicas by chips."""
        with self._lock:
            t = self._thread
            stats = self.engine.pool_stats()
            live = stats["allocated"] - stats.get("cached_reusable", 0)
            return {
                "broken": self.engine.broken,
                "failed": self._failed,
                "pump_alive": None if t is None else t.is_alive(),
                "queue_depth": self.engine.queue_depth(),
                "max_queue": self.config.max_queue,
                "live_requests": len(self._live),
                "level": self.controller.level,
                "level_name": self.controller.level_name,
                "kv_utilization": round(
                    live / stats["total"] if stats["total"] else 0.0, 4
                ),
                "tp_degree": getattr(self.engine, "tp_degree", 1),
            }

    def snapshot(self) -> Dict[str, Any]:
        """Cheap health view (the HTTP /healthz payload)."""
        with self._lock:
            stats = self.engine.pool_stats()
            live = stats["allocated"] - stats.get("cached_reusable", 0)
            cache = self.engine.prefix_cache_stats()
            spec = self.engine.spec_decode_stats()
            return {
                "level": self.controller.level_name,
                "queue_depth": self.engine.queue_depth(),
                "max_queue": self.config.max_queue,
                "live_requests": len(self._live),
                "kv_utilization": round(
                    live / stats["total"] if stats["total"] else 0.0, 4
                ),
                # quantized serving surface: the pool's storage dtype and the
                # effective bytes one cached token costs across all layers
                "kv_cache_dtype": stats.get("kv_cache_dtype", "bf16"),
                "kv_bytes_per_token": stats.get("bytes_per_token", 0),
                "ttft_p99_s": round(self._ttft_p99(), 4),
                "failed": self._failed,
                "prefix_cache": {
                    "enabled": bool(cache.get("enabled")),
                    "hit_rate": round(cache.get("hit_rate", 0.0), 4),
                    "tokens_reused": cache.get("tokens_reused", 0),
                    "evictable_blocks": cache.get("evictable_blocks", 0),
                },
                "spec_decode": {
                    "enabled": bool(spec.get("enabled")),
                    "acceptance_rate": round(spec.get("acceptance_rate", 0.0), 4),
                    "accepted_tokens": spec.get("accepted_tokens", 0),
                    "drafted_tokens": spec.get("drafted_tokens", 0),
                },
                # hierarchical KV: the host-RAM spill tier under the prefix
                # cache (enabled: False == FLAGS_kv_host_tier_bytes=0)
                "kv_tier": (
                    self.engine.kv_tier_stats()
                    if hasattr(self.engine, "kv_tier_stats")
                    else {"enabled": False}
                ),
                # the shard-group identity: one engine = one ['tp'] mesh
                "tensor_parallel": (
                    self.engine.tp_stats()
                    if hasattr(self.engine, "tp_stats")
                    else {"tp_degree": 1}
                ),
                # device-time attribution over the step-timeline ring
                # (enabled: False == FLAGS_devprof_sample_rate=0)
                "devprof": (
                    self.engine.devprof_stats()
                    if hasattr(self.engine, "devprof_stats")
                    else {"enabled": False, "sampled_steps": 0}
                ),
            }
