"""Abstract evaluation of Pallas kernel geometry.

Every ``pl.pallas_call`` site in a module is reduced to a static
:class:`SiteEval`: the grid, every BlockSpec's block shape and index-map
return arity, ``out_shape``/scratch shapes and the scalar-prefetch arity —
with block sizes, grid extents and operand dims resolved to *sets of
concrete ints* where the code pins them statically:

- literals, module-level constants (own module or imported), local
  assignments and ``functools.partial`` bindings;
- enclosing-function parameters traced to their intra-module call sites,
  each call site expanded into one *configuration* (so correlated values —
  a grid computed from the same block size the BlockSpec uses — stay
  correlated instead of mixing across candidates);
- the autotune protocol: a parameter of a builder passed to
  ``autotune(name, key, candidates, build, ...)`` takes each entry of the
  candidates tuple as its own configuration, which is how autotune
  candidate block sizes become concrete without running anything.

The evaluator is deliberately three-valued: a window is *proven* in
bounds, *refuted* (a concrete overrun — a PG902 finding), or *unproven* —
symbolic residue is reported as such, never silently passed (the same
honesty rule as the CLI's never-vacuous exits).  The PG checker family
(:mod:`paddle_tpu.analysis.checkers.pallas_geometry`) consumes these
reports; module reports are memoized in the run's
:class:`~paddle_tpu.analysis.dataflow.PackageIndex` so the tier-1
single-dataflow-pass and wall-time gates hold.
"""

from __future__ import annotations

import ast
import itertools
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

__all__ = [
    "ValueSet",
    "UNPROVEN",
    "SpecEval",
    "AxisProof",
    "VmemConfig",
    "SiteEval",
    "ModuleGeometry",
    "evaluate_module",
    "DTYPE_BYTES",
]

# jnp dtype name -> element width in bytes (geometry's only dtype fact)
DTYPE_BYTES = {
    "float64": 8, "int64": 8, "uint64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1, "bool_": 1,
    "float8_e4m3fn": 1, "float8_e5m2": 1, "float8_e4m3": 1, "float8_e5m2fnuz": 1,
}

_FOLD_CAP = 64          # max values an abstract int may hold before widening
_CONFIG_CAP = 32        # max expanded per-site configurations
_CALLSITE_CAP = 16      # max call sites consulted when resolving a parameter
_DEPTH_CAP = 12


@dataclass(frozen=True)
class ValueSet:
    """Abstract integer: the set of values an expression may take.

    ``complete=True`` means the set is exhaustive, so a *proof* may rely on
    it; an incomplete set still witnesses violations ("some call site
    passes 96") but can never prove anything.  The empty incomplete set is
    the honest bottom, :data:`UNPROVEN`."""

    values: FrozenSet[int]
    complete: bool

    @staticmethod
    def of(*vals: int) -> "ValueSet":
        return ValueSet(frozenset(vals), True)

    @property
    def known(self) -> bool:
        return bool(self.values)

    def concrete(self) -> Optional[int]:
        """The single exact value, when there is one."""
        if self.complete and len(self.values) == 1:
            return next(iter(self.values))
        return None

    def __repr__(self) -> str:  # compact in messages
        if not self.values:
            return "unproven"
        body = ",".join(str(v) for v in sorted(self.values))
        return ("{%s}" % body) + ("" if self.complete else "+?")


UNPROVEN = ValueSet(frozenset(), False)


def _fold2(f, a, b) -> ValueSet:
    if not isinstance(a, ValueSet) or not isinstance(b, ValueSet):
        return UNPROVEN
    vals: Set[int] = set()
    for x in a.values:
        for y in b.values:
            try:
                v = f(x, y)
            except (ZeroDivisionError, ValueError, OverflowError):
                return UNPROVEN
            if isinstance(v, bool) or not isinstance(v, int):
                return UNPROVEN
            vals.add(v)
            if len(vals) > _FOLD_CAP:
                return UNPROVEN
    return ValueSet(frozenset(vals), a.complete and b.complete)


def _attr_chain(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _last(chain: Optional[str]) -> str:
    return chain.split(".")[-1] if chain else ""


def _dtype_name(node: Optional[ast.AST]) -> Optional[str]:
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in DTYPE_BYTES else None
    name = _last(_attr_chain(node))
    return name if name in DTYPE_BYTES else None


# -- report dataclasses -------------------------------------------------------

@dataclass
class SpecEval:
    """One BlockSpec (or ``out_shape``-only output) at a pallas_call site."""

    kind: str                       # "in" | "out"
    index: int                      # position within its spec list
    lineno: int
    block_shape: Optional[Tuple]    # tuple of ValueSet, or None (whole-array)
    index_map: Optional[ast.AST]    # Lambda / FunctionDef, if any
    map_params: List[str] = field(default_factory=list)
    ret_arity: Optional[int] = None  # index-map return tuple length
    operand_rank: Optional[int] = None
    operand_dims: Optional[Tuple] = None   # tuple of ValueSet
    operand_dtype: Optional[str] = None
    # AST residue for per-configuration re-resolution (correlated values)
    shape_node: Optional[ast.AST] = None   # BlockSpec block_shape expr
    dims_node: Optional[ast.AST] = None    # operand expr or out-shape tuple expr
    dims_is_operand: bool = False          # dims_node needs operand inference


@dataclass
class AxisProof:
    """In-bounds status of one (spec, dim) window across all configurations."""

    kind: str
    spec_index: int
    dim: int
    status: str                     # "proven" | "unproven" | "overrun"
    detail: str = ""
    lineno: int = 0


@dataclass
class VmemConfig:
    """Per-grid-step VMEM window footprint under one configuration."""

    binding: Dict[str, int]         # concrete params this config pinned
    bytes_per_step: ValueSet        # window bytes (no double-buffer factor)
    assumed_width: bool = False     # some element width defaulted to 1 byte


@dataclass
class SiteEval:
    path: str
    lineno: int
    kernel_name: str
    kernel_node: Optional[ast.AST]
    kernel_params: Optional[List[str]]   # after functools.partial bindings
    has_vararg: bool
    grid_len: Optional[int]              # statically-known grid rank
    grid: Optional[Tuple]                # tuple of ValueSet (merged configs)
    num_scalar_prefetch: int
    prefetch_grid_spec: bool             # came from PrefetchScalarGridSpec
    grid_node: Optional[ast.AST] = None  # grid expr, for per-config re-resolution
    in_specs: List[SpecEval] = field(default_factory=list)
    out_specs: List[SpecEval] = field(default_factory=list)
    out_specs_declared: bool = False
    n_out_shapes: Optional[int] = None
    n_scratch: int = 0
    scratch: List[Tuple[str, Tuple, Optional[str]]] = field(default_factory=list)
    scratch_nodes: List[Optional[ast.AST]] = field(default_factory=list)
    axis_proofs: List[AxisProof] = field(default_factory=list)
    vmem_configs: List[VmemConfig] = field(default_factory=list)
    # (lineno, detail) — prefetch refs indexed by non-grid values (PG904)
    prefetch_indexing: List[Tuple[int, str]] = field(default_factory=list)
    notes: List[str] = field(default_factory=list)

    def proof(self, kind: str, spec_index: int, dim: int) -> Optional[AxisProof]:
        for p in self.axis_proofs:
            if (p.kind, p.spec_index, p.dim) == (kind, spec_index, dim):
                return p
        return None


@dataclass
class ModuleGeometry:
    path: str
    sites: List[SiteEval] = field(default_factory=list)


# -- the evaluator ------------------------------------------------------------

class _ModuleEval:
    def __init__(self, path: str, tree: ast.Module, index=None) -> None:
        self.path = path
        self.tree = tree
        self.index = index  # PackageIndex (optional, for imported constants)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.module_consts: Dict[str, ast.expr] = {}
        self.defs: Dict[str, ast.FunctionDef] = {}
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        self.import_aliases: Set[str] = set()
        self.calls_by_name: Dict[str, List[ast.Call]] = {}
        self._foreign_consts: Dict[str, Dict[str, ast.expr]] = {}
        self._name_stack: Set[Tuple[int, str]] = set()
        self._param_stack: Set[Tuple[str, str]] = set()
        self._collect()

    # -- module facts ---------------------------------------------------------
    def _collect(self) -> None:
        for stmt in self.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and isinstance(
                stmt.targets[0], ast.Name
            ):
                self.module_consts[stmt.targets[0].id] = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None and isinstance(
                stmt.target, ast.Name
            ):
                self.module_consts[stmt.target.id] = stmt.value
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs.setdefault(node.name, node)  # type: ignore[arg-type]
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for alias in node.names:
                    self.from_imports[alias.asname or alias.name] = (
                        node.module, alias.name,
                    )
                    self.import_aliases.add(alias.asname or alias.name)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    self.import_aliases.add(
                        alias.asname or alias.name.split(".", 1)[0]
                    )
            elif isinstance(node, ast.Call):
                name = _last(_attr_chain(node.func))
                if name:
                    self.calls_by_name.setdefault(name, []).append(node)

    def scope_of(self, node: ast.AST) -> Tuple[ast.AST, ...]:
        """Enclosing function chain, innermost first."""
        out: List[ast.AST] = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                out.append(cur)
            cur = self.parents.get(cur)
        return tuple(out)

    # -- scoped binding lookup ------------------------------------------------
    def _scoped_stmts(self, fn: ast.AST):
        """Statements of ``fn``'s body, not descending into nested defs."""
        body = getattr(fn, "body", [])
        if not isinstance(body, list):  # Lambda: body is an expression
            return
        stack = list(body)
        while stack:
            stmt = stack.pop()
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            yield stmt
            for f in ("body", "orelse", "finalbody"):
                stack.extend(getattr(stmt, f, []))
            for h in getattr(stmt, "handlers", []):
                stack.extend(h.body)

    def _binding_in(self, fn: ast.AST, name: str):
        """How ``name`` is bound inside ``fn``: ("assign", expr) |
        ("tupelem", expr, i) | ("loopvar", iter_expr) | ("dimof", base, i, n)
        | ("param", fn) | ("multi",) | None."""
        found = None
        count = 0
        for stmt in self._scoped_stmts(fn):
            targets: List[ast.expr] = []
            value: Optional[ast.expr] = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            elif isinstance(stmt, ast.AugAssign):
                if isinstance(stmt.target, ast.Name) and stmt.target.id == name:
                    count += 2  # re-binding: give up
                continue
            elif isinstance(stmt, ast.For):
                if isinstance(stmt.target, ast.Name) and stmt.target.id == name:
                    found, count = ("loopvar", stmt.iter), count + 1
                continue
            else:
                continue
            for t in targets:
                if isinstance(t, ast.Name) and t.id == name:
                    found, count = ("assign", value), count + 1
                elif isinstance(t, (ast.Tuple, ast.List)):
                    for i, elt in enumerate(t.elts):
                        if isinstance(elt, ast.Name) and elt.id == name:
                            count += 1
                            if (
                                isinstance(value, ast.Attribute)
                                and value.attr == "shape"
                            ):
                                found = ("dimof", value.value, i, len(t.elts))
                            else:
                                found = ("tupelem", value, i)
        # comprehension targets bind like loop vars
        for node in ast.walk(fn) if not isinstance(fn, ast.Lambda) else ():
            if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
                for gen in node.generators:
                    if isinstance(gen.target, ast.Name) and gen.target.id == name:
                        found, count = ("loopvar", gen.iter), count + 1
        if count > 1:
            return ("multi",)
        if found is not None:
            return found
        params = self._positional_params(fn) + self._kwonly_params(fn)
        if name in params:
            return ("param", fn)
        return None

    @staticmethod
    def _positional_params(fn: ast.AST) -> List[str]:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return []
        a = fn.args
        return [p.arg for p in (*a.posonlyargs, *a.args)]

    @staticmethod
    def _kwonly_params(fn: ast.AST) -> List[str]:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return []
        return [p.arg for p in fn.args.kwonlyargs]

    # -- abstract resolution --------------------------------------------------
    def resolve(self, node, scopes=(), overrides=None, depth=0):
        """Resolve an expression to a ValueSet, a tuple of resolved values,
        or :data:`UNPROVEN`."""
        if node is None or depth > _DEPTH_CAP:
            return UNPROVEN
        ov = overrides or {}
        if isinstance(node, ast.Constant):
            v = node.value
            if isinstance(v, bool) or not isinstance(v, int):
                return UNPROVEN
            return ValueSet.of(v)
        if isinstance(node, (ast.Tuple, ast.List)):
            return tuple(
                self.resolve(e, scopes, ov, depth + 1) for e in node.elts
            )
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
            return _fold2(lambda a, b: a - b, ValueSet.of(0),
                          self.resolve(node.operand, scopes, ov, depth + 1))
        if isinstance(node, ast.BinOp):
            a = self.resolve(node.left, scopes, ov, depth + 1)
            b = self.resolve(node.right, scopes, ov, depth + 1)
            if isinstance(node.op, ast.Add) and isinstance(a, tuple) and isinstance(b, tuple):
                return a + b
            ops = {
                ast.Add: lambda x, y: x + y,
                ast.Sub: lambda x, y: x - y,
                ast.Mult: lambda x, y: x * y,
                ast.FloorDiv: lambda x, y: x // y,
                ast.Mod: lambda x, y: x % y,
                ast.Pow: lambda x, y: x ** y if y >= 0 and y < 64 else 1 // 0,
            }
            f = ops.get(type(node.op))
            return _fold2(f, a, b) if f else UNPROVEN
        if isinstance(node, ast.Call):
            return self._resolve_call(node, scopes, ov, depth)
        if isinstance(node, ast.Name):
            return self._resolve_name(node.id, scopes, ov, depth)
        if isinstance(node, ast.Subscript):
            base = self.resolve(node.value, scopes, ov, depth + 1)
            if isinstance(base, tuple):
                idx = node.slice
                if isinstance(idx, ast.Constant) and isinstance(idx.value, int):
                    i = idx.value
                    if -len(base) <= i < len(base):
                        return base[i]
            return UNPROVEN
        if isinstance(node, ast.Attribute):
            # mod.CONST through a from-import of the module
            chain = _attr_chain(node)
            if chain and "." in chain:
                head, attr = chain.split(".", 1)
                if "." not in attr and head in self.from_imports:
                    mod, orig = self.from_imports[head]
                    return self._imported_const(f"{mod}.{orig}", attr)
            return UNPROVEN
        if isinstance(node, ast.IfExp):
            a = self.resolve(node.body, scopes, ov, depth + 1)
            b = self.resolve(node.orelse, scopes, ov, depth + 1)
            if isinstance(a, ValueSet) and isinstance(b, ValueSet):
                return ValueSet(a.values | b.values, a.complete and b.complete)
            return UNPROVEN
        return UNPROVEN

    def _resolve_call(self, node: ast.Call, scopes, ov, depth):
        name = _last(_attr_chain(node.func))
        args = [self.resolve(a, scopes, ov, depth + 1) for a in node.args]
        if name == "cdiv" and len(args) == 2:
            return _fold2(lambda a, b: -(-a // b), args[0], args[1])
        if name in ("min", "minimum") and len(args) == 2:
            return _fold2(min, args[0], args[1])
        if name in ("max", "maximum") and len(args) == 2:
            return _fold2(max, args[0], args[1])
        if name == "len" and len(args) == 1 and isinstance(args[0], tuple):
            return ValueSet.of(len(args[0]))
        if name == "int" and len(args) == 1:
            return args[0]
        if name == "tuple" and len(args) == 1 and isinstance(args[0], tuple):
            return args[0]
        return UNPROVEN

    def _resolve_name(self, name: str, scopes, ov, depth):
        if name in ov:
            return ov[name]
        key = (id(scopes[0]) if scopes else 0, name)
        if key in self._name_stack:
            return UNPROVEN
        self._name_stack.add(key)
        try:
            for i, fn in enumerate(scopes):
                b = self._binding_in(fn, name)
                if b is None:
                    continue
                outer = scopes[i:]
                if b[0] == "assign":
                    return self.resolve(b[1], outer, ov, depth + 1)
                if b[0] == "tupelem":
                    val = self.resolve(b[1], outer, ov, depth + 1)
                    if isinstance(val, tuple) and b[2] < len(val):
                        return val[b[2]]
                    return UNPROVEN
                if b[0] == "loopvar":
                    val = self.resolve(b[1], outer, ov, depth + 1)
                    if isinstance(val, tuple):
                        vals: Set[int] = set()
                        complete = True
                        for v in val:
                            if isinstance(v, ValueSet) and v.known:
                                vals |= v.values
                                complete = complete and v.complete
                            else:
                                complete = False
                        return ValueSet(frozenset(vals), complete)
                    return UNPROVEN
                if b[0] == "param":
                    return self._resolve_param(fn, name, scopes[i + 1:], ov, depth)
                return UNPROVEN  # "multi" / "dimof": not a static int
            if name in self.module_consts:
                return self.resolve(self.module_consts[name], (), ov, depth + 1)
            if name in self.from_imports:
                mod, orig = self.from_imports[name]
                return self._imported_const(mod, orig)
            return UNPROVEN
        finally:
            self._name_stack.discard(key)

    # -- parameters via intra-module call sites (incl. the autotune protocol) -
    def _param_bindings(self, fn: ast.AST, outer_scopes, ov, depth):
        """(arg_expr | ValueSet, call_node) pairs for each intra-module call
        of ``fn``, one entry per parameter, as raw material for configs."""
        fname = getattr(fn, "name", None)
        if not fname:
            return None
        sites: List[Tuple[Dict[str, ast.expr], ast.Call]] = []
        pos = self._positional_params(fn)
        for call in self.calls_by_name.get(fname, ())[:_CALLSITE_CAP]:
            if call in getattr(self, "_seen_calls", ()):
                continue
            bind: Dict[str, ast.expr] = {}
            ok = True
            if any(isinstance(a, ast.Starred) for a in call.args):
                ok = False
            else:
                for i, a in enumerate(call.args):
                    if _last(_attr_chain(call.func)) != fname:
                        ok = False
                        break
                    if i < len(pos):
                        bind[pos[i]] = a
                for kw in call.keywords:
                    if kw.arg:
                        bind[kw.arg] = kw.value
            if ok:
                sites.append((bind, call))
        # autotune protocol: fn passed as the builder to
        # autotune(name, key, candidates, build, default=...) — each candidate
        # becomes a synthetic one-param call site
        if len(pos) == 1:
            for call in self.calls_by_name.get("autotune", ()):
                if (
                    len(call.args) >= 4
                    and isinstance(call.args[3], ast.Name)
                    and call.args[3].id == fname
                ):
                    cands = self.resolve(
                        call.args[2], self.scope_of(call), ov, depth + 1
                    )
                    if isinstance(cands, tuple):
                        for c in cands:
                            sites.append(({pos[0]: c}, call))  # type: ignore[dict-item]
        return sites or None

    def _resolve_param(self, fn: ast.AST, name: str, outer_scopes, ov, depth):
        fname = getattr(fn, "name", None) or "<lambda>"
        key = (fname, name)
        if key in self._param_stack or depth > _DEPTH_CAP:
            return UNPROVEN
        self._param_stack.add(key)
        try:
            sites = self._param_bindings(fn, outer_scopes, ov, depth)
            default = self._param_default(fn, name)
            if sites is None:
                return UNPROVEN
            vals: Set[int] = set()
            complete = True
            for bind, call in sites:
                expr = bind.get(name, default)
                if expr is None:
                    complete = False
                    continue
                v = (
                    expr
                    if isinstance(expr, (ValueSet, tuple))
                    else self.resolve(expr, self.scope_of(call), {}, depth + 1)
                )
                if isinstance(v, ValueSet) and v.known:
                    vals |= v.values
                    complete = complete and v.complete
                else:
                    complete = False
            return ValueSet(frozenset(vals), complete)
        finally:
            self._param_stack.discard(key)

    def _param_default(self, fn: ast.AST, name: str) -> Optional[ast.expr]:
        a = fn.args
        pos = [p.arg for p in (*a.posonlyargs, *a.args)]
        if name in pos:
            i = pos.index(name) - (len(pos) - len(a.defaults))
            if 0 <= i < len(a.defaults):
                return a.defaults[i]
        for p, d in zip(a.kwonlyargs, a.kw_defaults):
            if p.arg == name and d is not None:
                return d
        return None

    def _imported_const(self, mod: str, orig: str):
        """A constant imported from another indexed module — literal values
        only (the cross-module leg of the resolution chain)."""
        if self.index is None:
            return UNPROVEN
        if mod not in self._foreign_consts:
            consts: Dict[str, ast.expr] = {}
            for g in self.index.modules():
                if g.dotted_name == mod:
                    for stmt in g.tree.body:
                        if (
                            isinstance(stmt, ast.Assign)
                            and len(stmt.targets) == 1
                            and isinstance(stmt.targets[0], ast.Name)
                        ):
                            consts[stmt.targets[0].id] = stmt.value
            self._foreign_consts[mod] = consts
        expr = self._foreign_consts[mod].get(orig)
        if expr is None:
            return UNPROVEN
        return self._literal_only(expr)

    def _literal_only(self, node: ast.AST):
        if isinstance(node, ast.Constant):
            v = node.value
            if isinstance(v, bool) or not isinstance(v, int):
                return UNPROVEN
            return ValueSet.of(v)
        if isinstance(node, (ast.Tuple, ast.List)):
            return tuple(self._literal_only(e) for e in node.elts)
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
            inner = self._literal_only(node.operand)
            return _fold2(lambda a, b: a - b, ValueSet.of(0), inner)
        return UNPROVEN

    # -- operand rank / dims / dtype ------------------------------------------
    def operand_info(self, expr, scopes, ov, depth=0):
        """(rank, dims tuple | None, dtype name | None) for a pallas_call
        operand expression, resolved opportunistically."""
        if expr is None or depth > 6:
            return (None, None, None)
        if isinstance(expr, ast.Call):
            name = _last(_attr_chain(expr.func))
            if name in ("zeros", "ones", "empty"):
                dims = self.resolve(expr.args[0], scopes, ov) if expr.args else UNPROVEN
                dt = _dtype_name(
                    expr.args[1] if len(expr.args) > 1 else self._kw(expr, "dtype")
                )
                if isinstance(dims, tuple):
                    return (len(dims), dims, dt)
                return (None, None, dt)
            if name == "full":
                dims = self.resolve(expr.args[0], scopes, ov) if expr.args else UNPROVEN
                dt = _dtype_name(
                    expr.args[2] if len(expr.args) > 2 else self._kw(expr, "dtype")
                )
                if isinstance(dims, tuple):
                    return (len(dims), dims, dt)
                return (None, None, dt)
            if name == "astype" and isinstance(expr.func, ast.Attribute):
                rank, dims, _ = self.operand_info(expr.func.value, scopes, ov, depth + 1)
                dt = _dtype_name(expr.args[0] if expr.args else None)
                return (rank, dims, dt)
            if name == "reshape" and isinstance(expr.func, ast.Attribute):
                _, _, dt = self.operand_info(expr.func.value, scopes, ov, depth + 1)
                shape_args = expr.args
                if len(shape_args) == 1 and isinstance(shape_args[0], (ast.Tuple, ast.List)):
                    shape_args = list(shape_args[0].elts)
                dims = tuple(self.resolve(a, scopes, ov) for a in shape_args)
                return (len(dims), dims, dt)
            if name == "asarray" and expr.args:
                rank, dims, _ = self.operand_info(expr.args[0], scopes, ov, depth + 1)
                dt = _dtype_name(
                    expr.args[1] if len(expr.args) > 1 else self._kw(expr, "dtype")
                )
                return (rank, dims, dt)
            if name == "broadcast_to" and len(expr.args) >= 2:
                dims = self.resolve(expr.args[1], scopes, ov)
                if isinstance(dims, tuple):
                    return (len(dims), dims, None)
            return (None, None, None)
        if isinstance(expr, ast.Name):
            for i, fn in enumerate(scopes):
                b = self._binding_in(fn, expr.id)
                if b is not None and b[0] == "assign":
                    return self.operand_info(b[1], scopes[i:], ov, depth + 1)
                if b is not None:
                    break
            # `b, s, h, d = x.shape` anywhere in scope fixes x's rank
            for fn in scopes:
                for stmt in self._scoped_stmts(fn):
                    if (
                        isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], (ast.Tuple, ast.List))
                        and isinstance(stmt.value, ast.Attribute)
                        and stmt.value.attr == "shape"
                        and isinstance(stmt.value.value, ast.Name)
                        and stmt.value.value.id == expr.id
                    ):
                        elts = stmt.targets[0].elts
                        dims = tuple(
                            self.resolve(e, scopes, ov)
                            if isinstance(e, ast.Name)
                            else UNPROVEN
                            for e in elts
                        )
                        return (len(elts), dims, None)
            return (None, None, None)
        if isinstance(expr, ast.Attribute) or isinstance(expr, ast.Subscript):
            return (None, None, None)
        return (None, None, None)

    @staticmethod
    def _kw(call: ast.Call, name: str) -> Optional[ast.expr]:
        for kw in call.keywords:
            if kw.arg == name:
                return kw.value
        return None

    # -- site extraction ------------------------------------------------------
    def evaluate(self) -> ModuleGeometry:
        geom = ModuleGeometry(self.path)
        for node in ast.walk(self.tree):
            if (
                isinstance(node, ast.Call)
                and _last(_attr_chain(node.func)) == "pallas_call"
                and (node.args or node.keywords)
            ):
                try:
                    geom.sites.append(self._eval_site(node))
                except RecursionError:  # pragma: no cover - defensive
                    continue
        return geom

    def _deref(self, expr, scopes):
        """Follow Name -> single local/module assignment hops."""
        hops = 0
        while isinstance(expr, ast.Name) and hops < 3:
            hops += 1
            nxt = None
            for i, fn in enumerate(scopes):
                b = self._binding_in(fn, expr.id)
                if b is not None:
                    if b[0] == "assign":
                        nxt = b[1]
                    break
            if nxt is None and expr.id in self.module_consts:
                nxt = self.module_consts[expr.id]
            if nxt is None:
                return expr
            expr = nxt
        return expr

    def _parse_blockspec(self, expr, scopes, kind, idx) -> SpecEval:
        expr = self._deref(expr, scopes)
        spec = SpecEval(kind=kind, index=idx, lineno=getattr(expr, "lineno", 0),
                        block_shape=None, index_map=None)
        if not (isinstance(expr, ast.Call) and _last(_attr_chain(expr.func)) == "BlockSpec"):
            return spec
        shape_expr = expr.args[0] if expr.args else self._kw(expr, "block_shape")
        map_expr = expr.args[1] if len(expr.args) > 1 else self._kw(expr, "index_map")
        # legacy argument order: BlockSpec(index_map, block_shape)
        if isinstance(shape_expr, ast.Lambda):
            shape_expr, map_expr = map_expr, shape_expr
        if shape_expr is not None:
            shape = self.resolve(shape_expr, scopes)
            if isinstance(shape, tuple):
                spec.block_shape = shape
                spec.shape_node = shape_expr
        if map_expr is not None:
            map_node = map_expr
            if isinstance(map_node, ast.Name):
                target = None
                for fn in scopes:
                    for sub in ast.walk(fn):
                        if (
                            isinstance(sub, ast.FunctionDef)
                            and sub.name == map_node.id
                        ):
                            target = sub
                            break
                    if target:
                        break
                target = target or self.defs.get(map_node.id)
                map_node = target
            if isinstance(map_node, (ast.Lambda, ast.FunctionDef)):
                spec.index_map = map_node
                spec.map_params = self._positional_params(map_node)
                spec.ret_arity = self._ret_arity(map_node)
        return spec

    @staticmethod
    def _ret_arity(fn: ast.AST) -> Optional[int]:
        if isinstance(fn, ast.Lambda):
            body = fn.body
            return len(body.elts) if isinstance(body, ast.Tuple) else 1
        arities: Set[int] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                v = node.value
                arities.add(len(v.elts) if isinstance(v, ast.Tuple) else 1)
        return arities.pop() if len(arities) == 1 else None

    def _ret_exprs(self, fn: ast.AST) -> Optional[List[ast.expr]]:
        if isinstance(fn, ast.Lambda):
            body = fn.body
            return list(body.elts) if isinstance(body, ast.Tuple) else [body]
        rets = [n for n in ast.walk(fn) if isinstance(n, ast.Return) and n.value]
        if len(rets) != 1:
            return None
        v = rets[0].value
        return list(v.elts) if isinstance(v, ast.Tuple) else [v]

    def _spec_list(self, expr, scopes, kind) -> List[SpecEval]:
        expr = self._deref(expr, scopes)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return [
                self._parse_blockspec(e, scopes, kind, i)
                for i, e in enumerate(expr.elts)
            ]
        return [self._parse_blockspec(expr, scopes, kind, 0)]

    def _out_shapes(self, expr, scopes):
        """[(dims tuple | None, dtype name | None, shape expr node | None)]"""
        expr = self._deref(expr, scopes)
        # [ShapeDtypeStruct(...)] * 3 replication idiom
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Mult):
            seq, n = expr.left, expr.right
            if isinstance(n, (ast.Tuple, ast.List)):
                seq, n = n, seq
            reps = self.resolve(n, scopes)
            if (
                isinstance(seq, (ast.Tuple, ast.List))
                and isinstance(reps, ValueSet)
                and reps.concrete() is not None
            ):
                out: List[Tuple[Optional[Tuple], Optional[str], Optional[ast.AST]]] = []
                for _ in range(min(32, reps.concrete() or 0)):
                    for item in seq.elts:
                        out.extend(self._out_shapes(item, scopes))
                return out
        items = expr.elts if isinstance(expr, (ast.Tuple, ast.List)) else [expr]
        out: List[Tuple[Optional[Tuple], Optional[str], Optional[ast.AST]]] = []
        for item in items:
            item = self._deref(item, scopes)
            if isinstance(item, ast.Call) and _last(_attr_chain(item.func)) == "ShapeDtypeStruct":
                shape_e = item.args[0] if item.args else self._kw(item, "shape")
                dtype_e = item.args[1] if len(item.args) > 1 else self._kw(item, "dtype")
                dims = self.resolve(shape_e, scopes) if shape_e is not None else UNPROVEN
                out.append(
                    (
                        dims if isinstance(dims, tuple) else None,
                        _dtype_name(dtype_e),
                        shape_e,
                    )
                )
            else:
                out.append((None, None, None))
        return out

    def _scratch_list(self, expr, scopes):
        """([(space, shape tuple, dtype)], [shape expr node]) pairs."""
        expr = self._deref(expr, scopes)
        items = expr.elts if isinstance(expr, (ast.Tuple, ast.List)) else [expr]
        out: List[Tuple[str, Tuple, Optional[str]]] = []
        nodes: List[Optional[ast.AST]] = []
        for item in items:
            if isinstance(item, ast.Call):
                space = _last(_attr_chain(item.func))
                shape = self.resolve(item.args[0], scopes) if item.args else UNPROVEN
                dt = _dtype_name(item.args[1] if len(item.args) > 1 else None)
                out.append(
                    (space, shape if isinstance(shape, tuple) else (), dt)
                )
                nodes.append(item.args[0] if item.args else None)
            else:
                out.append(("?", (), None))
                nodes.append(None)
        return out, nodes

    def _resolve_kernel(self, expr, scopes):
        """(kernel def node | None, name, bound kwarg names, bound leading
        positional count) through partial/local-assign hops."""
        expr = self._deref(expr, scopes)
        bound_kw: Set[str] = set()
        bound_pos = 0
        if isinstance(expr, ast.Call) and _last(_attr_chain(expr.func)) in (
            "partial",
        ):
            bound_kw = {kw.arg for kw in expr.keywords if kw.arg}
            bound_pos = max(0, len(expr.args) - 1)
            expr = self._deref(expr.args[0], scopes) if expr.args else expr
        if isinstance(expr, ast.Lambda):
            return expr, "<lambda>", bound_kw, bound_pos
        if isinstance(expr, ast.Name):
            target = None
            for fn in scopes:
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.FunctionDef) and sub.name == expr.id:
                        target = sub
                        break
                if target:
                    break
            target = target or self.defs.get(expr.id)
            if target is not None:
                return target, expr.id, bound_kw, bound_pos
            return None, expr.id, bound_kw, bound_pos
        if isinstance(expr, ast.FunctionDef):
            return expr, expr.name, bound_kw, bound_pos
        return None, "<unresolved>", bound_kw, bound_pos

    # -- configurations -------------------------------------------------------
    def _site_configs(self, scopes) -> List[Dict[str, object]]:
        """Expand the innermost *named* enclosing function's parameters into
        per-call-site configurations, splitting small complete value sets so
        correlated quantities (grid derived from a block-size param) stay
        consistent within each configuration."""
        fn = next(
            (s for s in scopes if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))),
            None,
        )
        chain_fns = [
            s for s in scopes if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        configs: List[Dict[str, object]] = [{}]
        for fn in chain_fns[:2]:  # innermost def and its enclosing def
            sites = self._param_bindings(fn, (), {}, 0)
            if not sites:
                continue
            expanded: List[Dict[str, object]] = []
            for bind, call in sites:
                env: Dict[str, object] = {}
                for pname in self._positional_params(fn) + self._kwonly_params(fn):
                    expr = bind.get(pname, self._param_default(fn, pname))
                    if expr is None:
                        continue
                    v = (
                        expr
                        if isinstance(expr, (ValueSet, tuple))
                        else self.resolve(expr, self.scope_of(call), {}, 1)
                    )
                    if isinstance(v, ValueSet) and not v.known:
                        continue
                    env[pname] = v
                expanded.append(env)
            # split multi-valued complete params into singleton configs
            split: List[Dict[str, object]] = []
            for env in expanded:
                axes = [
                    (k, sorted(v.values))
                    for k, v in env.items()
                    if isinstance(v, ValueSet) and v.complete and 1 < len(v.values) <= 8
                ]
                if not axes or len(split) > _CONFIG_CAP:
                    split.append(env)
                    continue
                keys = [k for k, _ in axes]
                for combo in itertools.product(*(vs for _, vs in axes)):
                    if len(split) > _CONFIG_CAP:
                        break
                    e = dict(env)
                    for k, val in zip(keys, combo):
                        e[k] = ValueSet.of(val)
                    split.append(e)
            merged: List[Dict[str, object]] = []
            for base in configs:
                for env in split[:_CONFIG_CAP]:
                    if len(merged) > _CONFIG_CAP:
                        break
                    m = dict(env)
                    m.update(base)  # inner binding wins
                    merged.append(m)
            configs = merged or configs
        # dedupe identical configs
        uniq: List[Dict[str, object]] = []
        seen: Set[str] = set()
        for c in configs:
            key = repr(sorted((k, repr(v)) for k, v in c.items()))
            if key not in seen:
                seen.add(key)
                uniq.append(c)
        return uniq[:_CONFIG_CAP]

    # -- full site evaluation -------------------------------------------------
    def _eval_site(self, call: ast.Call) -> SiteEval:
        scopes = self.scope_of(call)
        kw = {k.arg: k.value for k in call.keywords if k.arg}
        kernel_node, kernel_name, bound_kw, bound_pos = (
            self._resolve_kernel(call.args[0], scopes)
            if call.args
            else (None, "<none>", set(), 0)
        )

        grid_expr = kw.get("grid")
        in_specs_expr = kw.get("in_specs")
        out_specs_expr = kw.get("out_specs")
        scratch_expr = kw.get("scratch_shapes")
        out_shape_expr = kw.get("out_shape")
        nsp = 0
        prefetch = False
        gs = kw.get("grid_spec")
        if gs is not None:
            gs = self._deref(gs, scopes)
            if isinstance(gs, ast.Call):
                gname = _last(_attr_chain(gs.func))
                prefetch = gname == "PrefetchScalarGridSpec"
                gkw = {k.arg: k.value for k in gs.keywords if k.arg}
                grid_expr = gkw.get("grid", grid_expr)
                in_specs_expr = gkw.get("in_specs", in_specs_expr)
                out_specs_expr = gkw.get("out_specs", out_specs_expr)
                scratch_expr = gkw.get("scratch_shapes", scratch_expr)
                if prefetch:
                    nexpr = gkw.get("num_scalar_prefetch") or (
                        gs.args[0] if gs.args else None
                    )
                    nval = self.resolve(nexpr, scopes) if nexpr is not None else UNPROVEN
                    if isinstance(nval, ValueSet) and nval.concrete() is not None:
                        nsp = nval.concrete() or 0

        site = SiteEval(
            path=self.path,
            lineno=call.lineno,
            kernel_name=kernel_name,
            kernel_node=kernel_node,
            kernel_params=None,
            has_vararg=False,
            grid_len=None,
            grid=None,
            num_scalar_prefetch=nsp,
            prefetch_grid_spec=prefetch,
        )
        if kernel_node is not None:
            params = self._positional_params(kernel_node)
            params = params[bound_pos:]
            params = [p for p in params if p not in bound_kw]
            site.kernel_params = params
            site.has_vararg = bool(
                getattr(kernel_node, "args", None)
                and (kernel_node.args.vararg or kernel_node.args.kwarg)
            )

        configs = self._site_configs(scopes)

        # grid: resolve under the first config for structure, merge extents
        grid_vals: List[Tuple] = []
        for cfg in configs:
            g = self.resolve(grid_expr, scopes, cfg) if grid_expr is not None else None
            if isinstance(g, ValueSet):
                g = (g,)
            if isinstance(g, tuple):
                grid_vals.append(g)
        if grid_expr is not None:
            lens = {len(g) for g in grid_vals}
            if len(lens) == 1:
                site.grid_len = lens.pop()
                merged = []
                for d in range(site.grid_len):
                    vals: Set[int] = set()
                    complete = True
                    for g in grid_vals:
                        v = g[d]
                        if isinstance(v, ValueSet) and v.known:
                            vals |= v.values
                            complete = complete and v.complete
                        else:
                            complete = False
                    merged.append(ValueSet(frozenset(vals), complete))
                site.grid = tuple(merged)
            else:
                # structurally unresolvable grid (e.g. computed tuple)
                g = self.resolve(grid_expr, scopes) if grid_expr is not None else None
                if isinstance(g, tuple):
                    site.grid_len = len(g)
                    site.grid = g

        if in_specs_expr is not None:
            site.in_specs = self._spec_list(in_specs_expr, scopes, "in")
        if out_specs_expr is not None:
            site.out_specs = self._spec_list(out_specs_expr, scopes, "out")
            site.out_specs_declared = True
        if scratch_expr is not None:
            site.scratch, site.scratch_nodes = self._scratch_list(scratch_expr, scopes)
            site.n_scratch = len(site.scratch)
        out_shapes = (
            self._out_shapes(out_shape_expr, scopes)
            if out_shape_expr is not None
            else []
        )
        site.n_out_shapes = len(out_shapes) if out_shape_expr is not None else None

        # operands: pallas_call(...)(op0, op1, ...)
        outer = self.parents.get(call)
        operands: List[ast.expr] = []
        if isinstance(outer, ast.Call) and outer.func is call:
            operands = list(outer.args)
        for i, spec in enumerate(site.in_specs):
            oi = nsp + i
            if oi < len(operands):
                rank, dims, dt = self.operand_info(operands[oi], scopes, {})
                spec.operand_rank, spec.operand_dims, spec.operand_dtype = rank, dims, dt
                spec.dims_node, spec.dims_is_operand = operands[oi], True
        for i, spec in enumerate(site.out_specs):
            if i < len(out_shapes):
                dims, dt, shape_e = out_shapes[i]
                if dims is not None:
                    spec.operand_rank = len(dims)
                    spec.operand_dims = dims
                    spec.dims_node, spec.dims_is_operand = shape_e, False
                spec.operand_dtype = dt
        if not site.out_specs and out_shapes:
            # out_shape without out_specs: whole-array outputs, no window math
            for i, (dims, dt, shape_e) in enumerate(out_shapes):
                site.out_specs.append(
                    SpecEval(
                        kind="out", index=i, lineno=call.lineno,
                        block_shape=None, index_map=None,
                        operand_rank=len(dims) if dims is not None else None,
                        operand_dims=dims, operand_dtype=dt,
                        dims_node=shape_e, dims_is_operand=False,
                    )
                )
        site.grid_node = grid_expr

        self._prove_axes(site, scopes, configs)
        self._eval_vmem(site, scopes, configs)
        if site.prefetch_grid_spec and site.num_scalar_prefetch > 0:
            self._check_prefetch_indexing(site, scopes)
        return site

    # -- prefetch-ref indexing discipline (PG904) ------------------------------
    def _is_immutable_literal(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Constant):
            return True
        if isinstance(node, ast.Tuple):
            return all(self._is_immutable_literal(e) for e in node.elts)
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
            return self._is_immutable_literal(node.operand)
        return False

    _BUILTIN_NAMES = {"len", "min", "max", "int", "abs", "range", "sum", "divmod"}

    def _check_prefetch_indexing(self, site: SiteEval, scopes) -> None:
        """Inside a PrefetchScalarGridSpec index map, a prefetch ref may only
        be subscripted by grid/prefetch-derived values, map locals, and
        immutable constants — never by unbound names or mutable module
        state."""
        for spec in site.in_specs + site.out_specs:
            if spec.index_map is None or not spec.map_params:
                continue
            n_grid = site.grid_len if site.grid_len is not None else max(
                0, len(spec.map_params) - site.num_scalar_prefetch
            )
            prefetch_params = set(spec.map_params[n_grid:])
            if not prefetch_params:
                continue
            fn = spec.index_map
            local_names: Set[str] = set(spec.map_params)
            if isinstance(fn, ast.FunctionDef):
                for sub in ast.walk(fn):
                    if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                        targets = (
                            sub.targets
                            if isinstance(sub, ast.Assign)
                            else [sub.target]
                        )
                        for t in targets:
                            for n in ast.walk(t):
                                if isinstance(n, ast.Name):
                                    local_names.add(n.id)
            for sub in ast.walk(fn):
                if not (
                    isinstance(sub, ast.Subscript)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id in prefetch_params
                ):
                    continue
                bad: List[str] = []
                for n in ast.walk(sub.slice):
                    if not (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)):
                        continue
                    name = n.id
                    if (
                        name in local_names
                        or name in self.import_aliases
                        or name in self._BUILTIN_NAMES
                        or name in self.from_imports
                    ):
                        continue
                    if any(
                        self._binding_in(f, name) is not None for f in scopes
                    ):
                        continue  # closure-derived: grid/param lineage
                    const = self.module_consts.get(name)
                    if const is not None and self._is_immutable_literal(const):
                        continue
                    bad.append(name)
                if bad:
                    site.prefetch_indexing.append(
                        (
                            getattr(sub, "lineno", spec.lineno),
                            f"prefetch ref '{sub.value.id}' indexed by non-grid "
                            f"value(s): {', '.join(sorted(set(bad)))}",
                        )
                    )

    # -- in-bounds proofs ------------------------------------------------------
    def _prove_axes(self, site: SiteEval, scopes, configs) -> None:
        n_grid = site.grid_len
        for spec in site.in_specs + site.out_specs:
            if spec.block_shape is None or spec.index_map is None:
                continue
            rets = self._ret_exprs(spec.index_map)
            if rets is None or len(rets) != len(spec.block_shape):
                continue  # rank mismatch — PG901 territory, not window math
            map_scopes = (spec.index_map,) + tuple(scopes)
            grid_params = (
                spec.map_params[: n_grid]
                if n_grid is not None
                else spec.map_params[: max(0, len(spec.map_params) - site.num_scalar_prefetch)]
            )
            prefetch_params = spec.map_params[len(grid_params):]
            for d in range(len(spec.block_shape)):
                status, detail = self._prove_dim(
                    site, spec, d, rets[d], grid_params, prefetch_params,
                    map_scopes, configs,
                )
                site.axis_proofs.append(
                    AxisProof(
                        kind=spec.kind, spec_index=spec.index, dim=d,
                        status=status, detail=detail, lineno=spec.lineno,
                    )
                )

    def _cfg_tuple(self, node, scopes, cfg, fallback=None):
        """Re-resolve a stored shape/grid expr under one configuration, so
        correlated quantities (a grid computed from the block-size param a
        BlockSpec also uses) stay consistent per config."""
        if node is not None:
            v = self.resolve(node, scopes, cfg)
            if isinstance(v, ValueSet):
                v = (v,)
            if isinstance(v, tuple):
                return v
        return fallback

    def _cfg_dims(self, spec, scopes, cfg):
        if spec.dims_node is not None:
            if spec.dims_is_operand:
                _, dims, _ = self.operand_info(spec.dims_node, scopes, cfg)
                if dims is not None:
                    return dims
            else:
                v = self.resolve(spec.dims_node, scopes, cfg)
                if isinstance(v, tuple):
                    return v
        return spec.operand_dims

    def _prove_dim(
        self, site, spec, d, comp, grid_params, prefetch_params, map_scopes, configs,
    ) -> Tuple[str, str]:
        scopes = tuple(map_scopes[1:])
        any_unproven = False
        for cfg in configs:
            ov: Dict[str, object] = dict(cfg)
            for p in prefetch_params:
                ov[p] = UNPROVEN
            blk_t = self._cfg_tuple(spec.shape_node, scopes, cfg, spec.block_shape)
            blk_v = (
                blk_t[d]
                if blk_t is not None and d < len(blk_t) and isinstance(blk_t[d], ValueSet)
                else UNPROVEN
            )
            if not blk_v.known:
                any_unproven = True
                continue
            dims_cfg = self._cfg_dims(spec, scopes, cfg)
            dim_v = (
                dims_cfg[d]
                if dims_cfg is not None
                and d < len(dims_cfg)
                and isinstance(dims_cfg[d], ValueSet)
                else UNPROVEN
            )
            grid_t = self._cfg_tuple(site.grid_node, scopes, cfg, site.grid)
            # corner assignments over the grid params this component reads
            free = {
                n.id
                for n in ast.walk(comp)
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
            }
            deps = [p for p in grid_params if p in free]
            corner_sets: List[List[int]] = []
            complete_corners = True
            for p in deps:
                gi = grid_params.index(p)
                ext = (
                    grid_t[gi]
                    if grid_t is not None
                    and gi < len(grid_t)
                    and isinstance(grid_t[gi], ValueSet)
                    else UNPROVEN
                )
                if ext.known:
                    corners = sorted({0} | {e - 1 for e in ext.values if e > 0})
                    complete_corners = complete_corners and ext.complete
                    corner_sets.append(corners)
                else:
                    corner_sets.append([0])
                    complete_corners = False
            proven_here = True
            for combo in itertools.product(*corner_sets) if corner_sets else [()]:
                ov_c = dict(ov)
                for p in grid_params:
                    ov_c.setdefault(p, ValueSet.of(0))
                for p, v in zip(deps, combo):
                    ov_c[p] = ValueSet.of(v)
                idx = self.resolve(comp, map_scopes, ov_c)
                if not isinstance(idx, ValueSet) or not idx.known:
                    proven_here = False
                    continue
                win_end = _fold2(
                    lambda i, b: i * b + b, idx, blk_v
                )
                if not win_end.known:
                    proven_here = False
                    continue
                if dim_v.known:
                    # a concrete overrun witness refutes the window
                    if (
                        idx.concrete() is not None
                        and blk_v.concrete() is not None
                        and dim_v.concrete() is not None
                        and win_end.concrete() is not None
                        and win_end.concrete() > dim_v.concrete()
                    ):
                        return (
                            "overrun",
                            f"{spec.kind}_spec[{spec.index}] dim {d}: window end "
                            f"{win_end.concrete()} > dim {dim_v.concrete()} "
                            f"(block {blk_v.concrete()}, block index {idx.concrete()}"
                            + (
                                ", config "
                                + ",".join(
                                    f"{k}={v.concrete()}"
                                    for k, v in cfg.items()
                                    if isinstance(v, ValueSet) and v.concrete() is not None
                                )
                                if cfg
                                else ""
                            )
                            + ")",
                        )
                    if not (
                        win_end.complete
                        and dim_v.complete
                        and max(win_end.values) <= min(dim_v.values)
                    ):
                        proven_here = False
                else:
                    proven_here = False
            if not (proven_here and complete_corners and blk_v.complete):
                any_unproven = True
        if any_unproven or not configs:
            return ("unproven", f"{spec.kind}_spec[{spec.index}] dim {d}: symbolic residue")
        return ("proven", "")

    # -- VMEM footprint --------------------------------------------------------
    def _eval_vmem(self, site: SiteEval, scopes, configs) -> None:
        for cfg in configs:
            total = ValueSet.of(0)
            assumed = False
            for spec in site.in_specs + site.out_specs:
                shape = self._cfg_tuple(spec.shape_node, scopes, cfg, spec.block_shape)
                if shape is None:
                    shape = self._cfg_dims(spec, scopes, cfg)  # whole-array window
                if shape is None:
                    total = UNPROVEN
                    break
                width = DTYPE_BYTES.get(spec.operand_dtype or "", 0)
                if width == 0:
                    width = 1  # sound lower bound when the dtype is unknown
                    assumed = True
                bytes_v = ValueSet.of(width)
                for dv in shape:
                    dv_c = dv if isinstance(dv, ValueSet) else UNPROVEN
                    bytes_v = _fold2(lambda a, b: a * b, bytes_v, dv_c)
                total = _fold2(lambda a, b: a + b, total, bytes_v)
            if isinstance(total, ValueSet) and total.known:
                for i, (space, shape, dt) in enumerate(site.scratch):
                    if space not in ("VMEM", "SMEM"):
                        continue
                    node = (
                        site.scratch_nodes[i]
                        if i < len(site.scratch_nodes)
                        else None
                    )
                    shape_t = self._cfg_tuple(node, scopes, cfg, shape)
                    width = DTYPE_BYTES.get(dt or "", 0)
                    if width == 0:
                        width = 1
                        assumed = True
                    bytes_v = ValueSet.of(width)
                    for dv in shape_t or ():
                        bytes_v = _fold2(
                            lambda a, b: a * b, bytes_v,
                            dv if isinstance(dv, ValueSet) else UNPROVEN,
                        )
                    total = _fold2(lambda a, b: a + b, total, bytes_v)
            binding = {
                k: v.concrete()
                for k, v in cfg.items()
                if isinstance(v, ValueSet) and v.concrete() is not None
            }
            site.vmem_configs.append(
                VmemConfig(
                    binding=binding,
                    bytes_per_step=total if isinstance(total, ValueSet) else UNPROVEN,
                    assumed_width=assumed,
                )
            )

def evaluate_module(path: str, tree: ast.Module, index=None) -> ModuleGeometry:
    """Evaluate every ``pl.pallas_call`` site in ``tree``.  ``index`` is the
    run's :class:`~paddle_tpu.analysis.dataflow.PackageIndex`, used for
    imported-constant resolution; pass None for single-file runs."""
    return _ModuleEval(path, tree, index).evaluate()
