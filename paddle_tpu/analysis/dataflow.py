"""Interprocedural dataflow layer under the checker suite.

The single-file AST checkers (TS/PK/FD/EH/RB/OB) pattern-match one tree at a
time; the two bug classes that slipped past them — the PR 6 recovery-replay
race (host numpy vectors mutated while an async dispatch still aliased them)
and per-dispatch registry-lock reads — need facts that span functions and
modules: who calls whom, which functions run on which threads, which lock is
held where, and which buffers a jit dispatch donated or aliased. This module
computes those facts ONCE per run and shares them across checkers:

- :class:`ModuleGraph` — per-module facts, built once per file and memoized
  in the :class:`PackageIndex` (the CI gate budget depends on this: the
  CC and DN checker families both consume the same graphs);
- **call graph** (package-local): edges resolved through ``self.method()``,
  bound instance fields (``self._mgr = BlockKVCache(...)`` in ``__init__``),
  module-level singletons (``GLOBAL_FLAGS = FlagRegistry()``), plain module
  functions, and package imports (``from paddle_tpu.x import f`` /
  ``import paddle_tpu.x.y as alias``). Unresolvable receivers produce no
  edge — the graph under-approximates, so reachability-based checks miss
  rather than spam;
- **thread entries**: ``threading.Thread(target=...)`` targets, HTTP handler
  classes (``BaseHTTPRequestHandler`` subclasses — every ``do_*``/helper
  method runs on a server thread), and flag-listener registrations
  (``GLOBAL_FLAGS.on_change(name, fn)`` — listeners fire on whichever
  thread calls ``set_flags``);
- **lock-held regions**: ``with self._lock:`` / ``with MODULE_LOCK:`` scopes
  recorded on every field access and call site, so the CC checkers know the
  holding set at each point (keys are ``Class._lock`` / module-level names);
- **reaching defs** (intraprocedural, statement-ordered): jit-wrapper
  bindings (``self._fn = jax.jit(impl, donate_argnums=...)``), host numpy
  buffer bindings, and ``jnp.asarray(buf)`` aliases — what the DN family
  walks to find use-after-donate and mutate-before-sync hazards.

Everything here is ``ast``-only (no imports of the analyzed code), like the
rest of the analysis package.
"""

from __future__ import annotations

import ast
import time
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "CallSite",
    "ClassInfo",
    "FieldAccess",
    "FunctionInfo",
    "JitWrapper",
    "ModuleGraph",
    "PackageIndex",
    "ProtocolCall",
    "receiver_key",
]

# constructors that make a field an inherently thread-safe sync primitive —
# method calls on such fields are not shared-state hazards (Queue/Event do
# their own locking); the lock kinds double as the lock-field detector
_SYNC_CTORS = {
    "Lock": "lock", "RLock": "lock", "Condition": "sync", "Event": "sync",
    "Semaphore": "sync", "BoundedSemaphore": "sync", "Barrier": "sync",
    "Queue": "sync", "SimpleQueue": "sync", "LifoQueue": "sync",
    "PriorityQueue": "sync", "local": "sync",
}
# constructors that make a field a plain mutable container: mutator METHOD
# calls on it count as writes for the guarded-field inference
_CONTAINER_CTORS = {"dict", "set", "list", "deque", "defaultdict", "OrderedDict", "Counter"}
_MUTATOR_METHODS = {
    "append", "appendleft", "add", "extend", "insert", "remove", "discard",
    "pop", "popleft", "popitem", "clear", "update", "setdefault", "sort",
    "reverse", "rotate",
}
# numpy array constructors: a name/field assigned from one of these is a HOST
# buffer (jax's CPU backend zero-copies them into device arrays)
_NUMPY_CTORS = {
    "zeros", "ones", "empty", "full", "asarray", "array", "arange",
    "concatenate", "frombuffer", "copy", "zeros_like", "ones_like",
}
_HTTP_HANDLER_BASES = {"BaseHTTPRequestHandler", "SimpleHTTPRequestHandler"}

# -- distributed-protocol vocabulary (the CM checker family) -------------------
# python-level collective entry points (distributed/collective.py parity
# surface) — only a collective when the call resolves through a distributed
# import, so a local function that happens to be named `barrier` never counts
_COLLECTIVE_OPS = {
    "all_reduce", "all_gather", "all_gather_object", "reduce", "reduce_scatter",
    "broadcast", "scatter", "alltoall", "alltoall_single", "send", "recv",
    "isend", "irecv", "ppermute", "batch_isend_irecv", "barrier",
}
# shard_map-level primitives: unambiguous under a jax.lax / lax chain
_LAX_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "ppermute", "all_gather", "all_to_all",
    "psum_scatter", "pshuffle",
}
# coordination-service KV names are globally unambiguous; the short
# set/get/wait/delete forms additionally need a store-shaped receiver
_STORE_METHOD_KINDS = {
    "set": "store_set", "key_value_set": "store_set",
    "get": "store_get", "wait": "store_get",
    "blocking_key_value_get": "store_get", "key_value_get": "store_get",
    "key_value_try_get": "store_get", "wait_at_barrier": "store_get",
    "delete": "store_delete", "key_value_delete": "store_delete",
    "delete_key": "store_delete",
}
_STORE_UNAMBIGUOUS = {
    "key_value_set", "blocking_key_value_get", "key_value_get",
    "key_value_try_get", "wait_at_barrier", "key_value_delete",
}


def _store_receiver(name: str) -> bool:
    """A receiver segment that denotes a coordination store/KV client —
    deliberately narrow so `self._store` deques in observability modules
    (append/clear only) and dict `.get` on arbitrary names stay out."""
    n = name.lstrip("_").lower()
    return "store" in n or n in ("client", "kv")


def receiver_key(node: ast.AST) -> Optional[str]:
    """``name`` for a Name, ``self.attr`` for a self attribute — the alias
    granularity every map in this module keys on (same as RB502)."""
    if isinstance(node, ast.Name):
        return node.id
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return f"self.{node.attr}"
    return None


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _warn_fallback_callees(tree: ast.Module) -> Set[str]:
    """Simple names of every function called inside a function whose body
    calls ``warn_fallback`` with a literal kernel label (the PG905 coverage
    contribution of one module)."""
    covered: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        names: Set[str] = set()
        has_wf = False
        for c in ast.walk(node):
            if not isinstance(c, ast.Call):
                continue
            chain = _dotted(c.func)
            simple = chain.split(".")[-1] if chain else None
            if simple == "warn_fallback":
                if c.args and isinstance(c.args[0], ast.Constant):
                    has_wf = True
            elif simple:
                names.add(simple)
        if has_wf:
            covered |= names
    return covered


def _mesh_axes_of_tree(tree: ast.Module) -> Set[str]:
    """Standalone mesh-axis collection for lazily-parsed package files (the
    scoped-run fallback in :meth:`PackageIndex.mesh_axes`) — same rules as
    :meth:`ModuleGraph._collect_mesh_axes` without building a full graph."""
    str_consts: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)) and getattr(node, "value", None):
            val = node.value
            if isinstance(val, ast.Constant) and isinstance(val.value, str):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    if isinstance(t, ast.Name):
                        str_consts[t.id] = val.value
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None
        )
        exprs: List[ast.AST] = []
        if name in ("Mesh", "make_mesh") and len(node.args) >= 2:
            exprs.append(node.args[1])
        if name == "init_mesh" and node.args:
            exprs.append(node.args[0])
        for kw in node.keywords:
            if kw.arg in ("axis_name", "axis_names", "dim_names"):
                exprs.append(kw.value)
        for e in exprs:
            for n in ast.walk(e):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    out.add(n.value)
                elif isinstance(n, ast.Name) and n.id in str_consts:
                    out.add(str_consts[n.id])
    return out


@dataclass
class CallSite:
    """One call expression with its interprocedural context."""

    target: str  # resolved node key "<module>::<qualname>"
    node: ast.Call
    lineno: int
    in_loop: bool  # lexically inside for/while/comprehension in the caller
    locks_held: FrozenSet[str]


@dataclass
class ProtocolCall:
    """One distributed-protocol operation (collective or coordination-store
    op) with the context the CM checkers consume. Recorded whether or not the
    call graph can resolve the callee — protocol identity comes from the
    import/receiver shape, not from resolution."""

    kind: str  # "collective" | "store_set" | "store_get" | "store_delete"
    op: str  # simple op name ("all_reduce", "key_value_set", ...)
    chain: str  # dotted call chain as written ("dist.all_reduce")
    node: ast.Call
    lineno: int
    col: int
    func: str  # qualname of the enclosing function
    locks_held: FrozenSet[str]
    in_loop: bool


@dataclass
class FieldAccess:
    field: str
    func: str  # qualname of the accessing function ("" = class/module body)
    kind: str  # "read" | "write" | "iterate"
    locks_held: FrozenSet[str]
    node: ast.AST
    lineno: int
    col: int
    in_init: bool


@dataclass
class JitWrapper:
    """A binding ``<key> = jax.jit(fn, donate_argnums=...)``. ``donated`` is
    the set of argument positions that MAY be donated (constants collected
    from tuples anywhere in the kwarg expression — the engine's conditional
    ``(1,) if donate else ()`` idiom resolves to {1})."""

    key: str  # local name or "self.attr"
    target: Optional[str]  # resolved wrapped-function node key, if any
    donated: FrozenSet[int]
    lineno: int


@dataclass
class FunctionInfo:
    qualname: str  # "fn", "Class.fn", "outer.<locals>.fn"
    node: ast.AST  # FunctionDef / AsyncFunctionDef
    class_name: Optional[str]
    calls: List[CallSite] = field(default_factory=list)
    # every lock key this function acquires directly (with-statement)
    acquires: List[Tuple[str, FrozenSet[str], ast.AST]] = field(default_factory=list)


@dataclass
class ClassInfo:
    name: str
    node: ast.ClassDef
    base_names: Set[str]
    # lock-kind fields (self._lock = threading.Lock()/RLock())
    lock_fields: Set[str] = field(default_factory=set)
    # field -> "sync" | "container" | "numpy" | "plain" (last assign wins)
    field_kinds: Dict[str, str] = field(default_factory=dict)
    accesses: List[FieldAccess] = field(default_factory=list)
    # field -> class name, for self._mgr = SomeClass(...) bindings in methods
    instance_fields: Dict[str, str] = field(default_factory=dict)

    def fields_locked_somewhere(self) -> Dict[str, Set[str]]:
        out: Dict[str, Set[str]] = {}
        for a in self.accesses:
            for lk in a.locks_held:
                out.setdefault(a.field, set()).add(lk)
        return out


class ModuleGraph:
    """All per-module facts. Built once by :class:`PackageIndex`."""

    def __init__(self, path: str, tree: ast.Module, dotted_name: Optional[str]) -> None:
        self.path = path
        self.tree = tree
        self.dotted_name = dotted_name  # "paddle_tpu.serving.frontend" or None
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        # alias -> dotted module name, for "import paddle_tpu.x as y"
        self.module_aliases: Dict[str, str] = {}
        # local name -> (dotted module, original name), for from-imports
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        # module-level NAME = ClassName(...) singletons (class local or imported)
        self.instances: Dict[str, str] = {}
        # module-level lock names (NAME = threading.Lock())
        self.module_locks: Set[str] = set()
        # (qualname, kind) thread entries: kind in thread|handler|listener
        self.thread_entries: List[Tuple[str, str, int]] = []
        # jit wrappers visible module-wide (self.attr ones are class-scoped
        # but donation is keyed by receiver, which includes the class context)
        self.jit_wrappers: Dict[Tuple[Optional[str], str], JitWrapper] = {}
        # distributed-protocol ops (collectives + coordination-store calls)
        self.protocol_calls: List[ProtocolCall] = []
        # module-level NAME = "string" constants (TP_AXIS = "tp" style)
        self.str_consts: Dict[str, str] = {}
        # mesh axis names defined in this module (Mesh/ProcessMesh/new_group)
        self.mesh_axes: Set[str] = set()
        self._build()

    # -- construction --------------------------------------------------------
    def _build(self) -> None:
        """Two-phase: register every function shell and binding first (so a
        call to a method defined LATER in the file still resolves), then
        walk bodies for accesses/calls/acquires."""
        self._collect_imports()
        self._collect_module_level()
        to_walk: List[Tuple[ast.AST, Optional[str]]] = []
        for cls_node in [n for n in self.tree.body if isinstance(n, ast.ClassDef)]:
            self._register_class(cls_node)
            for item in cls_node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._register_function(item, cls_node.name)
                    to_walk.append((item, cls_node.name))
        for fn in [
            n for n in self.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]:
            self._register_function(fn, None)
            to_walk.append((fn, None))
        for fn, class_name in to_walk:
            self._prescan_bindings(fn, class_name)
        for fn, class_name in to_walk:
            self._walk_function(fn, class_name)
        self._collect_thread_entries()
        self._collect_mesh_axes()

    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.module_aliases[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_imports[a.asname or a.name] = (node.module, a.name)

    def _collect_module_level(self) -> None:
        for node in self.tree.body:
            if isinstance(node, (ast.Assign, ast.AnnAssign)) and getattr(node, "value", None):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                val = node.value
                wrapper = self._match_jit(val)
                if wrapper is not None:
                    # MODULE-level jit binding: visible to every function in
                    # the module (function-local ones are scoped to their own
                    # function by the DN scan — a bare name bound in one
                    # function must not taint same-named locals elsewhere)
                    target_fn, donated = wrapper
                    for t in targets:
                        if isinstance(t, ast.Name):
                            self.jit_wrappers[(None, t.id)] = JitWrapper(
                                key=t.id, target=target_fn, donated=donated,
                                lineno=node.lineno,
                            )
                if isinstance(val, ast.Constant) and isinstance(val.value, str):
                    for t in targets:
                        if isinstance(t, ast.Name):
                            self.str_consts[t.id] = val.value
                if isinstance(val, ast.Call):
                    ctor = self._ctor_name(val.func)
                    for t in targets:
                        if not isinstance(t, ast.Name):
                            continue
                        if ctor in ("Lock", "RLock"):
                            self.module_locks.add(t.id)
                        elif ctor and ctor[0].isupper():
                            self.instances[t.id] = ctor

    def _ctor_name(self, fn: ast.AST) -> Optional[str]:
        """Constructor simple name for Name()/mod.Name() calls."""
        if isinstance(fn, ast.Name):
            return fn.id
        if isinstance(fn, ast.Attribute):
            return fn.attr
        return None

    def _collect_mesh_axes(self) -> None:
        """Axis names this module *defines*: ``Mesh(devices, (names...))`` /
        ``jax.make_mesh(shape, names)`` second argument, ``init_mesh(names,
        shape)`` first argument, and any ``axis_name=``/``axis_names=``/
        ``dim_names=`` keyword anywhere (``ProcessMesh``, ``new_group``,
        ``shard_map``). String constants are collected from anywhere inside
        the argument expression; names resolve through module-level string
        constants (the ``TP_AXIS = "tp"`` idiom). Over-collection only makes
        CM1005 quieter — the axis universe is a membership check."""
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            name = self._ctor_name(node.func)
            exprs: List[ast.AST] = []
            if name in ("Mesh", "make_mesh") and len(node.args) >= 2:
                exprs.append(node.args[1])
            if name == "init_mesh" and node.args:
                exprs.append(node.args[0])
            for kw in node.keywords:
                if kw.arg in ("axis_name", "axis_names", "dim_names"):
                    exprs.append(kw.value)
            for e in exprs:
                for n in ast.walk(e):
                    if isinstance(n, ast.Constant) and isinstance(n.value, str):
                        self.mesh_axes.add(n.value)
                    elif isinstance(n, ast.Name) and n.id in self.str_consts:
                        self.mesh_axes.add(self.str_consts[n.id])

    def _register_class(self, cls: ast.ClassDef) -> None:
        self.classes[cls.name] = ClassInfo(
            name=cls.name, node=cls,
            base_names={b for b in (_dotted(x) for x in cls.bases) if b} | {
                x.rsplit(".", 1)[-1] for x in (_dotted(b) for b in cls.bases) if x
            },
        )

    def _register_function(self, fn: ast.AST, class_name: Optional[str]) -> FunctionInfo:
        qual = f"{class_name}.{fn.name}" if class_name else fn.name
        finfo = FunctionInfo(qualname=qual, node=fn, class_name=class_name)
        self.functions[qual] = finfo
        return finfo

    def _prescan_bindings(self, fn: ast.AST, class_name: Optional[str]) -> None:
        """Field kinds / lock fields / jit wrappers from every assignment,
        BEFORE any body walk: a ``with self._lock:`` in a method defined
        above ``__init__`` (or a lock assigned late) must still resolve."""
        qual = f"{class_name}.{fn.name}" if class_name else fn.name
        finfo = self.functions[qual]
        cls = self.classes.get(class_name) if class_name else None
        in_init = fn.name == "__init__"
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                self._record_binding(node, finfo, cls, in_init)

    # -- the per-function walk (locks, accesses, calls) ----------------------
    def _walk_function(self, fn: ast.AST, class_name: Optional[str]) -> None:
        qual = f"{class_name}.{fn.name}" if class_name else fn.name
        finfo = self.functions[qual]
        cls = self.classes.get(class_name) if class_name else None
        in_init = fn.name == "__init__"
        self._walk_block(
            fn.body, finfo, cls, in_init,
            locks=frozenset(), in_loop=False,
        )

    def _walk_block(
        self,
        stmts: Sequence[ast.stmt],
        finfo: FunctionInfo,
        cls: Optional[ClassInfo],
        in_init: bool,
        locks: FrozenSet[str],
        in_loop: bool,
    ) -> None:
        for stmt in stmts:
            self._walk_stmt(stmt, finfo, cls, in_init, locks, in_loop)

    def _lock_key(self, expr: ast.AST, cls: Optional[ClassInfo]) -> Optional[str]:
        key = receiver_key(expr)
        if key is None:
            return None
        if key.startswith("self.") and cls is not None:
            attr = key[5:]
            if attr in cls.lock_fields:
                return f"{cls.name}.{attr}"
            return None
        if key in self.module_locks:
            return f"<module>.{key}"
        return None

    def _walk_stmt(
        self,
        stmt: ast.stmt,
        finfo: FunctionInfo,
        cls: Optional[ClassInfo],
        in_init: bool,
        locks: FrozenSet[str],
        in_loop: bool,
    ) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested function: its body runs later, with no lock held at def
            # time (closures re-entered from callbacks); record under a
            # <locals> qualname so thread targets can still resolve to it
            nested_qual = f"{finfo.qualname}.<locals>.{stmt.name}"
            nested = FunctionInfo(
                qualname=nested_qual, node=stmt, class_name=finfo.class_name
            )
            self.functions[nested_qual] = nested
            self._walk_block(stmt.body, nested, cls, False, frozenset(), False)
            return
        if isinstance(stmt, ast.ClassDef):
            return  # method-local classes: out of scope
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            new_locks = set(locks)
            for item in stmt.items:
                lk = self._lock_key(item.context_expr, cls)
                if lk is not None:
                    finfo.acquires.append((lk, locks, item.context_expr))
                    new_locks.add(lk)
                else:
                    self._scan_exprs([item.context_expr], finfo, cls, in_init, locks, in_loop)
                if item.optional_vars is not None:
                    self._scan_exprs([item.optional_vars], finfo, cls, in_init, locks, in_loop)
            self._walk_block(stmt.body, finfo, cls, in_init, frozenset(new_locks), in_loop)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._scan_exprs([stmt.iter], finfo, cls, in_init, locks, in_loop, iterating=True)
            self._scan_exprs([stmt.target], finfo, cls, in_init, locks, in_loop)
            self._walk_block(stmt.body, finfo, cls, in_init, locks, True)
            self._walk_block(stmt.orelse, finfo, cls, in_init, locks, in_loop)
            return
        if isinstance(stmt, ast.While):
            self._scan_exprs([stmt.test], finfo, cls, in_init, locks, in_loop)
            self._walk_block(stmt.body, finfo, cls, in_init, locks, True)
            self._walk_block(stmt.orelse, finfo, cls, in_init, locks, in_loop)
            return
        if isinstance(stmt, ast.If):
            self._scan_exprs([stmt.test], finfo, cls, in_init, locks, in_loop)
            self._walk_block(stmt.body, finfo, cls, in_init, locks, in_loop)
            self._walk_block(stmt.orelse, finfo, cls, in_init, locks, in_loop)
            return
        if isinstance(stmt, ast.Try):
            self._walk_block(stmt.body, finfo, cls, in_init, locks, in_loop)
            for h in stmt.handlers:
                self._walk_block(h.body, finfo, cls, in_init, locks, in_loop)
            self._walk_block(stmt.orelse, finfo, cls, in_init, locks, in_loop)
            self._walk_block(stmt.finalbody, finfo, cls, in_init, locks, in_loop)
            return
        # leaf statements: bindings first (field kinds / jit wrappers), then
        # a generic expression scan for accesses and calls
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._record_binding(stmt, finfo, cls, in_init)
        self._scan_exprs(
            list(ast.iter_child_nodes(stmt)), finfo, cls, in_init, locks, in_loop,
            stmt=stmt,
        )

    def _record_binding(
        self,
        stmt: ast.stmt,
        finfo: FunctionInfo,
        cls: Optional[ClassInfo],
        in_init: bool,
    ) -> None:
        value = getattr(stmt, "value", None)
        if value is None:
            return
        targets = (
            stmt.targets if isinstance(stmt, ast.Assign)
            else [stmt.target]
        )
        keys = [receiver_key(t) for t in targets]
        # jit wrapper binding: self.<attr> = jax.jit(fn, donate_argnums=...)
        # — only SELF-attribute bindings are recorded class-wide; a bare-name
        # local (`step = jax.jit(...)` inside one function) stays scoped to
        # that function's own DN scan, so an unrelated local named `step`
        # elsewhere in the module can never inherit its donation semantics
        wrapper = self._match_jit(value)
        if wrapper is not None:
            target_fn, donated = wrapper
            for key in keys:
                if key is not None and key.startswith("self."):
                    self.jit_wrappers[(finfo.class_name, key)] = JitWrapper(
                        key=key, target=target_fn, donated=donated,
                        lineno=stmt.lineno,
                    )
        # field-kind classification for self.<attr> = ctor(...)
        if cls is None or not isinstance(value, ast.Call):
            return
        ctor = self._ctor_name(value.func)
        for key in keys:
            if key is None or not key.startswith("self."):
                continue
            attr = key[5:]
            if ctor in ("Lock", "RLock"):
                cls.lock_fields.add(attr)
                cls.field_kinds[attr] = "lock"
            elif ctor in _SYNC_CTORS:
                cls.field_kinds[attr] = "sync"
            elif ctor in _CONTAINER_CTORS:
                cls.field_kinds[attr] = "container"
            elif ctor in _NUMPY_CTORS and self._is_numpy_call(value):
                cls.field_kinds[attr] = "numpy"
            elif ctor and ctor[0].isupper():
                cls.instance_fields[attr] = ctor
                cls.field_kinds.setdefault(attr, "instance")

    def _is_numpy_call(self, call: ast.Call) -> bool:
        if not isinstance(call.func, ast.Attribute):
            return False
        root = _dotted(call.func.value)
        return root in ("np", "numpy")

    def _match_jit(self, value: ast.AST) -> Optional[Tuple[Optional[str], FrozenSet[int]]]:
        """``jax.jit(fn, ...)`` → (resolved fn or None, donated positions)."""
        if not isinstance(value, ast.Call):
            return None
        chain = _dotted(value.func)
        if chain not in ("jax.jit", "jit"):
            return None
        target = None
        if value.args:
            tkey = receiver_key(value.args[0])
            if tkey is not None:
                target = tkey  # "impl" or "self._impl" — resolved lazily
        donated: Set[int] = set()
        for kw in value.keywords:
            if kw.arg == "donate_argnums":
                # collect int constants from tuples ANYWHERE in the value —
                # handles the engine's `(1,) if donate else ()` conditional
                for node in ast.walk(kw.value):
                    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                            and not isinstance(node.value, bool):
                        donated.add(node.value)
        return target, frozenset(donated)

    def _scan_exprs(
        self,
        nodes: Sequence[ast.AST],
        finfo: FunctionInfo,
        cls: Optional[ClassInfo],
        in_init: bool,
        locks: FrozenSet[str],
        in_loop: bool,
        iterating: bool = False,
        stmt: Optional[ast.stmt] = None,
    ) -> None:
        """Record field accesses and resolved call sites in expression trees.
        ``iterating`` marks the top node as a for-loop iterable."""
        comp_types = (ast.GeneratorExp, ast.ListComp, ast.SetComp, ast.DictComp)
        for top in nodes:
            if top is None or isinstance(top, ast.stmt):
                continue
            # everything nested inside a comprehension runs once per element
            comp_members: Set[int] = set()
            for n in ast.walk(top):
                if isinstance(n, comp_types):
                    comp_members.update(id(m) for m in ast.walk(n) if m is not n)
            for node in ast.walk(top):
                inner_loop = in_loop or id(node) in comp_members
                if isinstance(node, ast.Call):
                    self._record_call(node, finfo, cls, locks, inner_loop)
                if cls is None:
                    continue
                if (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                ):
                    self._record_access(node, finfo, cls, in_init, locks, iterating and node is top)

    def _record_access(
        self,
        node: ast.Attribute,
        finfo: FunctionInfo,
        cls: ClassInfo,
        in_init: bool,
        locks: FrozenSet[str],
        iterating: bool,
    ) -> None:
        attr = node.attr
        if attr in cls.lock_fields:
            return
        parent_kind = "read"
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            parent_kind = "write"
        cls.accesses.append(
            FieldAccess(
                field=attr, func=finfo.qualname,
                kind="iterate" if iterating and parent_kind == "read" else parent_kind,
                locks_held=locks, node=node, lineno=node.lineno,
                col=node.col_offset, in_init=in_init,
            )
        )

    def _record_call(
        self,
        node: ast.Call,
        finfo: FunctionInfo,
        cls: Optional[ClassInfo],
        locks: FrozenSet[str],
        in_loop: bool,
    ) -> None:
        ident = self._protocol_identity(node)
        if ident is not None:
            kind, op, chain = ident
            self.protocol_calls.append(
                ProtocolCall(
                    kind=kind, op=op, chain=chain, node=node,
                    lineno=node.lineno, col=node.col_offset,
                    func=finfo.qualname, locks_held=locks, in_loop=in_loop,
                )
            )
        target = self.resolve_call(node, cls)
        if target is None:
            return
        finfo.calls.append(
            CallSite(
                target=target, node=node, lineno=node.lineno,
                in_loop=in_loop, locks_held=locks,
            )
        )

    def _protocol_identity(self, call: ast.Call) -> Optional[Tuple[str, str, str]]:
        """``(kind, op, chain)`` when the call is a distributed-protocol
        operation, else None. Identity is import/receiver-shaped — never a
        bare-name match — so the record under-approximates like the rest of
        the graph: a local helper named ``barrier`` or a dict ``.get`` never
        registers."""
        chain = _dotted(call.func)
        if chain is None:
            return None
        parts = chain.split(".")
        op = parts[-1]
        # coordination-store ops: the long KV names are unambiguous, the
        # short ones need a store/client/kv-shaped receiver segment
        kind = _STORE_METHOD_KINDS.get(op)
        if kind is not None:
            if op in _STORE_UNAMBIGUOUS:
                return kind, op, chain
            if len(parts) >= 2 and _store_receiver(parts[-2]):
                return kind, op, chain
        # shard_map-level primitives under a lax chain
        if op in _LAX_COLLECTIVES and (
            chain.startswith("jax.lax.") or chain.startswith("lax.")
        ):
            return "collective", op, chain
        # python-level entry points, resolved through a distributed import
        if op in _COLLECTIVE_OPS:
            if len(parts) == 1:
                fi = self.from_imports.get(op)
                if fi is not None and (
                    "distributed" in fi[0] or "collective" in fi[0]
                ):
                    return "collective", op, chain
                if op in self.functions and self.dotted_name is not None \
                        and "distributed" in self.dotted_name:
                    # intra-module call inside the collectives package itself
                    return "collective", op, chain
                return None
            if "distributed" in chain:  # paddle.distributed.all_reduce
                return "collective", op, chain
            root = parts[0]
            mod = self.module_aliases.get(root)
            if mod is not None and "distributed" in mod:
                return "collective", op, chain
            fi = self.from_imports.get(root)
            if fi is not None and "distributed" in f"{fi[0]}.{fi[1]}":
                return "collective", op, chain
        return None

    # -- call resolution ------------------------------------------------------
    def node_key(self, qualname: str) -> str:
        return f"{self.path}::{qualname}"

    def resolve_call(self, call: ast.Call, cls: Optional[ClassInfo]) -> Optional[str]:
        """Best-effort local/package target key for one call, or None."""
        fn = call.func
        if isinstance(fn, ast.Name):
            if fn.id in self.functions:
                return self.node_key(fn.id)
            if fn.id in self.from_imports:
                mod, orig = self.from_imports[fn.id]
                return f"@{mod}::{orig}"  # cross-module, resolved by the index
            return None
        if not isinstance(fn, ast.Attribute):
            return None
        recv, meth = fn.value, fn.attr
        # self.m() -> same-class method
        if isinstance(recv, ast.Name) and recv.id == "self" and cls is not None:
            if f"{cls.name}.{meth}" in self.functions:
                return self.node_key(f"{cls.name}.{meth}")
            return None
        # self.attr.m() -> bound instance field's class
        if (
            isinstance(recv, ast.Attribute)
            and isinstance(recv.value, ast.Name)
            and recv.value.id == "self"
            and cls is not None
        ):
            bound = cls.instance_fields.get(recv.attr)
            if bound:
                return self._resolve_class_method(bound, meth)
            return None
        if isinstance(recv, ast.Name):
            # NAME.m() where NAME is a module-level instance
            bound = self.instances.get(recv.id)
            if bound:
                return self._resolve_class_method(bound, meth)
            # imported singleton: from paddle_tpu.flags import GLOBAL_FLAGS
            if recv.id in self.from_imports:
                mod, orig = self.from_imports[recv.id]
                return f"@{mod}::{orig}.{meth}"  # instance OR submodule fn
            # module alias: import paddle_tpu.x.y as alias; alias.f()
            if recv.id in self.module_aliases:
                return f"@{self.module_aliases[recv.id]}::{meth}"
            return None
        return None

    def _resolve_class_method(self, class_name: str, meth: str) -> Optional[str]:
        if f"{class_name}.{meth}" in self.functions:
            return self.node_key(f"{class_name}.{meth}")
        if class_name in self.from_imports:
            mod, orig = self.from_imports[class_name]
            return f"@{mod}::{orig}.{meth}"
        return None

    # -- thread entries --------------------------------------------------------
    def _collect_thread_entries(self) -> None:
        # handler classes: every method runs on a server thread
        for cname, cinfo in self.classes.items():
            if cinfo.base_names & _HTTP_HANDLER_BASES or any(
                self._base_is_handler(b) for b in cinfo.base_names
            ):
                for qual in self.functions:
                    if qual.startswith(f"{cname}."):
                        self.thread_entries.append((qual, "handler", cinfo.node.lineno))
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _dotted(node.func)
            name = chain.rsplit(".", 1)[-1] if chain else None
            if name == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        qual = self._callable_qual(kw.value)
                        if qual:
                            self.thread_entries.append((qual, "thread", node.lineno))
            elif name == "on_change":
                # GLOBAL_FLAGS.on_change("flag", listener): listeners fire on
                # whatever thread calls set_flags — a cross-thread entry
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    qual = self._callable_qual(arg)
                    if qual:
                        self.thread_entries.append((qual, "listener", node.lineno))

    def _base_is_handler(self, base: str) -> bool:
        # one level of local subclassing: class A(BaseHTTPRequestHandler);
        # class B(A) — B's methods are handler entries too
        parent = self.classes.get(base)
        return parent is not None and bool(parent.base_names & _HTTP_HANDLER_BASES)

    def _callable_qual(self, expr: ast.AST) -> Optional[str]:
        key = receiver_key(expr)
        if key is None:
            return None
        if key.startswith("self."):
            attr = key[5:]
            for qual in self.functions:
                if qual.endswith(f".{attr}") or qual == attr:
                    return qual
            return None
        if key in self.functions:
            return key
        for qual in self.functions:
            if qual.endswith(f".<locals>.{key}"):
                return qual
        return None


class PackageIndex:
    """Memoized per-module graphs plus the package-level closures the CC/DN
    checkers share. ``build_count`` counts actual graph constructions — the
    CI perf gate asserts it equals the number of analyzed modules (i.e. the
    graphs are built once, not re-resolved per checker)."""

    def __init__(self) -> None:
        self._modules: Dict[str, ModuleGraph] = {}
        self.build_count = 0
        self._thread_reachable: Optional[Set[str]] = None
        self._loop_reachable: Optional[Set[str]] = None
        self._edges: Optional[Dict[str, List[CallSite]]] = None
        self._lock_pairs: Optional[Dict[Tuple[str, str], List[Tuple[str, int, str]]]] = None
        # memoized per-module Pallas geometry reports (analysis.kernel_geometry)
        self._geometry: Dict[str, object] = {}
        self._fallback_labels: Optional[Set[str]] = None
        self._mesh_axes: Optional[Set[str]] = None
        self._thread_acquirers: Optional[Dict[str, List[Tuple[str, str]]]] = None
        # accumulated wall time per phase ("index-build" / "dataflow" /
        # "geometry") — the CLI --timings attribution source
        self.phase_seconds: Dict[str, float] = {}

    def _timed(self, phase: str, t0: float) -> None:
        self.phase_seconds[phase] = (
            self.phase_seconds.get(phase, 0.0) + (time.perf_counter() - t0)
        )

    # -- module memoization ---------------------------------------------------
    def add_module(self, path: str, tree: ast.Module) -> ModuleGraph:
        if path not in self._modules:
            t0 = time.perf_counter()
            self._modules[path] = ModuleGraph(path, tree, _dotted_name_of(path))
            self._timed("index-build", t0)
            self.build_count += 1
            # package-level closures are stale once the module set changes
            self._thread_reachable = None
            self._loop_reachable = None
            self._edges = None
            self._lock_pairs = None
            self._geometry.clear()
            self._fallback_labels = None
            self._mesh_axes = None
            self._thread_acquirers = None
        return self._modules[path]

    def module(self, path: str) -> Optional[ModuleGraph]:
        return self._modules.get(path)

    def modules(self) -> Iterable[ModuleGraph]:
        return self._modules.values()

    # -- Pallas kernel geometry (analysis.kernel_geometry) --------------------
    def kernel_geometry(self, path: str, tree: Optional[ast.Module] = None):
        """The module's abstract Pallas-geometry report, evaluated once per
        (module set, path) — the PG checkers all read this memo, keeping the
        single-pass and wall-time CI gates honest."""
        if path not in self._geometry:
            if tree is None:
                g = self._modules.get(path)
                if g is None:
                    raise KeyError(f"module not indexed: {path}")
                tree = g.tree
            from paddle_tpu.analysis.kernel_geometry import evaluate_module

            t0 = time.perf_counter()
            self._geometry[path] = evaluate_module(path, tree, self)
            self._timed("geometry", t0)
        return self._geometry[path]

    def fallback_covered(self) -> Set[str]:
        """Simple names of every function called inside any indexed function
        whose body calls ``warn_fallback`` with a literal kernel label — the
        PG905 coverage set: a kernel entry called from such a function
        degrades to XLA with a counted, scrapeable fallback."""
        if self._fallback_labels is None:
            covered: Set[str] = set()
            for g in self._modules.values():
                covered |= _warn_fallback_callees(g.tree)
            covered |= self._package_fallback_callees()
            self._fallback_labels = covered
        return self._fallback_labels

    def _package_fallback_callees(self) -> Set[str]:
        """PG905's coverage universe is the PACKAGE, not the analyzed file
        set: a run scoped to ``kernels/`` (the bench geometry preflight, or
        ``--changed-only`` touching a kernel module) must still see the
        fallback-wrapping dispatch layer living outside it. When every
        on-disk indexed module sits under a ``kernels`` directory, the rest
        of the package is lazily parsed from disk for its warn_fallback
        wrappers — nothing else about unindexed modules is consulted."""
        from pathlib import Path

        pkg_root = None
        for p in self._modules:
            path = Path(p)
            if not path.is_file():
                continue  # fixture/snippet paths keep module-local semantics
            parts = path.resolve().parts
            if "kernels" not in parts:
                return set()  # the index already spans the package
            pkg_root = Path(*parts[: parts.index("kernels")])
        if pkg_root is None:
            return set()
        indexed = {str(Path(p).resolve()) for p in self._modules}
        out: Set[str] = set()
        for f in sorted(pkg_root.rglob("*.py")):
            if str(f.resolve()) in indexed:
                continue
            try:
                tree = ast.parse(f.read_text(encoding="utf-8", errors="replace"))
            except (OSError, SyntaxError):
                continue
            out |= _warn_fallback_callees(tree)
        return out

    # -- distributed-protocol closures (CM family) -----------------------------
    def mesh_axes(self) -> Set[str]:
        """Every mesh axis name defined anywhere in the PACKAGE — the CM1005
        membership universe. Like the PG905 coverage set, the universe is the
        package, not the analyzed file set: a ``--changed-only`` run touching
        one module with a ``PartitionSpec("tp")`` must still see the mesh
        that defines ``tp`` elsewhere, so unindexed on-disk package files are
        lazily parsed for their axis definitions only (memoized; a
        whole-package run parses nothing extra). Empty when no indexed
        module defines a mesh — CM1005 then stays silent rather than
        guessing."""
        if self._mesh_axes is None:
            t0 = time.perf_counter()
            axes: Set[str] = set()
            for g in self._modules.values():
                axes |= g.mesh_axes
            axes |= self._package_mesh_axes()
            self._timed("dataflow", t0)
            self._mesh_axes = axes
        return self._mesh_axes

    def _package_mesh_axes(self) -> Set[str]:
        from pathlib import Path

        pkg_root: Optional[Path] = None
        for p in self._modules:
            path = Path(p)
            if not path.is_file():
                continue  # fixture/snippet paths keep module-local semantics
            parts = path.resolve().parts
            if "paddle_tpu" in parts:
                idx = len(parts) - 1 - tuple(reversed(parts)).index("paddle_tpu")
                pkg_root = Path(*parts[: idx + 1])
                break
        if pkg_root is None:
            return set()
        indexed = {str(Path(p).resolve()) for p in self._modules}
        out: Set[str] = set()
        for f in sorted(pkg_root.rglob("*.py")):
            if str(f.resolve()) in indexed:
                continue
            try:
                tree = ast.parse(f.read_text(encoding="utf-8", errors="replace"))
            except (OSError, SyntaxError):
                continue
            out |= _mesh_axes_of_tree(tree)
        return out

    def thread_lock_acquirers(self) -> Dict[str, List[Tuple[str, str]]]:
        """lock key -> [(path, qualname)] of functions that acquire it AND
        are thread entries or thread-reachable — the CM1002 deadlock partner
        set (a collective issued under such a lock can park forever behind
        the probe loop / HTTP handler holding it)."""
        if self._thread_acquirers is None:
            t0 = time.perf_counter()
            reach = self.thread_reachable()
            out: Dict[str, List[Tuple[str, str]]] = {}
            for g in self._modules.values():
                for qual, finfo in g.functions.items():
                    if g.node_key(qual) not in reach:
                        continue
                    for lk, _held, _n in finfo.acquires:
                        out.setdefault(lk, []).append((g.path, qual))
            self._timed("dataflow", t0)
            self._thread_acquirers = out
        return self._thread_acquirers

    # -- cross-module resolution ----------------------------------------------
    def _resolve_key(self, key: str) -> List[str]:
        """Resolve an ``@module::name`` cross-module reference against the
        indexed modules; concrete ``path::qual`` keys pass through."""
        if not key.startswith("@"):
            return [key]
        mod, name = key[1:].split("::", 1)
        out: List[str] = []
        for g in self._modules.values():
            if g.dotted_name is None:
                continue
            # "from paddle_tpu.observability import flight_recorder" imports a
            # MODULE; "<mod>.<name>" may itself be the module holding the attr
            if g.dotted_name == mod:
                out.extend(self._expand_in_module(g, name))
            elif g.dotted_name == f"{mod}.{name.split('.', 1)[0]}" and "." in name:
                out.extend(self._expand_in_module(g, name.split(".", 1)[1]))
        return out

    def _expand_in_module(self, g: ModuleGraph, name: str) -> List[str]:
        if name in g.functions:
            return [g.node_key(name)]
        if "." in name:
            head, meth = name.rsplit(".", 1)
            # instance attr call: GLOBAL_FLAGS.get -> FlagRegistry.get
            inst_cls = g.instances.get(head)
            if inst_cls and f"{inst_cls}.{meth}" in g.functions:
                return [g.node_key(f"{inst_cls}.{meth}")]
            if name in g.functions:  # Class.method direct
                return [g.node_key(name)]
            # the head may be a re-exported module alias inside g
            if head in g.module_aliases or head in g.from_imports:
                mod = (
                    g.module_aliases.get(head)
                    or ".".join(g.from_imports[head])
                )
                return self._resolve_key(f"@{mod}::{meth}")
        if name in g.classes:
            # calling a class = running __init__
            if f"{name}.__init__" in g.functions:
                return [g.node_key(f"{name}.__init__")]
        return []

    def _all_edges(self) -> Dict[str, List[CallSite]]:
        if self._edges is None:
            t0 = time.perf_counter()
            edges: Dict[str, List[CallSite]] = {}
            for g in self._modules.values():
                for qual, finfo in g.functions.items():
                    resolved: List[CallSite] = []
                    for cs in finfo.calls:
                        for tgt in self._resolve_key(cs.target):
                            resolved.append(
                                CallSite(
                                    target=tgt, node=cs.node, lineno=cs.lineno,
                                    in_loop=cs.in_loop, locks_held=cs.locks_held,
                                )
                            )
                    edges[g.node_key(qual)] = resolved
            self._edges = edges
            self._timed("dataflow", t0)
        return self._edges

    # -- reachability closures -------------------------------------------------
    def thread_reachable(self) -> Set[str]:
        """Node keys reachable from any thread entry in the package."""
        if self._thread_reachable is None:
            roots = [
                g.node_key(qual)
                for g in self._modules.values()
                for qual, _kind, _ln in g.thread_entries
            ]
            self._thread_reachable = self._bfs(roots)
        return self._thread_reachable

    def loop_reachable(self) -> Set[str]:
        """Node keys reachable from a call site that sits inside a loop —
        i.e. functions whose body may run once per iteration of some hot
        loop, directly or transitively."""
        if self._loop_reachable is None:
            edges = self._all_edges()
            roots: List[str] = []
            for sites in edges.values():
                for cs in sites:
                    if cs.in_loop:
                        roots.append(cs.target)
            self._loop_reachable = self._bfs(roots)
        return self._loop_reachable

    def _bfs(self, roots: Sequence[str]) -> Set[str]:
        edges = self._all_edges()
        seen: Set[str] = set()
        stack = list(roots)
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            for cs in edges.get(key, ()):
                if cs.target not in seen:
                    stack.append(cs.target)
        return seen

    # -- lock-order pairs -------------------------------------------------------
    def lock_order_pairs(self) -> Dict[Tuple[str, str], List[Tuple[str, int, str]]]:
        """(held, acquired) -> [(path, line, via)] across the package:
        lexical nesting plus one interprocedural expansion (a call made with
        L held reaches a function whose acquire-closure contains M).
        Memoized like the other package-level closures — the checker asks
        once per analyzed FILE, and recomputing the acquire-closure per file
        would be O(files x package) on the tier-1 gate path."""
        if self._lock_pairs is not None:
            return self._lock_pairs
        edges = self._all_edges()
        # direct acquire sets per function, then closure over calls
        direct: Dict[str, Set[str]] = {}
        for g in self._modules.values():
            for qual, finfo in g.functions.items():
                direct[g.node_key(qual)] = {lk for lk, _held, _n in finfo.acquires}
        closure: Dict[str, Set[str]] = {}

        def acq_closure(key: str, trail: Set[str]) -> Set[str]:
            if key in closure:
                return closure[key]
            if key in trail:
                return direct.get(key, set())
            trail.add(key)
            out = set(direct.get(key, set()))
            for cs in edges.get(key, ()):
                out |= acq_closure(cs.target, trail)
            closure[key] = out
            return out

        pairs: Dict[Tuple[str, str], List[Tuple[str, int, str]]] = {}
        for g in self._modules.values():
            for qual, finfo in g.functions.items():
                key = g.node_key(qual)
                for lk, held, node in finfo.acquires:
                    for h in held:
                        if h != lk:
                            pairs.setdefault((h, lk), []).append(
                                (g.path, node.lineno, qual)
                            )
                for cs in edges.get(key, ()):
                    if not cs.locks_held:
                        continue
                    for m in acq_closure(cs.target, set()):
                        for h in cs.locks_held:
                            if h != m:
                                pairs.setdefault((h, m), []).append(
                                    (g.path, cs.lineno, qual)
                                )
        self._lock_pairs = pairs
        return pairs


def _dotted_name_of(path: str) -> Optional[str]:
    """Dotted module name from a file path, anchored at the package root
    (the last path component named ``paddle_tpu``); None for snippets."""
    parts = [p for p in path.replace("\\", "/").split("/") if p]
    if not parts or not parts[-1].endswith(".py"):
        return None
    anchors = [i for i, p in enumerate(parts) if p == "paddle_tpu"]
    if not anchors:
        return None
    rel = parts[anchors[-1]:]
    rel[-1] = rel[-1][:-3]
    if rel[-1] == "__init__":
        rel = rel[:-1]
    return ".".join(rel) if rel else None
