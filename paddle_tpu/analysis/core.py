"""Visitor core of the static-analysis framework.

Framework-invariant AST lint: everything here operates on ``ast`` trees and
raw source lines only — no imports of the modules under analysis, so the
analyzer can run on a checkout where ``jax`` is broken or absent, and the
same pass works on any Python codebase that adopts the checker conventions.

Pieces:

- :class:`Violation` — one finding, with a stable code (``TS101``), location,
  and suppression state;
- :class:`Checker` — pluggable checker base; concrete checkers live in
  :mod:`paddle_tpu.analysis.checkers` and register via ``all_checkers()``;
- inline suppressions — ``# analysis: disable=TS101 <reason>`` on the
  violating line (or an immediately preceding comment-only line) suppresses
  that code there; a suppression **must** carry a reason string, otherwise
  the violation stays live and is additionally marked as reason-less;
- :func:`analyze_paths` / :func:`analyze_source` — drivers that parse files,
  build the cross-file :class:`ProjectContext` (the defined-flag universe for
  the FD checkers), run every checker, and resolve suppressions.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Violation",
    "Checker",
    "FileContext",
    "ProjectContext",
    "analyze_paths",
    "analyze_source",
    "iter_python_files",
    "parent_map",
]

# modules whose code runs per-op / per-step / per-token: flag reads inside
# loops here must go through an on_change-cached local (FD302)
HOT_PATH_DIR_NAMES = ("kernels", "inference", "core", "observability", "jit")

_SUPPRESS_RE = re.compile(
    r"#\s*analysis:\s*disable=([A-Z]{2,3}\d{3}(?:\s*,\s*[A-Z]{2,3}\d{3})*)"
    r"(?:[ \t]+(\S.*?))?\s*$"
)


@dataclass
class Violation:
    """One finding. ``suppressed`` is resolved by the driver, not checkers."""

    path: str
    line: int
    col: int
    code: str
    message: str
    suppressed: bool = False
    reason: Optional[str] = None  # the suppression's stated reason, if any

    def format(self) -> str:
        tag = f" [suppressed: {self.reason}]" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}{tag}"


@dataclass
class ProjectContext:
    """Cross-file facts shared by all checkers in one run."""

    # every flag name registered via flags.py / define_flag across the run's
    # file set (plus the always-scanned canonical flags.py)
    known_flags: Set[str] = field(default_factory=set)
    # memoized interprocedural dataflow (call graph / thread entries / lock
    # regions — see analysis.dataflow); built ONCE per run by the drivers,
    # shared by every checker that consumes it. Optional so ProjectContext
    # construction stays cheap for checkers that never touch it.
    index: Optional["PackageIndex"] = None

    def dataflow(self) -> "PackageIndex":
        if self.index is None:
            from paddle_tpu.analysis.dataflow import PackageIndex

            self.index = PackageIndex()
        return self.index


@dataclass
class FileContext:
    path: str
    lines: List[str]
    tree: ast.Module
    project: ProjectContext
    hot_path: bool
    # child -> parent links for the whole tree (ancestor queries: loop
    # enclosure for FD302, class resolution for jax.jit(self._method))
    parents: Dict[ast.AST, ast.AST] = field(default_factory=dict)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)


class Checker:
    """Base class. Subclasses set ``name`` and ``codes`` (code -> one-line
    description) and implement :meth:`run` returning violations with
    ``suppressed`` left False — the driver resolves suppressions."""

    name: str = "base"
    codes: Dict[str, str] = {}

    def run(self, ctx: FileContext) -> List[Violation]:
        raise NotImplementedError


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _suppressions_for_line(lines: Sequence[str], lineno: int) -> List[Tuple[Set[str], str]]:
    """All (codes, reason) directives governing ``lineno`` (1-based): an
    ``# analysis: disable=`` comment on the line itself and/or on an
    immediately preceding comment-only line. Reasons may be empty — the
    caller decides what that means."""
    out: List[Tuple[Set[str], str]] = []
    for idx in (lineno - 1, lineno - 2):
        if 0 <= idx < len(lines):
            if idx == lineno - 2 and not lines[idx].lstrip().startswith("#"):
                continue
            m = _SUPPRESS_RE.search(lines[idx])
            if m:
                codes = {c.strip() for c in m.group(1).split(",")}
                out.append((codes, (m.group(2) or "").strip()))
    return out


def _resolve_suppressions(violations: List[Violation], lines: Sequence[str]) -> None:
    for v in violations:
        matches = [
            reason
            for codes, reason in _suppressions_for_line(lines, v.line)
            if v.code in codes
        ]
        if not matches:
            continue
        reasons = [r for r in matches if r]
        if reasons:
            v.suppressed = True
            v.reason = reasons[0]
        else:
            # a reason-less suppression does NOT suppress: the acceptance
            # contract is "every suppression carries a reason"
            v.message += " (suppression ignored: missing reason string)"


def _is_hot_path(path: Path) -> bool:
    return any(part in HOT_PATH_DIR_NAMES for part in path.parts)


# -- defined-flag collection (FD checker universe) ---------------------------

def _collect_flags_from_tree(tree: ast.Module) -> Set[str]:
    """Flag names defined in one module: ``define_flag("name", ...)``,
    ``GLOBAL_FLAGS.define("name", ...)``, and calls through a local alias of
    ``GLOBAL_FLAGS.define`` (the ``d = GLOBAL_FLAGS.define`` idiom in
    flags.py)."""
    flags: Set[str] = set()
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Attribute):
            val = node.value
            if (
                val.attr == "define"
                and isinstance(val.value, ast.Name)
                and val.value.id == "GLOBAL_FLAGS"
            ):
                aliases.update(t.id for t in node.targets if isinstance(t, ast.Name))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        fn = node.func
        named = isinstance(fn, ast.Name) and (fn.id == "define_flag" or fn.id in aliases)
        attr = (
            isinstance(fn, ast.Attribute)
            and fn.attr == "define"
            and isinstance(fn.value, ast.Name)
            and fn.value.id == "GLOBAL_FLAGS"
        )
        if (named or attr) and isinstance(node.args[0], ast.Constant) and isinstance(
            node.args[0].value, str
        ):
            flags.add(node.args[0].value)
    return flags


def _canonical_flags_py() -> Optional[Path]:
    p = Path(__file__).resolve().parents[1] / "flags.py"
    return p if p.is_file() else None


def build_project_context(
    trees: Iterable[ast.Module], extra_flags: Iterable[str] = ()
) -> ProjectContext:
    ctx = ProjectContext()
    ctx.known_flags.update(extra_flags)
    canonical = _canonical_flags_py()
    if canonical is not None:
        try:
            ctx.known_flags |= _collect_flags_from_tree(
                ast.parse(canonical.read_text(encoding="utf-8"))
            )
        except SyntaxError:
            pass  # a broken flags.py surfaces as its own parse error elsewhere
    for tree in trees:
        ctx.known_flags |= _collect_flags_from_tree(tree)
    return ctx


# -- drivers -----------------------------------------------------------------

def iter_python_files(paths: Sequence[str]) -> List[Path]:
    """Expand files/directories to .py files. A path that does not exist is a
    hard error — a typo'd target must not turn the CI gate into a vacuous
    zero-file pass."""
    out: List[Path] = []
    for p in paths:
        path = Path(p)
        if path.is_dir():
            out.extend(sorted(path.rglob("*.py")))
        elif path.is_file():
            if path.suffix == ".py":
                out.append(path)
        else:
            raise FileNotFoundError(f"no such file or directory: {p}")
    if paths and not out:
        # an existing-but-empty target (or a non-.py file) must not become a
        # vacuous zero-file clean pass either
        raise FileNotFoundError(
            "no Python files found in: " + ", ".join(str(p) for p in paths)
        )
    return out


def _default_checkers() -> List[Checker]:
    from paddle_tpu.analysis.checkers import all_checkers

    return all_checkers()


def analyze_paths(
    paths: Sequence[str],
    checkers: Optional[Sequence[Checker]] = None,
    select: Optional[Sequence[str]] = None,
    timings: Optional[Dict[str, float]] = None,
) -> List[Violation]:
    """Analyze files/directories. ``select`` filters by checker-code prefix
    (e.g. ``["TS", "EH401"]``). Unparseable files yield a single ``GEN001``.
    ``timings``, when given, is filled with per-phase (``phase:parse`` /
    ``phase:index-build`` / ``phase:dataflow`` / ``phase:geometry``) and
    per-checker (``checker:<name>``) wall seconds — the ``--timings`` budget
    attribution; phase time spent lazily inside a checker run (geometry,
    package closures) is counted in both views."""
    import time

    checkers = list(checkers) if checkers is not None else _default_checkers()
    if timings is not None:
        for c in checkers:
            timings.setdefault(f"checker:{c.name}", 0.0)
    t0 = time.perf_counter()
    files = iter_python_files(paths)
    parsed: List[Tuple[Path, str, ast.Module]] = []
    violations: List[Violation] = []
    for f in files:
        src = f.read_text(encoding="utf-8", errors="replace")
        try:
            tree = ast.parse(src)
        except SyntaxError as exc:
            violations.append(
                Violation(str(f), exc.lineno or 1, exc.offset or 0, "GEN001",
                          f"file does not parse: {exc.msg}")
            )
            continue
        parsed.append((f, src, tree))
    project = build_project_context(tree for _, _, tree in parsed)
    if timings is not None:
        timings["phase:parse"] = time.perf_counter() - t0
    # build the interprocedural index ONCE over the whole file set (cross-
    # module call edges need every tree); checkers get the memoized graphs
    index = project.dataflow()
    for f, _, tree in parsed:
        index.add_module(str(f), tree)
    for f, src, tree in parsed:
        violations.extend(
            _run_checkers(tree, src, str(f), project, _is_hot_path(f), checkers,
                          select, timings)
        )
    if timings is not None:
        for phase, secs in index.phase_seconds.items():
            timings[f"phase:{phase}"] = secs
    return violations


def analyze_source(
    source: str,
    path: str = "<snippet>",
    checkers: Optional[Sequence[Checker]] = None,
    select: Optional[Sequence[str]] = None,
    known_flags: Optional[Iterable[str]] = None,
    hot_path: bool = False,
) -> List[Violation]:
    """Analyze one in-memory snippet (the fixture-test entry point). When
    ``known_flags`` is None the canonical flags.py plus the snippet's own
    definitions form the universe."""
    checkers = list(checkers) if checkers is not None else _default_checkers()
    tree = ast.parse(source)
    if known_flags is not None:
        project = ProjectContext(known_flags=set(known_flags))
        project.known_flags |= _collect_flags_from_tree(tree)
    else:
        project = build_project_context([tree])
    project.dataflow().add_module(path, tree)
    return _run_checkers(tree, source, path, project, hot_path, checkers, select)


def _run_checkers(
    tree: ast.Module,
    source: str,
    path: str,
    project: ProjectContext,
    hot_path: bool,
    checkers: Sequence[Checker],
    select: Optional[Sequence[str]],
    timings: Optional[Dict[str, float]] = None,
) -> List[Violation]:
    import time

    lines = source.splitlines()
    ctx = FileContext(
        path=path, lines=lines, tree=tree, project=project,
        hot_path=hot_path, parents=parent_map(tree),
    )
    violations: List[Violation] = []
    for checker in checkers:
        t0 = time.perf_counter()
        found = checker.run(ctx)
        if timings is not None:
            key = f"checker:{checker.name}"
            timings[key] = timings.get(key, 0.0) + (time.perf_counter() - t0)
        if select is not None:
            found = [v for v in found if any(v.code.startswith(s) for s in select)]
        violations.extend(found)
    _resolve_suppressions(violations, lines)
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.code))
    return violations
