"""Static analysis for the framework's own invariants.

AST-based lint (no imports of the analyzed code) with pluggable checkers,
stable codes, inline ``# analysis: disable=XX123 <reason>`` suppressions and
text/JSON reporters. The tier-1 suite runs the whole-package analysis
(``tests/test_static_analysis.py``), so every checker is a merge gate.

Checker families:

- **TS** trace-safety — host side effects inside ``@to_static``/``jax.jit``
  traced functions (:mod:`.checkers.trace_safety`);
- **PK** Pallas purity — impure kernel bodies / BlockSpec index maps
  (:mod:`.checkers.pallas_purity`);
- **PG** Pallas kernel geometry — abstract evaluation of every
  ``pl.pallas_call`` site (:mod:`.kernel_geometry`, memoized in the
  ``PackageIndex``): BlockSpec rank discipline (PG901), in-bounds proofs at
  the grid corners with symbolic axes reported ``unproven`` (PG902),
  per-grid-step VMEM window vs the per-target budget incl. autotune
  candidate configs (PG903), scalar-prefetch discipline (PG904), and the
  kernel↔XLA fallback lockstep contract (PG905)
  (:mod:`.checkers.pallas_geometry`);
- **FD** flag discipline — unresolvable flag strings, un-cached registry
  reads in hot-path loops (:mod:`.checkers.flag_discipline`);
- **EH** exception hygiene — bare/silent/unannotated broad excepts
  (:mod:`.checkers.exception_hygiene`);
- **RB** robustness — ``os._exit`` outside the watchdog/launcher abort
  paths (RB501), un-timed blocking waits (``Queue.get``/``Event.wait``/
  ``Thread.join``/``socket.recv``) in the request-serving and collective
  paths ``serving/``/``distributed/``/``inference/`` (RB502)
  (:mod:`.checkers.robustness`);
- **CC** concurrency (interprocedural, over :mod:`.dataflow`) — unguarded
  access to a lock-dominated field (CC701 guarded-field inference),
  inverted lock-acquisition order (CC702), iteration/snapshot over a
  guarded container outside its lock (CC703), flag-registry read on a
  loop-reachable hot path (CC704) (:mod:`.checkers.concurrency`);
- **DN** donation/buffer lifetime — use-after-donate through
  ``jax.jit(fn, donate_argnums=...)`` bindings (DN801), host numpy buffer
  mutated while a dispatch still aliases it, before any sync point (DN802 —
  the recovery-replay race class), watchdog/metrics record sequenced before
  the donated-state commit (DN803) (:mod:`.checkers.donation`);
- **OB** observability discipline — tracer spans opened outside a ``with``,
  span/flight emission in traced or kernel code, un-synced device timing
  (:mod:`.checkers.observability`);
- **TB** tape backward discipline — autodiff requested over an explicit
  tape-GradNode kernel whose backward jax cannot derive
  (:mod:`.checkers.tape_backward`);
- **CM** distributed protocol (interprocedural, over the ``ProtocolCall``
  record in :mod:`.dataflow`) — rank-divergent collective with no rejoin
  (CM1001), collective/blocking store op under a lock a thread entry also
  acquires (CM1002), coordination-store key hygiene: counter keys need an
  exit-dominating delete, generation families need GC, dynamic keys need a
  namespace (CM1003), collective in except/finally of a raising try
  (CM1004), ``PartitionSpec`` axes outside the package mesh universe and
  donating jits with ``in_shardings`` but no ``out_shardings`` (CM1005)
  (:mod:`.checkers.distributed_protocol`).

CLI: ``python -m paddle_tpu.analysis [--format json|sarif] [--baseline
known.json] [--timings] paddle_tpu/`` — exits non-zero on any NEW
unsuppressed violation.
"""

from paddle_tpu.analysis.checkers import CHECKER_CLASSES, all_checkers, all_codes  # noqa: F401
from paddle_tpu.analysis.core import (  # noqa: F401
    Checker,
    FileContext,
    ProjectContext,
    Violation,
    analyze_paths,
    analyze_source,
)
from paddle_tpu.analysis.reporters import render_json, render_text, summarize  # noqa: F401

__all__ = [
    "Checker",
    "FileContext",
    "ProjectContext",
    "Violation",
    "analyze_paths",
    "analyze_source",
    "all_checkers",
    "all_codes",
    "CHECKER_CLASSES",
    "render_json",
    "render_text",
    "summarize",
]
