"""FD — flag discipline checker.

The flag registry (``paddle_tpu/flags.py``) is stringly typed: a misspelled
name in ``GLOBAL_FLAGS.get("...")`` or a stale ``FLAGS_<name>`` env reference
fails only when that line finally runs (or worse, an env var silently stops
doing anything). FD301 resolves every statically-visible flag string against
the project's defined-flag universe (flags.py definitions plus every
``define_flag(...)`` in the analyzed file set).

FD302 enforces the hot-path idiom established by the observability layer:
``registry.get()`` takes the registry lock, so a flag read inside a loop in a
hot-path module (kernels/inference/core/observability/jit) must instead use a
module-local cached by an ``on_change`` listener (see
``observability/metrics.py``'s ``_ENABLED`` for the pattern).

Codes:

- FD301  flag string does not resolve to a defined flag
- FD302  registry flag read inside a loop in a hot-path module
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from paddle_tpu.analysis.checkers._shared import attr_chain, const_str
from paddle_tpu.analysis.core import Checker, FileContext, Violation

_LOOPS = (ast.For, ast.AsyncFor, ast.While, ast.ListComp, ast.SetComp,
          ast.DictComp, ast.GeneratorExp)
_ENV_GETTERS = {"os.environ.get", "environ.get", "os.getenv"}


def _registry_accessor(call: ast.Call) -> Optional[str]:
    """'get'/'set' when the call is ``GLOBAL_FLAGS.get/set(...)``."""
    fn = call.func
    if (
        isinstance(fn, ast.Attribute)
        and fn.attr in ("get", "set")
        and isinstance(fn.value, ast.Name)
        and fn.value.id == "GLOBAL_FLAGS"
    ):
        return fn.attr
    return None


def _flag_strings(call: ast.Call) -> Iterable[Tuple[str, str]]:
    """Yield (flag_name, how) for every statically-resolvable flag string the
    call references. FLAGS_ prefixes are stripped for env-var forms."""
    chain = attr_chain(call.func) or ""
    # match get_flags/set_flags by trailing name so the public attribute-
    # qualified spellings (paddle.set_flags, paddle_tpu.get_flags) are
    # resolved too, not just bare-name imports
    name = chain.split(".")[-1] if chain else None
    if _registry_accessor(call) and call.args:
        s = const_str(call.args[0])
        if s is not None:
            yield s, f"GLOBAL_FLAGS.{call.func.attr}()"  # type: ignore[union-attr]
    elif name == "get_flags" and call.args:
        arg = call.args[0]
        items = arg.elts if isinstance(arg, (ast.List, ast.Tuple)) else [arg]
        for item in items:
            s = const_str(item)
            if s is not None:
                yield s.removeprefix("FLAGS_"), "get_flags()"
    elif name == "set_flags" and call.args and isinstance(call.args[0], ast.Dict):
        for k in call.args[0].keys:
            s = const_str(k) if k is not None else None
            if s is not None:
                yield s.removeprefix("FLAGS_"), "set_flags()"
    elif chain in _ENV_GETTERS or chain.endswith(".setenv") or chain.endswith(".delenv"):
        if call.args:
            s = const_str(call.args[0])
            if s is not None and s.startswith("FLAGS_"):
                yield s.removeprefix("FLAGS_"), f"env reference '{s}'"


class FlagDisciplineChecker(Checker):
    name = "flag-discipline"
    codes = {
        "FD301": "flag string does not resolve to a defined flag",
        "FD302": "registry flag read inside a loop in a hot-path module",
    }

    def run(self, ctx: FileContext) -> List[Violation]:
        known = ctx.project.known_flags
        out: List[Violation] = []
        for node in ast.walk(ctx.tree):
            # env subscripts: os.environ["FLAGS_x"]
            if (
                isinstance(node, ast.Subscript)
                and attr_chain(node.value) in ("os.environ", "environ")
            ):
                s = const_str(node.slice)
                if s is not None and s.startswith("FLAGS_"):
                    flag = s.removeprefix("FLAGS_")
                    if flag not in known:
                        out.append(self._fd301(ctx, node, flag, f"env subscript '{s}'"))
            if not isinstance(node, ast.Call):
                continue
            for flag, how in _flag_strings(node):
                if flag not in known:
                    out.append(self._fd301(ctx, node, flag, how))
            if ctx.hot_path and self._is_loop_read(node, ctx):
                out.append(
                    Violation(
                        ctx.path, node.lineno, node.col_offset, "FD302",
                        "flag registry read inside a loop in a hot-path module; "
                        "cache the value in a local via an on_change listener "
                        "(see observability/metrics.py)",
                    )
                )
        return out

    def _fd301(self, ctx: FileContext, node: ast.AST, flag: str, how: str) -> Violation:
        return Violation(
            ctx.path, node.lineno, node.col_offset, "FD301",
            f"{how} references undefined flag '{flag}'; define it via "
            "define_flag()/flags.py or fix the name",
        )

    def _is_loop_read(self, node: ast.Call, ctx: FileContext) -> bool:
        is_read = _registry_accessor(node) == "get" or (
            isinstance(node.func, ast.Name) and node.func.id == "get_flags"
        )
        if not is_read:
            return False
        for anc in ctx.ancestors(node):
            if isinstance(anc, _LOOPS):
                return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return False
        return False
