"""Checker registry. Adding a checker = subclass :class:`Checker` in a module
here and list it in :data:`CHECKER_CLASSES`; codes must be unique across the
suite (enforced at import by :func:`all_checkers`)."""

from __future__ import annotations

from typing import Dict, List, Type

from paddle_tpu.analysis.checkers.concurrency import ConcurrencyChecker
from paddle_tpu.analysis.checkers.distributed_protocol import DistributedProtocolChecker
from paddle_tpu.analysis.checkers.donation import DonationChecker
from paddle_tpu.analysis.checkers.exception_hygiene import ExceptionHygieneChecker
from paddle_tpu.analysis.checkers.flag_discipline import FlagDisciplineChecker
from paddle_tpu.analysis.checkers.observability import ObservabilityChecker
from paddle_tpu.analysis.checkers.pallas_geometry import PallasGeometryChecker
from paddle_tpu.analysis.checkers.pallas_purity import PallasPurityChecker
from paddle_tpu.analysis.checkers.robustness import RobustnessChecker
from paddle_tpu.analysis.checkers.tape_backward import TapeBackwardChecker
from paddle_tpu.analysis.checkers.trace_safety import TraceSafetyChecker
from paddle_tpu.analysis.core import Checker

__all__ = ["CHECKER_CLASSES", "all_checkers", "all_codes"]

CHECKER_CLASSES: List[Type[Checker]] = [
    TraceSafetyChecker,
    PallasPurityChecker,
    PallasGeometryChecker,
    FlagDisciplineChecker,
    ExceptionHygieneChecker,
    RobustnessChecker,
    ObservabilityChecker,
    ConcurrencyChecker,
    DistributedProtocolChecker,
    DonationChecker,
    TapeBackwardChecker,
]


def all_checkers() -> List[Checker]:
    checkers = [cls() for cls in CHECKER_CLASSES]
    seen: Dict[str, str] = {}
    for c in checkers:
        for code in c.codes:
            if code in seen:
                raise ValueError(f"checker code {code} defined by both {seen[code]} and {c.name}")
            seen[code] = c.name
    return checkers


def all_codes() -> Dict[str, str]:
    out: Dict[str, str] = {}
    for c in all_checkers():
        out.update(c.codes)
    return out
