"""OB — observability-discipline checker.

**OB601** — the tracing/flight-recorder surface has exactly two safe shapes,
and this check pins both:

1. a live span may only be opened as a context manager. ``tracer.span(...)``
   returns an armed :class:`~paddle_tpu.observability.tracing.Span` whose
   recording happens in ``__exit__`` — assigned to a variable or called
   bare, the span is never closed, never reaches the bounded store, and the
   leak is silent (the trace just has a hole where the phase should be).
   The retroactive forms (``add_span``/``add_event``) take explicit
   timestamps and need no ``with``;
2. span opens AND flight-recorder event emission belong in host code only.
   Inside a ``@jax.jit``/``@to_static`` body they fire per COMPILE, not per
   call (the recorded-at-trace-time bug class TS104 pins for metrics), and
   inside a Pallas kernel/index map they are host I/O from device code.
   Emit at the jit call site, after the dispatch returns — exactly how the
   engine emits its decode-step spans.

Detection is receiver-shaped, so ordinary ``.span``/``.record`` methods on
unrelated objects are never confused for tracer calls:

- a span open is ``<recv>.span(...)`` where the receiver's last component
  names a tracer (contains ``tracer``, any case: ``tracer``, ``_tracer``,
  ``GLOBAL_TRACER``, ``self._tracer``) or is a ``get_tracer()`` call;
- flight-recorder emission is ``record_event(...)`` (any receiver or bare —
  the module-level shorthand) or ``<recv>.record(...)`` where the
  receiver's last component contains ``flight`` or ``recorder``.

**OB602** — metric-name drift. Aggregation/healthz/snapshot consumers read
metric families back from the registry BY NAME (``registry.family("...")``,
``GLOBAL_METRICS.get("...")``); the definitions live at the instrumented
components. A typo'd read name silently reads zeros (``get``) or only fails
at runtime on the consumer path (``family``) — this check closes the drift
statically: every literal name at a registry read site must resolve to a
family registered somewhere in the package (any ``<registry>.counter(
"name", ...)`` / ``.gauge`` / ``.histogram`` call — the package-wide
definition universe is scanned once and cached). Read-site detection is
receiver-shaped so ``dict.get("...")`` never false-positives:

- ``<anything>.family("lit")`` — the method name is the strict-read API,
  distinctive by construction;
- ``<recv>.get("lit")`` where the receiver's last component is
  ``GLOBAL_METRICS``, contains ``registry`` (any case), or is a
  ``get_registry()`` call.

**OB603** — async-dispatch-dishonest timing. jax dispatch is asynchronous:
a jitted call returns as soon as the work is ENQUEUED, so a
``time.perf_counter()`` / ``time.time()`` pair bracketing the call measures
dispatch latency, not device time — the bug that turns a kernel benchmark
into a noise generator (devprof's step decomposition exists precisely
because the gap is routinely 10-100x). The check is statement-sequence
shaped, scanning each suite for the timing-pair idiom:

1. a start timestamp: ``t0 = time.perf_counter()`` (or ``time.time`` /
   ``time.monotonic``) assigned to a plain name;
2. a later statement dispatching a KNOWN-jitted callable — a name assigned
   from ``jax.jit(...)`` / ``to_static(...)`` anywhere in the file
   (``f = jax.jit(g)``, ``self._fn = jax.jit(...)``), a ``@jax.jit``-
   decorated def, or a direct ``jax.jit(f)(x)`` double call;
3. a stop timestamp taken before any sync reached the result. A sync is
   ``block_until_ready`` (method or ``jax.block_until_ready``),
   ``jax.device_get``, ``np.asarray``/``np.array``, or a ``.item()`` /
   ``.tolist()`` / ``.numpy()`` / ``.copy_to_cpu()`` materialization — in
   a statement between dispatch and stop, or fused into the dispatch
   statement itself (``np.asarray(f(x))``).

Receiver-shaped and file-local by construction: calls to names never
assigned from a jit constructor are not dispatches, so ordinary helper
calls between two timestamps can't false-positive.

- OB601  tracer span opened outside ``with``, or tracer/flight-recorder
         emission inside a traced (``@jax.jit``/``to_static``) function or
         Pallas kernel body / index map.
- OB602  metric family name read through the registry does not resolve to
         any registered family (silent-zero drift).
- OB603  ``time.perf_counter()``/``time.time()`` pair times a jitted
         dispatch with no device sync before the stop timestamp
         (async-dispatch-dishonest timing).
"""

from __future__ import annotations

import ast
from functools import lru_cache
from pathlib import Path
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from paddle_tpu.analysis.checkers._shared import attr_chain, body_walk
from paddle_tpu.analysis.checkers.pallas_purity import _KernelCollector
from paddle_tpu.analysis.checkers.trace_safety import (
    _JIT_CHAINS,
    _TracedFunctions,
    _is_jit_decorator,
)
from paddle_tpu.analysis.core import Checker, FileContext, Violation


def _last_component(chain: Optional[str]) -> str:
    return chain.rsplit(".", 1)[-1].lower() if chain else ""


def _is_tracer_span_open(node: ast.Call) -> bool:
    fn = node.func
    if not isinstance(fn, ast.Attribute) or fn.attr != "span":
        return False
    recv = fn.value
    if isinstance(recv, ast.Call):
        # get_tracer().span(...)
        return _last_component(attr_chain(recv.func)) == "get_tracer"
    return "tracer" in _last_component(attr_chain(recv))


_FAMILY_DEF_METHODS = ("counter", "gauge", "histogram")


def _collect_family_definitions(tree: ast.AST) -> Set[str]:
    """Family names registered in one module: any ``<recv>.counter("name",
    ...)`` / ``.gauge`` / ``.histogram`` call with a literal first
    argument. Over-collection only loosens the check (an unrelated
    ``.counter()`` call can add a name, never hide a read)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _FAMILY_DEF_METHODS
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            out.add(node.args[0].value)
    return out


@lru_cache(maxsize=1)
def _package_family_universe() -> FrozenSet[str]:
    """Every family name registered anywhere in the ``paddle_tpu`` package
    (the canonical universe, like the FD checker's always-scanned
    flags.py — definitions are spread across engine/serving/kv_tier/...).
    Parsed once per process and cached."""
    root = Path(__file__).resolve().parents[2]
    names: Set[str] = set()
    for path in sorted(root.rglob("*.py")):
        try:
            names |= _collect_family_definitions(
                ast.parse(path.read_text(encoding="utf-8", errors="replace"))
            )
        except (OSError, SyntaxError):
            continue  # a broken module surfaces as its own GEN001 elsewhere
    return frozenset(names)


def _registry_read_name(node: ast.Call) -> Optional[str]:
    """The literal family name if ``node`` is a registry read-by-name
    (``.family("lit")`` anywhere; ``.get("lit")`` on a registry-shaped
    receiver), else None."""
    fn = node.func
    if not isinstance(fn, ast.Attribute):
        return None
    if not (
        node.args
        and isinstance(node.args[0], ast.Constant)
        and isinstance(node.args[0].value, str)
    ):
        return None
    if fn.attr == "family":
        return node.args[0].value
    if fn.attr != "get":
        return None
    recv = fn.value
    if isinstance(recv, ast.Call):
        return (
            node.args[0].value
            if _last_component(attr_chain(recv.func)) == "get_registry"
            else None
        )
    last = _last_component(attr_chain(recv))
    if last == "global_metrics" or "registry" in last:
        return node.args[0].value
    return None


def _is_flight_emit(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id == "record_event":
        return True
    if isinstance(fn, ast.Attribute):
        if fn.attr == "record_event":
            return True
        if fn.attr == "record":
            last = _last_component(attr_chain(fn.value))
            return "flight" in last or "recorder" in last
    return False


_OB603_TIME_CHAINS = {
    "time.perf_counter", "time.time", "time.monotonic",
    "perf_counter", "monotonic",
}
_OB603_SYNC_CHAINS = {
    "jax.block_until_ready", "block_until_ready", "jax.device_get",
    "device_get", "jax.effects_barrier",
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "onp.asarray", "onp.array",
}
_OB603_SYNC_ATTRS = {
    "block_until_ready", "item", "tolist", "numpy", "copy_to_cpu",
}


def _collect_jitted_callables(tree: ast.AST) -> Set[str]:
    """Names the file binds to jit-constructed callables: assignment targets
    of ``jax.jit(...)``/``to_static(...)`` calls (plain names AND attribute
    targets like ``self._step_fn``) plus ``@jax.jit``-decorated defs."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if attr_chain(node.value.func) in _JIT_CHAINS:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        names.add(tgt.id)
                    elif isinstance(tgt, ast.Attribute):
                        names.add(tgt.attr)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_decorator(d) for d in node.decorator_list):
                names.add(node.name)
    return names


def _ob603_timestamp_assign(stmt: ast.stmt) -> bool:
    """``t = time.perf_counter()`` (a plain-name target — subscript/attr
    targets are mark-dict bookkeeping, not the timing-pair idiom)."""
    return (
        isinstance(stmt, ast.Assign)
        and len(stmt.targets) == 1
        and isinstance(stmt.targets[0], ast.Name)
        and isinstance(stmt.value, ast.Call)
        and attr_chain(stmt.value.func) in _OB603_TIME_CHAINS
    )


def _ob603_dispatch(stmt: ast.stmt, jitted: Set[str]) -> Optional[ast.Call]:
    """First call in ``stmt`` that dispatches a known-jitted callable (or a
    direct ``jax.jit(f)(x)`` double call)."""
    for node in ast.walk(stmt):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Call) and attr_chain(fn.func) in _JIT_CHAINS:
            return node
        chain = attr_chain(fn)
        if chain and chain.rsplit(".", 1)[-1] in jitted:
            return node
    return None


def _ob603_syncs(stmt: ast.stmt) -> bool:
    for node in ast.walk(stmt):
        if isinstance(node, ast.Call):
            if attr_chain(node.func) in _OB603_SYNC_CHAINS:
                return True
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _OB603_SYNC_ATTRS
            ):
                return True
    return False


def _statement_suites(tree: ast.AST):
    """Every statement list (module/def bodies, if/for/while/with/try
    suites) — the unit OB603's sequence scan runs over."""
    for node in ast.walk(tree):
        for field in ("body", "orelse", "finalbody"):
            suite = getattr(node, field, None)
            if isinstance(suite, list) and suite and isinstance(suite[0], ast.stmt):
                yield suite


class ObservabilityChecker(Checker):
    name = "observability-discipline"
    codes = {
        "OB601": "tracer span opened outside a with statement (silent leak), "
                 "or tracer/flight-recorder emission inside a traced "
                 "function or Pallas kernel (fires per compile, not per "
                 "call)",
        "OB602": "metric family name read through the registry does not "
                 "resolve to any registered family (a typo'd name silently "
                 "reads zeros)",
        "OB603": "perf_counter/time pair times a jitted dispatch with no "
                 "block_until_ready/sync before the stop timestamp "
                 "(async dispatch returns at enqueue — this measures "
                 "dispatch latency, not device time)",
    }

    def run(self, ctx: FileContext) -> List[Violation]:
        out = self._run_ob601(ctx)
        out.extend(self._run_ob602(ctx))
        out.extend(self._run_ob603(ctx))
        return out

    def _run_ob603(self, ctx: FileContext) -> List[Violation]:
        jitted = _collect_jitted_callables(ctx.tree)
        out: List[Violation] = []
        for suite in _statement_suites(ctx.tree):
            started = False
            pending: Optional[ast.Call] = None  # dispatch awaiting a sync
            for stmt in suite:
                if _ob603_timestamp_assign(stmt):
                    if started and pending is not None:
                        out.append(
                            Violation(
                                ctx.path, stmt.lineno, stmt.col_offset,
                                "OB603",
                                "stop timestamp taken with no device sync "
                                "after the jitted dispatch on line "
                                f"{pending.lineno}: the call returned at "
                                "enqueue, so this pair measures dispatch "
                                "latency, not device time — "
                                "block_until_ready (or np.asarray / "
                                ".item()) the result first",
                            )
                        )
                        pending = None
                    started = True
                    continue
                # a statement that both dispatches and syncs (e.g.
                # ``np.asarray(f(x))``) is honest; sync wins
                if _ob603_syncs(stmt):
                    pending = None
                    continue
                if started:
                    disp = _ob603_dispatch(stmt, jitted)
                    if disp is not None:
                        pending = disp
        return out

    def _run_ob602(self, ctx: FileContext) -> List[Violation]:
        reads: List[Tuple[ast.Call, str]] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                name = _registry_read_name(node)
                if name is not None:
                    reads.append((node, name))
        if not reads:
            return []
        # the universe: the package-wide canonical scan plus this file's own
        # definitions (fixture snippets define-and-read in one tree)
        universe = _package_family_universe() | _collect_family_definitions(
            ctx.tree
        )
        out: List[Violation] = []
        for node, name in reads:
            if name in universe:
                continue
            out.append(
                Violation(
                    ctx.path, node.lineno, node.col_offset, "OB602",
                    f"metric family name '{name}' does not resolve to any "
                    "registered family (reg.counter/gauge/histogram call) — "
                    "a typo'd read silently returns zeros to the "
                    "aggregation/healthz consumer",
                )
            )
        return out

    def _run_ob601(self, ctx: FileContext) -> List[Violation]:
        device_nodes: Dict[int, Tuple[str, str]] = {}  # node id -> (kind, label)
        for fn in _TracedFunctions().resolve(ctx.tree):
            label = getattr(fn, "name", "<lambda>")
            for node in body_walk(fn):
                device_nodes.setdefault(id(node), ("traced function", label))
        for fn, role in _KernelCollector().collect(ctx):
            label = getattr(fn, "name", "<lambda>")
            for node in body_walk(fn):
                device_nodes.setdefault(id(node), (f"Pallas {role}", label))

        out: List[Violation] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            span_open = _is_tracer_span_open(node)
            flight_emit = _is_flight_emit(node)
            if not span_open and not flight_emit:
                continue
            hit = device_nodes.get(id(node))
            if hit is not None:
                kind, label = hit
                what = "tracer span open" if span_open else "flight-recorder emission"
                out.append(
                    Violation(
                        ctx.path, node.lineno, node.col_offset, "OB601",
                        f"{what} inside {kind} '{label}': fires per compile, "
                        "not per call — emit at the jit call site after the "
                        "dispatch returns",
                    )
                )
                continue
            if span_open and not isinstance(
                ctx.parents.get(node), ast.withitem
            ):
                out.append(
                    Violation(
                        ctx.path, node.lineno, node.col_offset, "OB601",
                        "tracer span opened outside a with statement: the "
                        "span records in __exit__, so this one is never "
                        "closed and silently leaks — use "
                        "'with tracer.span(...) as sp:' (or add_span for "
                        "retroactive timestamps)",
                    )
                )
        return out
