"""RB — robustness checker.

**RB501** — ``os._exit`` kills the process without running ``finally``
blocks, atexit hooks, or buffered-IO flush. The fault-tolerance layer
depends on orderly unwinding: a checkpoint save interrupted by ``os._exit``
skips its atomic commit, and a serving process exiting this way drops
finished requests that were awaiting delivery. The only sanctioned users
are:

- ``distributed/watchdog.py`` — the reference CommTaskManager contract is
  dump-then-abort; a hung collective cannot be cancelled from Python, so a
  normal exit would block forever;
- ``distributed/launch/`` — the launcher's process-group teardown, where the
  children being killed are the ones being relaunched.

**RB502** — an un-timed blocking wait is how a shed request wedges a worker
forever: the serving layer's contract is that every request reaches a
terminal state in bounded time, and one ``Queue.get()`` with no timeout on a
stream whose producer died (engine permanently failed, request shed, client
gone) parks the thread past any deadline the request carried. In the
request-serving and collective paths (``serving/``, ``distributed/``,
``inference/``), blocking waits must pass an explicit timeout. Detection is
constructor-tracked, so ``dict.get`` / ``str.join`` / path joins are never
confused for waits: a name (or ``self.<attr>``) assigned from
``queue.Queue/SimpleQueue/LifoQueue/PriorityQueue``,
``threading.Event/Condition``, ``threading.Thread`` or ``socket.socket`` is
the receiver set, and on those receivers:

- ``q.get()`` needs a ``timeout=`` kwarg or 2nd positional (``get(block,
  timeout)``); ``get_nowait`` is always fine;
- ``e.wait()`` / ``t.join()`` need a timeout kwarg or 1st positional;
- ``s.recv()`` has no timeout parameter — the socket must have
  ``settimeout(...)`` called on it somewhere in the same file.

**RB503** — unbounded retry discipline. A retry/re-dispatch loop that spins
until success is a retry storm waiting to happen: when the dependency it
retries against is *permanently* gone (a dead replica, an exhausted engine,
a partitioned peer), "retry until it works" means "spin forever while
holding the request". In the request-serving paths, a ``while True:`` loop
whose body makes a retry-shaped call (a callee whose name contains
``retry`` / ``redispatch`` / ``recover`` / ``failover``) must consult a
bounded budget *inside the loop*: a comparison against an attempt counter /
max-attempts / deadline / remaining-time name, or an ``expired()`` check.
Exiting on success alone does not count — success is exactly what the dead
dependency will never deliver. (The engine's ``step()`` recovery loop is
the reference shape: ``attempt >= self.max_recoveries`` bounds it.)

- RB501  ``os._exit`` call outside the sanctioned locations (including
         through an ``import os as X`` alias or ``from os import _exit``).
- RB502  un-timed blocking wait in ``serving/``/``distributed/``/
         ``inference/`` on a tracked Queue/Event/Condition/Thread/socket.
- RB503  ``while True:`` retry/re-dispatch loop in ``serving/``/
         ``distributed/``/``inference/`` with no bounded budget referenced
         in the loop.
"""

from __future__ import annotations

import ast
from pathlib import PurePath
from typing import Dict, List, Optional, Set

from paddle_tpu.analysis.core import Checker, FileContext, Violation

_ALLOWED_FILE_SUFFIX = ("distributed", "watchdog.py")
_ALLOWED_DIR = ("distributed", "launch")

# directories whose code serves requests / drives collectives: un-timed
# waits here turn a shed request or a dead peer into a wedged worker
_TIMED_WAIT_DIRS = ("serving", "distributed", "inference")

# RB503: callee-name markers that make a call "retry-shaped", and the
# budget-name markers a bounding comparison must reference. Substring match
# on the lowercased terminal name (``self.recover`` -> "recover",
# ``redispatch_once`` -> contains "redispatch").
_RETRY_CALL_MARKERS = ("retry", "redispatch", "re_dispatch", "recover", "failover")
_BUDGET_NAME_MARKERS = (
    "attempt", "budget", "deadline", "remaining", "tries", "retries", "max_",
)

# constructor -> receiver kind;   kind -> {method: min positional args that
# make the call timed (timeout kwarg always counts)}
_QUEUE_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue", "JoinableQueue"}
_KIND_METHODS = {
    "queue": {"get": 2},   # get(block, timeout)
    "event": {"wait": 1},  # wait(timeout)
    "thread": {"join": 1},  # join(timeout)
    "socket": {"recv": None},  # no timeout param; needs settimeout() in file
}


def _is_allowed_path(path: str) -> bool:
    parts = PurePath(path).parts
    if len(parts) >= 2 and parts[-2:] == _ALLOWED_FILE_SUFFIX:
        return True
    for i in range(len(parts) - 1):
        if parts[i : i + 2] == _ALLOWED_DIR:
            return True
    return False


def _is_timed_wait_path(path: str) -> bool:
    return any(part in _TIMED_WAIT_DIRS for part in PurePath(path).parts)


def _receiver_key(node: ast.AST) -> Optional[str]:
    """``name`` for ``name.m()``, ``self.attr`` for ``self.attr.m()``."""
    if isinstance(node, ast.Name):
        return node.id
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return f"self.{node.attr}"
    return None


def _ctor_kind(call: ast.expr, module_aliases: Dict[str, Set[str]],
               from_imports: Dict[str, str]) -> Optional[str]:
    """Classify a constructor call: Queue()/queue.Queue()/threading.Event()/
    socket.socket() etc. -> receiver kind, else None."""
    if not isinstance(call, ast.Call):
        return None
    fn = call.func
    if isinstance(fn, ast.Name):
        return from_imports.get(fn.id)
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        mod = fn.value.id
        if mod in module_aliases["queue"] and fn.attr in _QUEUE_CTORS:
            return "queue"
        if mod in module_aliases["threading"]:
            if fn.attr in ("Event", "Condition"):
                return "event"
            if fn.attr == "Thread":
                return "thread"
        if mod in module_aliases["socket"] and fn.attr == "socket":
            return "socket"
    return None


class RobustnessChecker(Checker):
    name = "robustness"
    codes = {
        "RB501": "os._exit outside distributed/watchdog.py or distributed/launch/ "
                 "(bypasses checkpoint flush and finished-request delivery)",
        "RB502": "blocking wait without an explicit timeout in serving/, "
                 "distributed/ or inference/ (an un-timed wait is how a shed "
                 "request wedges a worker forever)",
        "RB503": "while True: retry/re-dispatch loop without a bounded budget "
                 "(attempt counter or deadline check) referenced in the loop "
                 "— a permanently-dead dependency turns it into a retry "
                 "storm holding the request forever",
    }

    def run(self, ctx: FileContext) -> List[Violation]:
        out: List[Violation] = []
        if not _is_allowed_path(ctx.path):
            out.extend(self._check_os_exit(ctx))
        if _is_timed_wait_path(ctx.path):
            out.extend(self._check_untimed_waits(ctx))
            out.extend(self._check_unbounded_retry(ctx))
        return out

    # -- RB501 ---------------------------------------------------------------
    def _check_os_exit(self, ctx: FileContext) -> List[Violation]:
        os_aliases: Set[str] = {"os"}
        exit_names: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "os":
                        os_aliases.add(a.asname or "os")
            elif isinstance(node, ast.ImportFrom) and node.module == "os":
                for a in node.names:
                    if a.name == "_exit":
                        exit_names.add(a.asname or "_exit")
        out: List[Violation] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            hit = (
                isinstance(fn, ast.Attribute)
                and fn.attr == "_exit"
                and isinstance(fn.value, ast.Name)
                and fn.value.id in os_aliases
            ) or (isinstance(fn, ast.Name) and fn.id in exit_names)
            if hit:
                out.append(
                    Violation(
                        ctx.path, node.lineno, node.col_offset, "RB501",
                        "os._exit skips finally/atexit/IO flush — it bypasses "
                        "checkpoint commit and finished-request delivery; only "
                        "the watchdog abort path (distributed/watchdog.py) and "
                        "the launcher (distributed/launch/) may call it",
                    )
                )
        return out

    # -- RB502 ---------------------------------------------------------------
    def _collect_receivers(self, ctx: FileContext) -> tuple:
        """(receiver key -> kind, receivers with settimeout() called)."""
        module_aliases: Dict[str, Set[str]] = {
            "queue": set(), "threading": set(), "socket": set()
        }
        from_imports: Dict[str, str] = {}  # local ctor name -> kind
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name in module_aliases:
                        module_aliases[a.name].add(a.asname or a.name)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "queue":
                    for a in node.names:
                        if a.name in _QUEUE_CTORS:
                            from_imports[a.asname or a.name] = "queue"
                elif node.module == "threading":
                    for a in node.names:
                        if a.name in ("Event", "Condition"):
                            from_imports[a.asname or a.name] = "event"
                        elif a.name == "Thread":
                            from_imports[a.asname or a.name] = "thread"
                elif node.module == "socket":
                    for a in node.names:
                        if a.name == "socket":
                            from_imports[a.asname or a.name] = "socket"
        tracked: Dict[str, str] = {}
        timed_sockets: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                # AnnAssign too: `self._q: Queue = Queue()` is the style the
                # serving frontend itself uses — it must not be invisible
                if node.value is None:
                    continue
                kind = _ctor_kind(node.value, module_aliases, from_imports)
                if kind is not None:
                    targets = (
                        node.targets if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for tgt in targets:
                        key = _receiver_key(tgt)
                        if key is not None:
                            tracked[key] = kind
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "settimeout"
            ):
                key = _receiver_key(node.func.value)
                if key is not None:
                    timed_sockets.add(key)
        return tracked, timed_sockets

    def _check_untimed_waits(self, ctx: FileContext) -> List[Violation]:
        tracked, timed_sockets = self._collect_receivers(ctx)
        if not tracked:
            return []
        out: List[Violation] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Attribute):
                continue
            method = node.func.attr
            key = _receiver_key(node.func.value)
            if key is None:
                continue
            kind = tracked.get(key)
            if kind is None or method not in _KIND_METHODS.get(kind, ()):
                continue
            min_pos = _KIND_METHODS[kind][method]
            if min_pos is None:  # socket.recv: timeout lives on the socket
                if key in timed_sockets:
                    continue
            else:
                has_kw = any(kw.arg == "timeout" for kw in node.keywords)
                if has_kw or len(node.args) >= min_pos:
                    continue
            out.append(
                Violation(
                    ctx.path, node.lineno, node.col_offset, "RB502",
                    f"blocking {key}.{method}() without an explicit timeout "
                    "in a request-serving path: if the producer/peer dies "
                    "(request shed, engine failed, client gone) this wait "
                    "parks the worker forever — pass timeout= "
                    + ("(or call settimeout() on the socket)"
                       if kind == "socket" else "")
                    + " and handle the expiry",
                )
            )
        return out

    # -- RB503 ---------------------------------------------------------------
    @staticmethod
    def _terminal_name(node: ast.AST) -> Optional[str]:
        """``name`` for a Name, ``attr`` for any Attribute chain's last link
        (``self.max_recoveries`` -> ``max_recoveries``)."""
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            return node.attr
        return None

    @staticmethod
    def _loop_body_walk(loop: ast.While):
        """Walk the loop body without descending into nested function/class
        definitions (a closure's retry is that function's loop to bound)."""
        stack: list = list(loop.body) + list(loop.orelse)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
            ):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _check_unbounded_retry(self, ctx: FileContext) -> List[Violation]:
        out: List[Violation] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.While):
                continue
            test = node.test
            infinite = (
                isinstance(test, ast.Constant) and test.value in (True, 1)
            )
            if not infinite:
                continue  # a conditioned while IS its own bound
            retry_call = None
            budgeted = False
            for sub in self._loop_body_walk(node):
                if isinstance(sub, ast.Call):
                    name = (self._terminal_name(sub.func) or "").lower()
                    if any(m in name for m in _RETRY_CALL_MARKERS):
                        retry_call = retry_call or sub
                    if name == "expired":  # req.expired(now): a deadline check
                        budgeted = True
                elif isinstance(sub, ast.Compare):
                    names = [self._terminal_name(sub.left)] + [
                        self._terminal_name(c) for c in sub.comparators
                    ]
                    if any(
                        n is not None
                        and any(m in n.lower() for m in _BUDGET_NAME_MARKERS)
                        for n in names
                    ):
                        budgeted = True
            if retry_call is not None and not budgeted:
                out.append(
                    Violation(
                        ctx.path, node.lineno, node.col_offset, "RB503",
                        "unbounded retry loop: this while True: makes a "
                        "retry/re-dispatch call but references no bounded "
                        "budget — against a permanently-dead dependency it "
                        "spins forever holding the request; compare an "
                        "attempt counter or deadline inside the loop "
                        "(success-exit alone is not a bound)",
                    )
                )
        return out
