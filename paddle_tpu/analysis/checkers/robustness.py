"""RB — robustness checker.

``os._exit`` kills the process without running ``finally`` blocks, atexit
hooks, or buffered-IO flush. The fault-tolerance layer depends on orderly
unwinding: a checkpoint save interrupted by ``os._exit`` skips its atomic
commit, and a serving process exiting this way drops finished requests that
were awaiting delivery. The only sanctioned users are:

- ``distributed/watchdog.py`` — the reference CommTaskManager contract is
  dump-then-abort; a hung collective cannot be cancelled from Python, so a
  normal exit would block forever;
- ``distributed/launch/`` — the launcher's process-group teardown, where the
  children being killed are the ones being relaunched.

- RB501  ``os._exit`` call outside those locations (including through an
         ``import os as X`` alias or ``from os import _exit``).
"""

from __future__ import annotations

import ast
from pathlib import PurePath
from typing import List, Set

from paddle_tpu.analysis.core import Checker, FileContext, Violation

_ALLOWED_FILE_SUFFIX = ("distributed", "watchdog.py")
_ALLOWED_DIR = ("distributed", "launch")


def _is_allowed_path(path: str) -> bool:
    parts = PurePath(path).parts
    if len(parts) >= 2 and parts[-2:] == _ALLOWED_FILE_SUFFIX:
        return True
    for i in range(len(parts) - 1):
        if parts[i : i + 2] == _ALLOWED_DIR:
            return True
    return False


class RobustnessChecker(Checker):
    name = "robustness"
    codes = {
        "RB501": "os._exit outside distributed/watchdog.py or distributed/launch/ "
                 "(bypasses checkpoint flush and finished-request delivery)",
    }

    def run(self, ctx: FileContext) -> List[Violation]:
        if _is_allowed_path(ctx.path):
            return []
        os_aliases: Set[str] = {"os"}
        exit_names: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "os":
                        os_aliases.add(a.asname or "os")
            elif isinstance(node, ast.ImportFrom) and node.module == "os":
                for a in node.names:
                    if a.name == "_exit":
                        exit_names.add(a.asname or "_exit")
        out: List[Violation] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            hit = (
                isinstance(fn, ast.Attribute)
                and fn.attr == "_exit"
                and isinstance(fn.value, ast.Name)
                and fn.value.id in os_aliases
            ) or (isinstance(fn, ast.Name) and fn.id in exit_names)
            if hit:
                out.append(
                    Violation(
                        ctx.path, node.lineno, node.col_offset, "RB501",
                        "os._exit skips finally/atexit/IO flush — it bypasses "
                        "checkpoint commit and finished-request delivery; only "
                        "the watchdog abort path (distributed/watchdog.py) and "
                        "the launcher (distributed/launch/) may call it",
                    )
                )
        return out
