"""CM — distributed-protocol checker (collective matching, store hygiene,
mesh/sharding discipline).

Built on :mod:`paddle_tpu.analysis.dataflow`'s :class:`ProtocolCall` record
(collectives and coordination-store ops identified by import/receiver shape,
with lock context and enclosing function) plus the package-level memos
(``thread_lock_acquirers``, ``mesh_axes``) — so the whole-package run stays
one index build and the <30s wall gate holds. Every rule under-approximates:
unresolvable receivers, keys and axis names produce silence, not spam.

**Rank-divergent collective (CM1001)** — a collective reachable only under a
branch conditioned on rank-/replica-local state (``get_rank``/
``process_index`` results, ``.rank`` attributes, coordination-store probe
results) with no balanced collective of the same op in the sibling arm:
ranks that skip the call leave every peer parked in the collective forever.
The fix shape is the rejoin-after-branch idiom — branch on rank for the
*payload*, issue the collective unconditionally after the join. Except-arm
divergence is CM1004's territory and excluded here.

**Collective under a thread-shared lock (CM1002)** — a collective or
blocking store ``get``/``wait`` issued while holding a host lock that a
discovered thread entry (probe loop, HTTP handler, flag listener) or
anything reachable from one also acquires: the collective blocks on remote
ranks while the thread blocks on the lock — the PR 13
blocking-collective-under-lock deadlock, proven statically from the lock
regions and thread-entry discovery instead of at rendezvous timeout.

**Store key hygiene (CM1003)** — the PR 13 unbounded-store lesson. A store
``set`` whose key embeds a per-call counter must pair with a ``delete`` on a
path dominating function exit (``finally`` or unconditional top-level); a
key namespaced by a generation-style binding must have a same-module
``delete`` covering its key family (the generation-GC shape); a key with a
dynamic component that is neither counter/generation-scoped, rank-bounded,
nor a caller-supplied parameter grows the store without bound and is flagged
as un-namespaced. Fully-literal keys are bounded overwrites and exempt.

**Collective in an exception arm (CM1004)** — a collective inside an
``except`` body whose try-block can raise on a data-dependent path, or
inside a ``finally`` whose try-block both raises and issues collectives:
only the ranks that took the exception path run the handler's collective —
protocol skew against every rank that didn't.

**Mesh/sharding discipline (CM1005)** — (a) a literal ``PartitionSpec`` axis
name that resolves against no mesh axis defined anywhere in the package
(axes come from ``Mesh``/``make_mesh``/``init_mesh``/``ProcessMesh``/
``new_group`` definitions, through module string constants like
``TP_AXIS``); silently unresolvable axes shard nothing. (b) a ``jax.jit``
with ``donate_argnums`` and ``in_shardings`` but no ``out_shardings``: the
output sharding is then inferred per-call, and a second layout materializes
a silent second executable that today only the recompile watchdog's 1-compile
tests catch at runtime.

- CM1001  collective under a rank-local branch without an all-ranks rejoin
- CM1002  collective/blocking store op while holding a thread-shared lock
- CM1003  coordination-store key without bounded lifetime (counter key
          lacking a dominating delete / generation key lacking family GC /
          un-namespaced dynamic key)
- CM1004  collective inside an except/finally arm of a raising try block
- CM1005  PartitionSpec axis not defined by any mesh, or donating sharded
          jit without pinned out_shardings
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from paddle_tpu.analysis.checkers._shared import attr_chain, const_str, func_params
from paddle_tpu.analysis.core import Checker, FileContext, Violation
from paddle_tpu.analysis.dataflow import (
    ModuleGraph,
    PackageIndex,
    ProtocolCall,
    _store_receiver,
)

# calls whose result is rank-/replica-local state
_RANK_CALLS = {
    "get_rank", "process_index", "local_rank", "get_local_rank", "axis_index",
    "node_rank", "replica_id", "get_world_rank", "task_id",
}
# attribute/name leaves that denote rank-local state
_RANK_ATTRS = {
    "rank", "local_rank", "process_index", "replica_id", "my_rank",
    "node_rank", "rank_id", "proc_id",
}
# store probe methods whose results are rank-local (each rank sees its own
# answer at its own time)
_PROBE_METHODS = {"get", "check", "blocking_key_value_get", "key_value_try_get"}

# CM1003 placeholder classification
_GEN_RE = re.compile(
    r"gen|generation|epoch|seq|count|counter|version|round|step|attempt"
    r"|call|nonce|uid|lease|ticket"
)
_RANKLIKE_RE = re.compile(
    r"rank|world|host|node|proc|pid|local|worker|device|replica|index|idx"
)

# a skeleton part is ("lit", text, None) or ("ph", name, resolved_src_node)
_Part = Tuple[str, str, Optional[ast.AST]]


class DistributedProtocolChecker(Checker):
    name = "distributed_protocol"
    codes = {
        "CM1001": "collective reachable only under a rank-/replica-local "
                  "branch without an all-ranks rejoin (mismatched collective "
                  "sequences deadlock every peer)",
        "CM1002": "collective or blocking store op issued while holding a "
                  "lock that a thread entry also acquires (remote-blocking "
                  "call under a host lock: deadlock shape)",
        "CM1003": "coordination-store key without a bounded lifetime "
                  "(counter-namespaced key lacking a delete that dominates "
                  "function exit, generation key lacking family GC, or a "
                  "dynamic key not namespaced at all)",
        "CM1004": "collective inside an except/finally arm of a try block "
                  "that can raise data-dependently (only some ranks enter "
                  "the handler: protocol skew)",
        "CM1005": "PartitionSpec axis name that no mesh in the package "
                  "defines, or a donating jit over sharded inputs without "
                  "pinned out_shardings (silent second executable)",
    }

    def run(self, ctx: FileContext) -> List[Violation]:
        index = ctx.project.dataflow()
        graph = index.module(ctx.path)
        if graph is None:
            graph = index.add_module(ctx.path, ctx.tree)
        collectives = [p for p in graph.protocol_calls if p.kind == "collective"]
        out: List[Violation] = []
        out.extend(self._check_rank_divergence(ctx, graph, collectives))
        out.extend(self._check_lock_deadlock(ctx, index, graph))
        out.extend(self._check_store_hygiene(ctx, graph))
        out.extend(self._check_exception_skew(ctx, collectives))
        out.extend(self._check_mesh_discipline(ctx, index, graph))
        return out

    # -- CM1001 ---------------------------------------------------------------
    def _check_rank_divergence(
        self, ctx: FileContext, graph: ModuleGraph, collectives: List[ProtocolCall]
    ) -> List[Violation]:
        out: List[Violation] = []
        rank_names_memo: Dict[int, Set[str]] = {}
        for pc in collectives:
            fn = self._enclosing_function(ctx, pc.node)
            if fn is None:
                continue
            rank_names = rank_names_memo.get(id(fn))
            if rank_names is None:
                rank_names = _rank_local_names(fn)
                rank_names_memo[id(fn)] = rank_names
            cur: ast.AST = pc.node
            fired = False
            for anc in ctx.ancestors(pc.node):
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    break
                if isinstance(anc, ast.ExceptHandler):
                    fired = True  # CM1004's territory — never double-report
                    break
                sibling = self._divergent_sibling(anc, cur, rank_names)
                if sibling is not None and not self._balanced(
                    sibling, pc, collectives
                ):
                    out.append(
                        Violation(
                            ctx.path, pc.lineno, pc.col, "CM1001",
                            f"collective '{pc.chain}' in {pc.func} runs only "
                            "under a branch conditioned on rank-local state "
                            f"(line {anc.lineno}): ranks that skip it leave "
                            "every peer parked in the collective — issue the "
                            "collective on all ranks and branch on the "
                            "payload instead",
                        )
                    )
                    fired = True
                if fired:
                    break
                cur = anc
        return out

    def _divergent_sibling(
        self, anc: ast.AST, cur: ast.AST, rank_names: Set[str]
    ) -> Optional[Sequence[ast.AST]]:
        """When ``anc`` is a rank-local branch and ``cur`` sits in one arm,
        the statements of the other arm (the rejoin search space); None when
        ``anc`` is not a diverging construct. A while-loop body has no
        sibling arm — rank-local iteration counts always diverge — so it
        returns an empty sequence."""
        if isinstance(anc, ast.If):
            if not _is_rank_local(anc.test, rank_names):
                return None
            if any(cur is s for s in anc.body):
                return anc.orelse
            if any(cur is s for s in anc.orelse):
                return anc.body
            return None  # inside the test expression itself
        if isinstance(anc, ast.IfExp):
            if not _is_rank_local(anc.test, rank_names):
                return None
            if cur is anc.body:
                return [anc.orelse]
            if cur is anc.orelse:
                return [anc.body]
            return None
        if isinstance(anc, ast.While):
            if _is_rank_local(anc.test, rank_names) and any(
                cur is s for s in anc.body
            ):
                return []
            return None
        return None

    def _balanced(
        self,
        sibling: Sequence[ast.AST],
        pc: ProtocolCall,
        collectives: List[ProtocolCall],
    ) -> bool:
        """The other arm re-issues the same collective op — both sides of the
        branch keep the protocol sequence aligned."""
        ids: Set[int] = set()
        for s in sibling:
            ids.update(id(n) for n in ast.walk(s))
        return any(
            other.op == pc.op and id(other.node) in ids
            for other in collectives
            if other is not pc
        )

    def _enclosing_function(self, ctx: FileContext, node: ast.AST) -> Optional[ast.AST]:
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    # -- CM1002 ---------------------------------------------------------------
    def _check_lock_deadlock(
        self, ctx: FileContext, index: PackageIndex, graph: ModuleGraph
    ) -> List[Violation]:
        acquirers = index.thread_lock_acquirers()
        out: List[Violation] = []
        for pc in graph.protocol_calls:
            if pc.kind not in ("collective", "store_get") or not pc.locks_held:
                continue
            for lk in sorted(pc.locks_held):
                others = [
                    (p, q) for p, q in acquirers.get(lk, [])
                    if not (p == ctx.path and q == pc.func)
                ]
                if not others:
                    continue
                p, q = others[0]
                kindname = "collective" if pc.kind == "collective" else "blocking store op"
                out.append(
                    Violation(
                        ctx.path, pc.lineno, pc.col, "CM1002",
                        f"{kindname} '{pc.chain}' in {pc.func} is issued "
                        f"while holding {lk}, which {q} (thread-side, "
                        f"{p}) also acquires: the call blocks on remote "
                        "ranks while the thread blocks on the lock — move "
                        "the call outside the locked region",
                    )
                )
                break
        return out

    # -- CM1003 ---------------------------------------------------------------
    def _check_store_hygiene(
        self, ctx: FileContext, graph: ModuleGraph
    ) -> List[Violation]:
        out: List[Violation] = []
        deletes = [p for p in graph.protocol_calls if p.kind == "store_delete"]
        delete_heads = [
            self._family_head(self._key_parts(ctx, graph, d)) for d in deletes
        ]
        for pc in graph.protocol_calls:
            if pc.kind != "store_set":
                continue
            parts = self._key_parts(ctx, graph, pc)
            if parts is None:
                continue
            finfo = graph.functions.get(pc.func)
            fn = finfo.node if finfo is not None else None
            params = func_params(fn) if fn is not None else set()
            phs = [p for p in parts if p[0] == "ph"]
            if not phs:
                continue  # fully-literal key: bounded overwrite
            counter_phs = [
                p for p in phs if fn is not None and _is_per_call_counter(fn, p)
            ]
            gen_phs = [
                p for p in phs
                if p not in counter_phs and _GEN_RE.search(_norm(p[1]))
            ]
            loose = [
                p for p in phs
                if p not in counter_phs and p not in gen_phs
                and not _RANKLIKE_RE.search(_norm(p[1]))
                and p[1] not in params
            ]
            if counter_phs:
                dom = [
                    d for d in deletes
                    if d.func == pc.func and self._dominates_exit(ctx, d.node, fn)
                ]
                if not dom:
                    out.append(
                        Violation(
                            ctx.path, pc.lineno, pc.col, "CM1003",
                            f"store key in {pc.func} is namespaced by the "
                            f"per-call counter '{counter_phs[0][1]}' but no "
                            "delete dominates function exit: every call "
                            "leaves a fresh key behind — delete it in a "
                            "finally (the all_gather_object shape)",
                        )
                    )
            elif gen_phs:
                head = self._family_head(parts)
                covered = any(
                    dh is None or head is None or dh == head
                    for dh in delete_heads
                )
                if not covered:
                    out.append(
                        Violation(
                            ctx.path, pc.lineno, pc.col, "CM1003",
                            f"store key in {pc.func} is namespaced by "
                            f"generation-style binding '{gen_phs[0][1]}' but "
                            "this module never deletes keys of the "
                            f"'{head or '?'}' family: every generation bump "
                            "strands the previous generation's keys — GC the "
                            "old generation where the binding advances",
                        )
                    )
            elif loose:
                out.append(
                    Violation(
                        ctx.path, pc.lineno, pc.col, "CM1003",
                        f"store key in {pc.func} embeds dynamic component "
                        f"'{loose[0][1]}' that is neither counter/generation-"
                        "namespaced nor rank-bounded: the store grows by one "
                        "key per distinct value with nothing to GC it — "
                        "namespace the key by a generation/counter and pair "
                        "it with a delete",
                    )
                )
        return out

    def _key_parts(
        self, ctx: FileContext, graph: ModuleGraph, pc: ProtocolCall
    ) -> Optional[List[_Part]]:
        if not pc.node.args:
            return None
        finfo = graph.functions.get(pc.func)
        fn = finfo.node if finfo is not None else None
        cls = finfo.class_name if finfo is not None else None
        return _key_skeleton(pc.node.args[0], fn, graph, cls, {}, 0)

    def _family_head(self, parts: Optional[List[_Part]]) -> Optional[str]:
        """First literal key segment ("elastic" for ``elastic/{gen}/...``);
        None when the key opens with a placeholder — which then matches any
        family (under-approximation keeps unresolvable deletes counting)."""
        if not parts or parts[0][0] != "lit":
            return None
        return parts[0][1].split("/", 1)[0]

    def _dominates_exit(
        self, ctx: FileContext, node: ast.AST, fn: Optional[ast.AST]
    ) -> bool:
        """The statement holding ``node`` runs on every path out of ``fn``:
        every ancestor up to the function is plain sequencing, a with-block,
        or the *finalbody* of a try. Any If/loop/handler/try-body ancestor
        means a path can skip it."""
        if fn is None:
            return False
        cur: ast.AST = node
        for anc in ctx.ancestors(node):
            if anc is fn:
                return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return False
            if isinstance(
                anc,
                (ast.If, ast.IfExp, ast.While, ast.For, ast.AsyncFor,
                 ast.ExceptHandler),
            ):
                return False
            if isinstance(anc, ast.Try) and not any(
                cur is s for s in anc.finalbody
            ):
                return False
            cur = anc
        return False

    # -- CM1004 ---------------------------------------------------------------
    def _check_exception_skew(
        self, ctx: FileContext, collectives: List[ProtocolCall]
    ) -> List[Violation]:
        out: List[Violation] = []
        for pc in collectives:
            cur: ast.AST = pc.node
            for anc in ctx.ancestors(pc.node):
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    break
                if isinstance(anc, ast.ExceptHandler):
                    try_node = ctx.parents.get(anc)
                    if isinstance(try_node, ast.Try) and _can_raise(try_node.body):
                        out.append(
                            Violation(
                                ctx.path, pc.lineno, pc.col, "CM1004",
                                f"collective '{pc.chain}' in {pc.func} runs "
                                "inside an except arm of a try block that "
                                "can raise data-dependently: only the ranks "
                                "that hit the exception issue it — protocol "
                                "skew against every other rank",
                            )
                        )
                    break
                if isinstance(anc, ast.Try) and any(
                    cur is s for s in anc.finalbody
                ):
                    if _can_raise(anc.body) and self._body_has_collective(
                        anc.body, pc, collectives
                    ):
                        out.append(
                            Violation(
                                ctx.path, pc.lineno, pc.col, "CM1004",
                                f"collective '{pc.chain}' in {pc.func} runs "
                                "in a finally whose try block also issues "
                                "collectives and can raise: a mid-sequence "
                                "raise leaves ranks disagreeing on how many "
                                "collectives ran before this one",
                            )
                        )
                    break
                cur = anc
        return out

    def _body_has_collective(
        self,
        body: Sequence[ast.AST],
        pc: ProtocolCall,
        collectives: List[ProtocolCall],
    ) -> bool:
        ids: Set[int] = set()
        for s in body:
            ids.update(id(n) for n in ast.walk(s))
        return any(
            id(other.node) in ids for other in collectives if other is not pc
        )

    # -- CM1005 ---------------------------------------------------------------
    def _check_mesh_discipline(
        self, ctx: FileContext, index: PackageIndex, graph: ModuleGraph
    ) -> List[Violation]:
        out: List[Violation] = []
        universe = index.mesh_axes()
        pspec_locals = {
            local
            for local, (_m, orig) in graph.from_imports.items()
            if orig == "PartitionSpec"
        } | {"PartitionSpec"}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            is_pspec = (isinstance(f, ast.Name) and f.id in pspec_locals) or (
                isinstance(f, ast.Attribute) and f.attr == "PartitionSpec"
            )
            if is_pspec and universe:
                for arg in node.args:
                    elts = arg.elts if isinstance(arg, (ast.Tuple, ast.List)) else [arg]
                    for el in elts:
                        s = const_str(el)
                        if s is None and isinstance(el, ast.Name):
                            s = graph.str_consts.get(el.id)
                        if s is not None and s not in universe:
                            out.append(
                                Violation(
                                    ctx.path, el.lineno, el.col_offset, "CM1005",
                                    f"PartitionSpec axis '{s}' is not an "
                                    "axis of any mesh defined in the "
                                    "package (known axes: "
                                    f"{', '.join(sorted(universe))}): the "
                                    "spec silently resolves to no sharding",
                                )
                            )
            chain = attr_chain(f)
            if chain in ("jax.jit", "jit"):
                kwargs = {kw.arg for kw in node.keywords if kw.arg}
                donated = False
                for kw in node.keywords:
                    if kw.arg == "donate_argnums":
                        donated = any(
                            isinstance(n, ast.Constant)
                            and isinstance(n.value, int)
                            and not isinstance(n.value, bool)
                            for n in ast.walk(kw.value)
                        )
                if donated and "in_shardings" in kwargs and "out_shardings" not in kwargs:
                    out.append(
                        Violation(
                            ctx.path, node.lineno, node.col_offset, "CM1005",
                            "jit with donate_argnums over sharded inputs "
                            "(in_shardings) but no out_shardings: the output "
                            "layout is re-inferred per call and a second "
                            "layout compiles a silent second executable — "
                            "pin out_shardings",
                        )
                    )
        return out


# -- rank-locality inference ---------------------------------------------------

def _norm(name: str) -> str:
    return name.lstrip("_").lower()


def _is_rank_source(expr: ast.AST, rank_names: Set[str]) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            chain = attr_chain(n.func)
            if chain is None:
                continue
            parts = chain.split(".")
            if parts[-1] in _RANK_CALLS:
                return True
            if parts[-1] in _PROBE_METHODS and len(parts) >= 2 and _store_receiver(
                parts[-2]
            ):
                return True
        elif isinstance(n, ast.Attribute) and n.attr in _RANK_ATTRS:
            return True
        elif isinstance(n, ast.Name) and (
            n.id in _RANK_ATTRS or n.id in rank_names
        ):
            return True
    return False


def _rank_local_names(fn: ast.AST) -> Set[str]:
    """Names in ``fn`` assigned (directly or one propagation step) from
    rank-local sources — ``rank = jax.process_index()`` then
    ``is_main = rank == 0``."""
    names: Set[str] = set()
    for _ in range(2):
        grew = False
        for node in ast.walk(fn):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = getattr(node, "value", None)
            if value is None or not _is_rank_source(value, names):
                continue
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id not in names:
                    names.add(t.id)
                    grew = True
        if not grew:
            break
    return names


def _is_rank_local(test: ast.AST, rank_names: Set[str]) -> bool:
    return _is_rank_source(test, rank_names)


def _can_raise(body: Sequence[ast.AST]) -> bool:
    """A try body that contains a call, subscript or explicit raise can fail
    on a data-dependent path; constant-only bodies cannot."""
    for s in body:
        for n in ast.walk(s):
            if isinstance(n, (ast.Raise, ast.Call, ast.Subscript)):
                return True
    return False


# -- store-key skeleton resolution (CM1003) ------------------------------------

def _ph_name(expr: ast.AST) -> str:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Subscript):
        return _ph_name(expr.value)
    if isinstance(expr, ast.Call):
        chain = attr_chain(expr.func)
        return chain.split(".")[-1] if chain else "expr"
    return "expr"


_CTX_RE = re.compile(r"ctx=(?:Load|Store|Del)\(\)")


def _dump_noctx(node: ast.AST) -> str:
    """Expression identity modulo Load/Store context — ``x[0]`` read in an
    assignment must match ``x[0] += 1``'s store target."""
    return _CTX_RE.sub("ctx=*", ast.dump(node))


def _single_assign(fn: ast.AST, name: str) -> Optional[ast.AST]:
    found: List[ast.AST] = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = getattr(node, "value", None)
            if value is None:
                continue
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            if any(isinstance(t, ast.Name) and t.id == name for t in targets):
                found.append(value)
    return found[0] if len(found) == 1 else None


def _key_skeleton(
    expr: ast.AST,
    fn: Optional[ast.AST],
    graph: ModuleGraph,
    class_name: Optional[str],
    env: Dict[str, List[_Part]],
    depth: int,
) -> List[_Part]:
    """Resolve a store-key expression to literal/placeholder parts, chasing
    single-assignment locals, module string constants, string concatenation
    and single-return key-helper methods (``self._beat_key(rank)``) with
    caller-argument substitution. Anything unresolvable becomes a named
    placeholder — classification, not parsing, decides what fires."""
    if depth > 6:
        return [("ph", _ph_name(expr), None)]
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return [("lit", expr.value, None)]
    if isinstance(expr, ast.JoinedStr):
        parts: List[_Part] = []
        for v in expr.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(("lit", v.value, None))
            elif isinstance(v, ast.FormattedValue):
                parts.extend(
                    _key_skeleton(v.value, fn, graph, class_name, env, depth + 1)
                )
        return parts
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        return _key_skeleton(expr.left, fn, graph, class_name, env, depth + 1) + \
            _key_skeleton(expr.right, fn, graph, class_name, env, depth + 1)
    if isinstance(expr, ast.Name):
        if expr.id in env:
            return env[expr.id]
        if expr.id in graph.str_consts:
            return [("lit", graph.str_consts[expr.id], None)]
        if fn is not None:
            src = _single_assign(fn, expr.id)
            if src is not None:
                resolved = _key_skeleton(src, fn, graph, class_name, env, depth + 1)
                # keep the local's own name on a still-opaque placeholder so
                # counter detection sees both the name and the source expr
                if len(resolved) == 1 and resolved[0][0] == "ph" and resolved[0][2] is src:
                    return [("ph", expr.id, src)]
                return resolved
        return [("ph", expr.id, expr)]
    if isinstance(expr, ast.Call):
        callee = _resolve_key_helper(expr, graph, class_name)
        if callee is not None:
            finfo_node, callee_cls, ret = callee
            params = [
                a.arg
                for a in (*finfo_node.args.posonlyargs, *finfo_node.args.args)
                if a.arg != "self"
            ]
            newenv: Dict[str, List[_Part]] = {}
            for pname, arg in zip(params, expr.args):
                newenv[pname] = _key_skeleton(
                    arg, fn, graph, class_name, env, depth + 1
                )
            for kw in expr.keywords:
                if kw.arg:
                    newenv[kw.arg] = _key_skeleton(
                        kw.value, fn, graph, class_name, env, depth + 1
                    )
            return _key_skeleton(
                ret, finfo_node, graph, callee_cls, newenv, depth + 1
            )
        return [("ph", _ph_name(expr), expr)]
    return [("ph", _ph_name(expr), expr)]


def _resolve_key_helper(
    call: ast.Call, graph: ModuleGraph, class_name: Optional[str]
) -> Optional[Tuple[ast.AST, Optional[str], ast.AST]]:
    """``self._key(...)`` / ``_key(...)`` where the callee is a local
    single-return function: (callee node, callee class, returned expr)."""
    f = call.func
    qual: Optional[str] = None
    if (
        isinstance(f, ast.Attribute)
        and isinstance(f.value, ast.Name)
        and f.value.id == "self"
        and class_name is not None
    ):
        qual = f"{class_name}.{f.attr}"
    elif isinstance(f, ast.Name):
        qual = f.id
    finfo = graph.functions.get(qual) if qual else None
    if finfo is None:
        return None
    returns = [
        n for n in ast.walk(finfo.node)
        if isinstance(n, ast.Return) and n.value is not None
    ]
    if len(returns) != 1:
        return None
    return finfo.node, finfo.class_name, returns[0].value


def _is_per_call_counter(fn: ast.AST, ph: _Part) -> bool:
    """The placeholder advances once per call of ``fn`` itself: its resolved
    source (or its own name) is the target of an AugAssign increment in the
    same function, or it is bound from ``next(...)``."""
    _kind, name, src = ph
    if name == "next":
        return True
    src_dump = _dump_noctx(src) if src is not None else None
    for node in ast.walk(fn):
        if isinstance(node, ast.AugAssign):
            tgt = node.target
            if isinstance(tgt, ast.Name) and tgt.id == name:
                return True
            if src_dump is not None and _dump_noctx(tgt) == src_dump:
                return True
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = getattr(node, "value", None)
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            if (
                isinstance(value, ast.Call)
                and isinstance(value.func, ast.Name)
                and value.func.id == "next"
                and any(isinstance(t, ast.Name) and t.id == name for t in targets)
            ):
                return True
    return False
