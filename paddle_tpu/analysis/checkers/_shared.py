"""AST helpers shared by the checker suite."""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

# names whose attribute calls reach the observability layer (metrics registry
# or recompile watchdog) — host-side state no traced/kernel body may touch
OBSERVABILITY_ROOTS = {"GLOBAL_METRICS", "GLOBAL_WATCHDOG"}
OBSERVABILITY_CALLS = {
    "get_registry",
    "get_watchdog",
    "metrics_enabled",
    "record_compile",
    "write_snapshot_jsonl",
    "start_metrics_server",
    "drain_trace_events",
}


def attr_root(node: ast.AST) -> Optional[str]:
    """Leftmost Name of an attribute chain: ``a.b.c`` -> ``a``."""
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def attr_chain(node: ast.AST) -> Optional[str]:
    """Dotted form of a Name/Attribute chain, or None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_os_environ(node: ast.AST) -> bool:
    return attr_chain(node) in ("os.environ", "environ")


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def bound_names(fn: ast.AST) -> Set[str]:
    """Names bound inside a function: parameters plus every Store/for/with/
    comprehension target — used to separate closure/global reads from locals."""
    names: Set[str] = set()
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        a = fn.args
        for arg in (*a.posonlyargs, *a.args, *a.kwonlyargs):
            names.add(arg.arg)
        if a.vararg:
            names.add(a.vararg.arg)
        if a.kwarg:
            names.add(a.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            names.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                names.add((alias.asname or alias.name).split(".")[0])
    return names


def body_walk(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk a function's body (decorators/defaults of the function itself are
    not part of what executes when it runs)."""
    if isinstance(fn, ast.Lambda):
        yield from ast.walk(fn.body)
        return
    for stmt in getattr(fn, "body", []):
        yield from ast.walk(stmt)


def func_params(fn: ast.AST) -> Set[str]:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        return set()
    a = fn.args
    names = {arg.arg for arg in (*a.posonlyargs, *a.args, *a.kwonlyargs)}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    return names
