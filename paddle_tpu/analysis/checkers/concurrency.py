"""CC — interprocedural concurrency checker (lock discipline).

Built on :mod:`paddle_tpu.analysis.dataflow`: the package-local call graph,
thread-entry discovery and lock-held regions. Scope control is deliberate —
every rule below only fires on a class that (a) owns a lock
(``self._lock = threading.Lock()``/``RLock()``) and (b) is *concurrency
relevant*: one of its methods is a thread entry (``threading.Thread(target=
self._run_loop)``), an HTTP handler method, a flag listener, or is reachable
from any such entry through the call graph. A single-threaded class with a
vestigial lock never spams.

**Guarded-field inference (CC701)** — a field dominated by a lock in at
least one access must be guarded everywhere: if any access to ``self.f``
happens with a class-own lock held, and ``f`` is mutated outside
``__init__``, then every non-``__init__`` access must hold one of the locks
observed guarding ``f``. Lock context is interprocedural: a helper method
whose every resolved call site holds the lock inherits it (fixpoint over the
call graph), so ``submit() -> _tenant_label()`` does not false-positive.
Fields holding inherently thread-safe primitives (``Queue``, ``Event``,
``Condition``, …) are exempt — they do their own locking. Mutation includes
``self.f = ...``, ``self.f[k] = ...``, ``self.f += ...`` and mutator method
calls (``append``/``add``/``pop``/…) on container-kind fields.

**Lock order (CC702)** — two locks acquired in both orders anywhere in the
package (lexical nesting, or a call made with L held reaching a function
whose acquire-closure contains M) is the classic deadlock shape; every
acquisition/call site participating in an inverted pair is flagged.

**Unlocked iteration/snapshot (CC703)** — iterating (``for x in self.f``,
comprehensions, ``list(self.f)``/``sorted(...)``, ``self.f.items()``/
``.values()``/``.keys()``/``.copy()``) over a guarded container outside its
lock: another thread's resize mid-iteration is a ``RuntimeError`` at best
and silent corruption at worst.

**Locked hot read (CC704)** — the `_NAN_CHECK` lesson from PR 3,
interprocedural this time: a flag-registry read (``GLOBAL_FLAGS.get`` /
``get_flags``) inside a hot-path-module function that the call graph can
reach from a loop takes the registry lock once per iteration/op. FD302
already flags the syntactically-in-a-loop case; CC704 covers reads hidden
behind a call edge (the exact shape of the original per-dispatch registry
read in ``core/dispatch.py``). Fix shape: an ``on_change``-cached local
(see ``core/dispatch.py`` ``_NAN_CHECK``).

- CC701  unguarded access to a lock-dominated mutable field
- CC702  inconsistent lock acquisition order (deadlock shape)
- CC703  iteration/snapshot over a guarded container outside its lock
- CC704  flag-registry read on a loop-reachable hot path (registry lock
         taken per op — cache through an on_change listener)
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from paddle_tpu.analysis.checkers._shared import attr_chain
from paddle_tpu.analysis.core import Checker, FileContext, Violation
from paddle_tpu.analysis.dataflow import (
    ClassInfo,
    FieldAccess,
    ModuleGraph,
    PackageIndex,
    _MUTATOR_METHODS,
)

# snapshot/iteration wrappers: self.f handed to one of these leaves the lock
# with a view that is only safe if the copy completed atomically
_ITER_WRAPPERS = {"list", "sorted", "tuple", "set", "frozenset", "sum", "max", "min", "dict"}
_ITER_METHODS = {"items", "values", "keys", "copy"}

_FLAG_READ_CHAINS = {"GLOBAL_FLAGS.get", "get_flags", "paddle.get_flags"}


class ConcurrencyChecker(Checker):
    name = "concurrency"
    codes = {
        "CC701": "field guarded by a lock in some accesses but accessed "
                 "without it elsewhere (guarded-field inference: dominated "
                 "in >=1 access means guarded everywhere)",
        "CC702": "two locks acquired in opposite orders on different paths "
                 "(deadlock shape)",
        "CC703": "iteration/snapshot over a lock-guarded container outside "
                 "its lock (concurrent resize corrupts the traversal)",
        "CC704": "flag-registry read reachable from a loop in a hot-path "
                 "module (takes the registry lock per op — use an "
                 "on_change-cached local)",
    }

    def run(self, ctx: FileContext) -> List[Violation]:
        index = ctx.project.dataflow()
        graph = index.module(ctx.path)
        if graph is None:
            graph = index.add_module(ctx.path, ctx.tree)
        out: List[Violation] = []
        effective = _effective_locks(index, graph)
        relevant = _relevant_classes(index, graph)
        for cname in relevant:
            out.extend(self._check_class(ctx, graph, graph.classes[cname], effective))
        out.extend(self._check_lock_order(ctx, index))
        if ctx.hot_path:
            out.extend(self._check_hot_reads(ctx, index, graph))
        return out

    # -- CC701 + CC703 --------------------------------------------------------
    def _check_class(
        self,
        ctx: FileContext,
        graph: ModuleGraph,
        cls: ClassInfo,
        effective: Dict[str, FrozenSet[str]],
    ) -> List[Violation]:
        own_locks = {f"{cls.name}.{a}" for a in cls.lock_fields}
        if not own_locks:
            return []
        accesses = [
            a for a in cls.accesses
            if cls.field_kinds.get(a.field) not in ("sync", "lock")
        ]
        # guarding locks per field: class-own locks seen on any access
        guards: Dict[str, Set[str]] = {}
        mutated: Set[str] = set()
        enriched: List[Tuple[FieldAccess, FrozenSet[str], bool]] = []
        for a in accesses:
            locks = a.locks_held | effective.get(a.func, frozenset())
            write = a.kind == "write" or self._is_mutation(ctx, cls, a)
            enriched.append((a, locks, write))
            own_held = {lk for lk in locks if lk in own_locks}
            if own_held:
                guards.setdefault(a.field, set()).update(own_held)
            if write and not a.in_init:
                mutated.add(a.field)

        out: List[Violation] = []
        seen: Set[Tuple[int, str]] = set()
        for a, locks, write in enriched:
            g = guards.get(a.field)
            if not g or a.field not in mutated or a.in_init:
                continue
            if locks & set(g):
                continue
            key = (id(a.node), a.field)
            if key in seen:
                continue
            seen.add(key)
            lock_names = "/".join(sorted(g))
            if self._is_iteration(ctx, a):
                out.append(
                    Violation(
                        ctx.path, a.lineno, a.col, "CC703",
                        f"iteration/snapshot over '{cls.name}.{a.field}' "
                        f"without holding {lock_names} (guarding it in other "
                        "accesses): a concurrent resize corrupts the "
                        "traversal — copy under the lock",
                    )
                )
            else:
                verb = "write to" if write else "read of"
                out.append(
                    Violation(
                        ctx.path, a.lineno, a.col, "CC701",
                        f"unguarded {verb} '{cls.name}.{a.field}' in "
                        f"{a.func}: the field is guarded by {lock_names} in "
                        "other accesses, and a field dominated by a lock in "
                        ">=1 access must be guarded everywhere",
                    )
                )
        return out

    def _is_mutation(self, ctx: FileContext, cls: ClassInfo, a: FieldAccess) -> bool:
        """Container-mutator calls and subscript/aug stores count as writes."""
        parent = ctx.parents.get(a.node)
        if isinstance(parent, ast.Subscript) and isinstance(
            parent.ctx, (ast.Store, ast.Del)
        ):
            return True
        if isinstance(parent, ast.AugAssign) and parent.target is a.node:
            return True
        if (
            isinstance(parent, ast.Attribute)
            and parent.attr in _MUTATOR_METHODS
            and isinstance(ctx.parents.get(parent), ast.Call)
            and ctx.parents.get(parent).func is parent  # type: ignore[union-attr]
            and cls.field_kinds.get(a.field) in ("container", "numpy", None)
        ):
            return True
        return False

    def _is_iteration(self, ctx: FileContext, a: FieldAccess) -> bool:
        if a.kind == "iterate":
            return True
        parent = ctx.parents.get(a.node)
        if isinstance(parent, (ast.For, ast.AsyncFor)) and parent.iter is a.node:
            return True
        if isinstance(parent, ast.comprehension) and parent.iter is a.node:
            return True
        if (
            isinstance(parent, ast.Call)
            and a.node in parent.args
            and isinstance(parent.func, ast.Name)
            and parent.func.id in _ITER_WRAPPERS
        ):
            return True
        if (
            isinstance(parent, ast.Attribute)
            and parent.attr in _ITER_METHODS
            and isinstance(ctx.parents.get(parent), ast.Call)
        ):
            return True
        return False

    # -- CC702 ---------------------------------------------------------------
    def _check_lock_order(self, ctx: FileContext, index: PackageIndex) -> List[Violation]:
        pairs = index.lock_order_pairs()
        out: List[Violation] = []
        reported: Set[Tuple[str, int]] = set()
        for (a, b), sites in pairs.items():
            if a >= b or (b, a) not in pairs:
                continue  # visit each inverted pair once, from (min, max)
            for path, line, via in sites + pairs[(b, a)]:
                if path != ctx.path or (path, line) in reported:
                    continue
                reported.add((path, line))
                out.append(
                    Violation(
                        ctx.path, line, 0, "CC702",
                        f"locks {a} and {b} are acquired in both orders "
                        f"across the package (here via {via}): two threads "
                        "taking them in opposite orders deadlock — pick one "
                        "global order",
                    )
                )
        return out

    # -- CC704 ---------------------------------------------------------------
    def _check_hot_reads(
        self, ctx: FileContext, index: PackageIndex, graph: ModuleGraph
    ) -> List[Violation]:
        loopset = index.loop_reachable()
        out: List[Violation] = []
        for qual, finfo in graph.functions.items():
            if graph.node_key(qual) not in loopset:
                continue
            for node in ast.walk(finfo.node):
                if not isinstance(node, ast.Call):
                    continue
                chain = attr_chain(node.func)
                if chain is None:
                    continue
                is_flag_read = chain in _FLAG_READ_CHAINS or chain.endswith(
                    ".get_flags"
                ) or (chain.endswith("GLOBAL_FLAGS.get"))
                if not is_flag_read:
                    continue
                if self._inside_loop(ctx, node):
                    continue  # FD302's territory (syntactic loop in hot module)
                out.append(
                    Violation(
                        ctx.path, node.lineno, node.col_offset, "CC704",
                        f"flag-registry read '{chain}' in {qual} is "
                        "reachable from a loop (call graph): it takes the "
                        "registry lock once per op — cache the value in a "
                        "local refreshed by GLOBAL_FLAGS.on_change (the "
                        "_NAN_CHECK pattern in core/dispatch.py)",
                    )
                )
        return out

    def _inside_loop(self, ctx: FileContext, node: ast.AST) -> bool:
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.For, ast.AsyncFor, ast.While, ast.GeneratorExp,
                                ast.ListComp, ast.SetComp, ast.DictComp)):
                return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
        return False


# -- shared inference helpers --------------------------------------------------

def _relevant_classes(index: PackageIndex, graph: ModuleGraph) -> List[str]:
    """Classes that own a lock AND have a concurrency seam (a method that is
    a thread entry or reachable from one anywhere in the package)."""
    reach = index.thread_reachable()
    entry_quals = {q for q, _k, _ln in graph.thread_entries}
    out: List[str] = []
    for cname, cinfo in graph.classes.items():
        if not cinfo.lock_fields:
            continue
        methods = [q for q in graph.functions if q.startswith(f"{cname}.")]
        if any(q in entry_quals for q in methods) or any(
            graph.node_key(q) in reach for q in methods
        ):
            out.append(cname)
    return out


def _effective_locks(index: PackageIndex, graph: ModuleGraph) -> Dict[str, FrozenSet[str]]:
    """qualname -> locks held at EVERY resolved call site of that function
    (transitively: site locks include the caller's own inherited set). A
    method only ever invoked under the lock is as guarded as a ``with``
    block — this is what lets ``pump() -> _note_progress()`` pass CC701.
    Functions with no resolved call sites (public API, thread entries) get
    the empty set."""
    edges = index._all_edges()
    # call sites from ANY module participate in the intersection (their
    # lexical locks count), but inherited sets only chain through THIS
    # module's functions — a foreign caller's own inherited discipline is
    # not assumed on its behalf
    my_keys = {graph.node_key(q): q for q in graph.functions}
    callers: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}
    for src_key, sites in edges.items():
        for cs in sites:
            if cs.target in my_keys:
                callers.setdefault(cs.target, []).append((src_key, cs.locks_held))
    entry_keys = {
        g.node_key(q)
        for g in index.modules()
        for q, _k, _ln in g.thread_entries
    }
    effective: Dict[str, FrozenSet[str]] = {k: frozenset() for k in my_keys.values()}
    for _ in range(4):  # fixpoint over short call chains
        changed = False
        for key, qual in my_keys.items():
            if key in entry_keys:
                continue  # a thread entry runs with nothing held
            sites = callers.get(key)
            if not sites:
                continue
            acc: Optional[Set[str]] = None
            for src_key, locks in sites:
                src_qual = my_keys.get(src_key)
                inherited = effective.get(src_qual, frozenset()) if src_qual else frozenset()
                site_locks = set(locks) | set(inherited)
                acc = site_locks if acc is None else (acc & site_locks)
            new = frozenset(acc or set())
            if new != effective[qual]:
                effective[qual] = new
                changed = True
        if not changed:
            break
    return effective
