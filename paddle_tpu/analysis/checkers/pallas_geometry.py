"""PG — Pallas kernel geometry checker.

Consumes the abstract-evaluation reports of
:mod:`paddle_tpu.analysis.kernel_geometry` (memoized per module in the
run's ``PackageIndex``): every ``pl.pallas_call`` site reduced to its grid,
BlockSpecs, index-map arities, operand ranks/dims/dtypes, scalar-prefetch
arity and per-grid-step VMEM footprint, with block sizes and grid extents
resolved through module constants, ``functools.partial`` bindings,
enclosing-call-site parameters and autotune candidate tuples.

A mis-ranked index map or an over-budget block config otherwise only
surfaces as a cryptic Mosaic lowering error (or a silent clamp) at first
dispatch on TPU hardware this project rarely gets to touch; these checks
fail the same geometry at lint time.

Codes:

- PG901  BlockSpec rank discipline — block-shape length, index-map return
         arity, operand rank, and out_shape/out_specs structure must agree,
         and the kernel signature must take one ref per in/out/scratch
- PG902  in-bounds proof — an index-map window provably escapes its operand
         at a grid corner; an intentional clamp must be named via
         ``# analysis: disable=PG902 <reason>``.  Symbolic-residue axes are
         reported ``unproven`` in the geometry API, never silently passed —
         but only concrete overruns become findings
- PG903  per-grid-step VMEM window footprint (ins + outs + scratch, every
         resolvable configuration incl. autotune candidates) exceeds the
         per-target budget (``--vmem-budget``, default 16 MiB/core)
- PG904  scalar-prefetch discipline — ``PrefetchScalarGridSpec`` arg counts
         vs kernel signature positions; prefetch refs indexed only by
         grid-derived values
- PG905  fallback lockstep — a ``pallas_enabled``-gated dispatch without a
         counted ``warn_fallback`` degradation path, or a public kernel
         entry in ``kernels/`` no fallback-wrapped caller covers (the
         contract PRs 4/16 established by hand)
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Set

from paddle_tpu.analysis.checkers._shared import attr_chain
from paddle_tpu.analysis.core import Checker, FileContext, Violation
from paddle_tpu.analysis.kernel_geometry import (
    ModuleGeometry,
    SiteEval,
    evaluate_module,
)

# calls a gate predicate may make and still count as trivial (no dispatch)
_PREDICATE_CALLS = {
    "pallas_enabled", "bool", "int", "len", "isinstance", "getattr",
    "hasattr", "min", "max",
}

_DEFAULT_VMEM_BUDGET = 16 * 1024 * 1024  # bytes per core, v4/v5 class


def _simple_call_names(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain:
                out.add(chain.split(".")[-1])
    return out


class PallasGeometryChecker(Checker):
    name = "pallas_geometry"
    codes = {
        "PG901": "BlockSpec rank discipline: block shape, index-map arity, "
                 "operand rank and out_shape/out_specs must agree",
        "PG902": "index-map window provably escapes the operand at a grid "
                 "corner (name intentional clamps via a reasoned suppression)",
        "PG903": "per-grid-step VMEM window footprint exceeds the per-target "
                 "budget",
        "PG904": "scalar-prefetch discipline: PrefetchScalarGridSpec arity vs "
                 "kernel signature; prefetch refs indexed by non-grid values",
        "PG905": "Pallas kernel without XLA fallback lockstep (gated dispatch "
                 "or public kernel entry lacking warn_fallback coverage)",
    }

    # overridable per-run (CLI --vmem-budget); attribute so all_checkers()'s
    # no-arg construction stays valid
    vmem_budget: int = _DEFAULT_VMEM_BUDGET

    def run(self, ctx: FileContext) -> List[Violation]:
        out: List[Violation] = []
        geom = self._geometry(ctx)
        for site in geom.sites:
            out.extend(self._check_arity(ctx, site))
            out.extend(self._check_bounds(ctx, site))
            out.extend(self._check_vmem(ctx, site))
            out.extend(self._check_prefetch(ctx, site))
        out.extend(self._check_fallback(ctx))
        return out

    # -- report acquisition ----------------------------------------------------
    def _geometry(self, ctx: FileContext) -> ModuleGeometry:
        index = ctx.project.index
        if index is not None:
            try:
                return index.kernel_geometry(ctx.path, ctx.tree)
            except KeyError:
                pass
        return evaluate_module(ctx.path, ctx.tree, index)

    def _v(self, ctx, code, lineno, msg) -> Violation:
        return Violation(
            path=ctx.path, line=max(1, lineno), col=0, code=code, message=msg
        )

    # -- PG901 (+ arity halves of PG904) ---------------------------------------
    def _check_arity(self, ctx: FileContext, site: SiteEval) -> List[Violation]:
        out: List[Violation] = []
        arity_code = "PG904" if site.prefetch_grid_spec else "PG901"
        for spec in site.in_specs + site.out_specs:
            where = f"{spec.kind}_spec[{spec.index}] of {site.kernel_name}"
            if spec.block_shape is not None and spec.ret_arity is not None:
                if len(spec.block_shape) != spec.ret_arity:
                    out.append(self._v(
                        ctx, "PG901", spec.lineno,
                        f"{where}: block shape has {len(spec.block_shape)} dims "
                        f"but its index map returns {spec.ret_arity}",
                    ))
                    continue
            if (
                spec.block_shape is not None
                and spec.operand_rank is not None
                and len(spec.block_shape) != spec.operand_rank
            ):
                out.append(self._v(
                    ctx, "PG901", spec.lineno,
                    f"{where}: block shape has {len(spec.block_shape)} dims but "
                    f"the operand has rank {spec.operand_rank}",
                ))
            if (
                spec.index_map is not None
                and site.grid_len is not None
                and spec.map_params
            ):
                expected = site.grid_len + site.num_scalar_prefetch
                if len(spec.map_params) != expected:
                    out.append(self._v(
                        ctx, arity_code, spec.lineno,
                        f"{where}: index map takes {len(spec.map_params)} "
                        f"args but grid rank {site.grid_len}"
                        + (
                            f" + {site.num_scalar_prefetch} scalar-prefetch"
                            if site.num_scalar_prefetch
                            else ""
                        )
                        + f" = {expected}",
                    ))
        if (
            site.out_specs_declared
            and site.n_out_shapes is not None
            and len(site.out_specs) != site.n_out_shapes
        ):
            out.append(self._v(
                ctx, "PG901", site.lineno,
                f"{site.kernel_name}: {len(site.out_specs)} out_specs but "
                f"{site.n_out_shapes} out_shape entries",
            ))
        if (
            site.kernel_params is not None
            and not site.has_vararg
            and site.in_specs
            and (site.out_specs_declared or site.n_out_shapes is not None)
        ):
            n_out = (
                len(site.out_specs)
                if site.out_specs_declared
                else (site.n_out_shapes or 0)
            )
            expected = (
                site.num_scalar_prefetch
                + len(site.in_specs)
                + n_out
                + site.n_scratch
            )
            if len(site.kernel_params) != expected:
                out.append(self._v(
                    ctx, arity_code, site.lineno,
                    f"kernel {site.kernel_name} takes {len(site.kernel_params)} "
                    f"refs but the call wires {expected} "
                    f"({site.num_scalar_prefetch} prefetch + "
                    f"{len(site.in_specs)} in + {n_out} out + "
                    f"{site.n_scratch} scratch)",
                ))
        return out

    # -- PG902 -----------------------------------------------------------------
    def _check_bounds(self, ctx: FileContext, site: SiteEval) -> List[Violation]:
        out: List[Violation] = []
        for proof in site.axis_proofs:
            if proof.status == "overrun":
                out.append(self._v(
                    ctx, "PG902", proof.lineno or site.lineno,
                    f"{site.kernel_name}: {proof.detail}",
                ))
        return out

    # -- PG903 -----------------------------------------------------------------
    def _check_vmem(self, ctx: FileContext, site: SiteEval) -> List[Violation]:
        out: List[Violation] = []
        budget = int(self.vmem_budget)
        seen: Set[str] = set()
        for cfg in site.vmem_configs:
            b = cfg.bytes_per_step
            if not b.known:
                continue
            worst = min(b.values)  # every resolvable value must exceed
            if worst <= budget:
                continue
            binding = ", ".join(f"{k}={v}" for k, v in sorted(cfg.binding.items()))
            key = f"{worst}:{binding}"
            if key in seen:
                continue
            seen.add(key)
            out.append(self._v(
                ctx, "PG903", site.lineno,
                f"{site.kernel_name}: per-grid-step VMEM window is "
                f">= {worst} bytes (budget {budget})"
                + (f" under config {binding}" if binding else "")
                + (" [element widths partly assumed 1 byte]" if cfg.assumed_width else ""),
            ))
        return out

    # -- PG904 (indexing half) -------------------------------------------------
    def _check_prefetch(self, ctx: FileContext, site: SiteEval) -> List[Violation]:
        return [
            self._v(ctx, "PG904", lineno, f"{site.kernel_name}: {detail}")
            for lineno, detail in site.prefetch_indexing
        ]

    # -- PG905 -----------------------------------------------------------------
    def _check_fallback(self, ctx: FileContext) -> List[Violation]:
        out: List[Violation] = []
        # (a) gated dispatch without a counted degradation path, any module
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            names = _simple_call_names(node)
            if "pallas_enabled" not in names or "warn_fallback" in names:
                continue
            if not (names - _PREDICATE_CALLS):
                continue  # trivial gate predicate (returns a bool, no dispatch)
            out.append(self._v(
                ctx, "PG905", node.lineno,
                f"{node.name} gates on pallas_enabled but never registers the "
                f"XLA degradation via warn_fallback (fallback counter contract)",
            ))
        # (b) public kernel entries in kernels/ need a fallback-wrapped caller
        if "kernels" in Path(ctx.path).parts:
            out.extend(self._check_kernel_coverage(ctx))
        return out

    def _check_kernel_coverage(self, ctx: FileContext) -> List[Violation]:
        out: List[Violation] = []
        index = ctx.project.index
        covered: Set[str] = index.fallback_covered() if index is not None else set()
        # module-local transitive pallas_call lowering
        local_defs: Dict[str, ast.AST] = {}
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local_defs[node.name] = node
        lowers_direct = {
            name
            for name, fn in local_defs.items()
            if any(
                isinstance(c, ast.Call)
                and (attr_chain(c.func) or "").endswith("pallas_call")
                for c in ast.walk(fn)
            )
        }

        def lowers(name: str, seen: Set[str]) -> bool:
            if name in lowers_direct:
                return True
            if name in seen or name not in local_defs:
                return False
            seen.add(name)
            return any(
                lowers(n, seen)
                for n in _simple_call_names(local_defs[name])
                if n in local_defs
            )

        for name, fn in local_defs.items():
            if name.startswith("_") or not lowers(name, set()):
                continue
            if "warn_fallback" in _simple_call_names(fn):
                continue  # self-gating entry (counts its own degradation)
            if name in covered:
                continue
            out.append(self._v(
                ctx, "PG905", fn.lineno,
                f"public Pallas kernel entry {name} has no fallback-wrapped "
                f"caller (no warn_fallback coverage anywhere in the package) "
                f"— register an XLA fallback in lockstep",
            ))
        return out
