"""DN — donation / buffer-lifetime checker.

The two worst bugs previous PRs shipped-then-caught were buffer-lifetime
races invisible to single-line pattern matching: the PR 6 recovery-replay
race (host numpy vectors mutated while an async dispatch still zero-copy
aliased them) and donated-state hazards around the engine's jitted entry
points. This checker walks each function as an ordered statement sequence
(branch bodies merge their taints — path-insensitive but order-aware,
see :class:`_FunctionScan`) over facts from :mod:`..dataflow`:

- **jit wrappers** resolved through the assignment idiom ``inference/
  engine.py`` uses — ``self._fn = jax.jit(impl, donate_argnums=(1,))`` (the
  conditional ``(1,) if donate else ()`` form resolves too), plus local
  ``g = jax.jit(f, donate_argnums=...)`` bindings;
- **host buffers**: names/fields assigned from ``np.*`` constructors —
  jax's CPU backend zero-copies these into device arrays, so they stay
  aliased until a sync.

**DN801 use-after-donate** — a value passed at a donated position of a jit
wrapper is dead after the call: reading or mutating it is a
use-after-free on donating backends (TPU). The safe idiom rebinds in the
same statement (``tok, self._caches = self._prefill_fn(..., self._caches,
...)``) and is never flagged; any later read/mutation of a still-donated
key before a rebind is.

**DN802 mutate-before-sync** — the exact PR 6 replay-race class: a host
numpy buffer handed to a jit dispatch (directly or via ``jnp.asarray(buf)``
— no ``.copy()``) and then mutated (``buf[i] = ...``, ``buf += ...``,
``.fill()``) before a sync point. Sync points: ``int()``/``float()``/
``bool()`` of a result, ``np.asarray(result)``, ``jax.block_until_ready``
or ``.block_until_ready()``/``.item()``. ``jnp.asarray(buf.copy())``
snapshots and is safe — exactly the PR 6 fix shape in ``engine.recover``.

**DN803 record-before-commit** — the PR 2 lesson: when a donating dispatch
did NOT rebind its donated argument in the same statement, the old state is
dead and the replacement lives only in result temps; a watchdog/metrics
record (``record_compile``, ``record_event``, ``.inc()``/``.observe()``)
sequenced between the dispatch and the ``self.<state> = temp`` commit means
a warning escalated to an error (warnings-as-errors) discards committed
donated state — record AFTER the commit.

- DN801  read/mutation of a value after it was donated to a jit dispatch
- DN802  host numpy buffer mutated after dispatch before a sync point
- DN803  watchdog/metrics record between a donating dispatch and its
         donated-state commit
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from paddle_tpu.analysis.checkers._shared import attr_chain
from paddle_tpu.analysis.core import Checker, FileContext, Violation
from paddle_tpu.analysis.dataflow import (
    _NUMPY_CTORS,
    FunctionInfo,
    JitWrapper,
    ModuleGraph,
    receiver_key,
)

_SYNC_NAMES = {"int", "float", "bool"}
_SYNC_METHODS = {"block_until_ready", "item", "tolist"}
_NP_MUTATORS = {"fill", "sort", "put", "resize", "setfield", "partition"}
_RECORD_ATTRS = {"record_compile", "record_event", "inc", "observe"}


class _Taint:
    __slots__ = ("line", "wrapper")

    def __init__(self, line: int, wrapper: str) -> None:
        self.line = line
        self.wrapper = wrapper


class DonationChecker(Checker):
    name = "donation-lifetime"
    codes = {
        "DN801": "value read or mutated after being passed at a "
                 "donate_argnums position of a jit dispatch (use-after-free "
                 "on donating backends) — rebind it from the call's result",
        "DN802": "host numpy buffer mutated after a jit dispatch aliased it "
                 "and before any sync point (the recovery-replay race class) "
                 "— snapshot with .copy() or sync first",
        "DN803": "watchdog/metrics record sequenced between a donating "
                 "dispatch and its donated-state commit — an escalated "
                 "warning here discards committed donated state",
    }

    def run(self, ctx: FileContext) -> List[Violation]:
        index = ctx.project.dataflow()
        graph = index.module(ctx.path)
        if graph is None:
            graph = index.add_module(ctx.path, ctx.tree)
        out: List[Violation] = []
        for qual, finfo in graph.functions.items():
            scan = _FunctionScan(ctx, graph, finfo)
            out.extend(scan.run())
        return out


class _FunctionScan:
    """Order-aware walk of one function body. Branches (if/else, except
    handlers) are scanned from a snapshot of the incoming state and merged
    by taint union afterwards, so a donate in the `if` arm taints the code
    after the branch but not the sibling arm."""

    def __init__(self, ctx: FileContext, graph: ModuleGraph, finfo: FunctionInfo) -> None:
        self.ctx = ctx
        self.graph = graph
        self.finfo = finfo
        self.violations: List[Violation] = []
        # receiver key -> wrapper (module-level self-attr wrappers + locals)
        self.wrappers: Dict[str, JitWrapper] = {}
        for (cls_name, key), w in graph.jit_wrappers.items():
            if cls_name is None or cls_name == finfo.class_name:
                self.wrappers[key] = w
        # host numpy buffers: class fields of numpy kind + locals (assigned
        # np.* in this function)
        self.np_bufs: Set[str] = set()
        if finfo.class_name:
            cinfo = graph.classes.get(finfo.class_name)
            if cinfo:
                self.np_bufs |= {
                    f"self.{f}" for f, k in cinfo.field_kinds.items() if k == "numpy"
                }
        # temp key -> host buffer keys it zero-copy aliases
        self.aliases: Dict[str, Set[str]] = {}
        # donated taints / in-flight aliased buffers / pending commits
        self.donated: Dict[str, _Taint] = {}
        self.inflight: Dict[str, int] = {}  # buffer key -> dispatch line
        # donated key -> (result temps, record call nodes seen since)
        self.pending: Dict[str, Tuple[Set[str], List[ast.Call]]] = {}
        # nodes inside a dispatch call expression: the donated argument's own
        # appearance in the call must not read-flag against its fresh taint
        self._exempt: Set[int] = set()

    # -- state management -----------------------------------------------------
    def _snapshot(self):
        return (
            dict(self.donated), dict(self.inflight),
            {k: (set(t), list(r)) for k, (t, r) in self.pending.items()},
            {k: set(v) for k, v in self.aliases.items()}, set(self.np_bufs),
            dict(self.wrappers),
        )

    def _restore(self, snap) -> None:
        donated, inflight, pending, aliases, np_bufs, wrappers = snap
        self.donated = dict(donated)
        self.inflight = dict(inflight)
        self.pending = {k: (set(t), list(r)) for k, (t, r) in pending.items()}
        self.aliases = {k: set(v) for k, v in aliases.items()}
        self.np_bufs = set(np_bufs)
        self.wrappers = dict(wrappers)

    def _merge(self, other) -> None:
        donated, inflight, pending, aliases, np_bufs, wrappers = other
        self.donated.update(donated)
        self.inflight.update(inflight)
        for k, (t, r) in pending.items():
            mine = self.pending.setdefault(k, (set(), []))
            mine[0].update(t)
            mine[1].extend(r)
        for k, v in aliases.items():
            self.aliases.setdefault(k, set()).update(v)
        self.np_bufs |= np_bufs
        self.wrappers.update(wrappers)

    # -- driver ---------------------------------------------------------------
    def run(self) -> List[Violation]:
        body = getattr(self.finfo.node, "body", [])
        self._scan_block(body)
        return self.violations

    def _scan_block(self, stmts: Sequence[ast.stmt]) -> None:
        for stmt in stmts:
            self._scan_stmt(stmt)

    def _scan_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # nested defs are scanned as their own FunctionInfo
        if isinstance(stmt, ast.If):
            self._scan_value(stmt.test)
            snap = self._snapshot()
            self._scan_block(stmt.body)
            after_body = self._snapshot()
            self._restore(snap)
            self._scan_block(stmt.orelse)
            self._merge(after_body)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._scan_value(stmt.iter)
            self._scan_block(stmt.body)
            self._scan_block(stmt.orelse)
            return
        if isinstance(stmt, ast.While):
            self._scan_value(stmt.test)
            self._scan_block(stmt.body)
            self._scan_block(stmt.orelse)
            return
        if isinstance(stmt, ast.Try):
            self._scan_block(stmt.body)
            after_body = self._snapshot()
            for h in stmt.handlers:
                self._restore(after_body)
                self._scan_block(h.body)
            self._restore(after_body)
            self._scan_block(stmt.orelse)
            self._scan_block(stmt.finalbody)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan_value(item.context_expr)
            self._scan_block(stmt.body)
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            value = stmt.value
            targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            if value is not None:
                self._scan_value(value, assign_targets=targets)
            self._apply_bindings(targets, value, stmt)
            return
        if isinstance(stmt, ast.AugAssign):
            self._scan_value(stmt.value)
            self._check_mutation_target(stmt.target)
            return
        if isinstance(stmt, ast.Expr):
            self._scan_value(stmt.value)
            return
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            self._scan_value(stmt.value)
            return
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                key = receiver_key(t)
                if key:
                    self._kill(key)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._scan_value(child)

    # -- expression scan ------------------------------------------------------
    def _scan_value(
        self, expr: ast.expr, assign_targets: Optional[Sequence[ast.expr]] = None
    ) -> None:
        """Scan one expression in evaluation position: flag donated reads,
        process dispatch/sync/record calls."""
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._handle_call(node, assign_targets)
            elif isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                getattr(node, "ctx", None), ast.Load
            ):
                key = receiver_key(node)
                if key in self.donated and id(node) not in self._exempt:
                    t = self.donated[key]
                    self._flag(
                        node, "DN801",
                        f"'{key}' was donated to {t.wrapper} on line {t.line} "
                        "and is read here before being rebound: on a donating "
                        "backend this buffer no longer exists",
                    )
                    # one report per taint: further reads of the same key
                    # would repeat the same finding
                    del self.donated[key]
            # mutation shapes inside expressions: buf.fill(...), buf.sort()
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                if node.func.attr in _NP_MUTATORS:
                    key = receiver_key(node.func.value)
                    if key is not None:
                        self._check_mutated_key(key, node)

    # -- calls ----------------------------------------------------------------
    def _handle_call(
        self, node: ast.Call, assign_targets: Optional[Sequence[ast.expr]]
    ) -> None:
        fn = node.func
        chain = attr_chain(fn)
        # local jit wrapper binding handled in _apply_bindings; here: sync,
        # record, jnp.asarray aliasing, dispatch
        if isinstance(fn, ast.Name) and fn.id in _SYNC_NAMES and node.args:
            self._sync()
            return
        if chain in ("jax.block_until_ready", "np.asarray", "numpy.asarray"):
            self._sync()
            return
        if isinstance(fn, ast.Attribute) and fn.attr in _SYNC_METHODS:
            self._sync()
            return
        if isinstance(fn, ast.Attribute) and fn.attr in _RECORD_ATTRS:
            for key, (_temps, records) in self.pending.items():
                records.append(node)
        callee_key = receiver_key(fn)
        wrapper = self.wrappers.get(callee_key) if callee_key else None
        if wrapper is not None:
            self._handle_dispatch(node, wrapper, callee_key, assign_targets)

    def _handle_dispatch(
        self,
        node: ast.Call,
        wrapper: JitWrapper,
        callee_key: str,
        assign_targets: Optional[Sequence[ast.expr]],
    ) -> None:
        target_keys: Set[str] = set()
        if assign_targets:
            for t in assign_targets:
                if isinstance(t, (ast.Tuple, ast.List)):
                    target_keys |= {k for k in map(receiver_key, t.elts) if k}
                else:
                    k = receiver_key(t)
                    if k:
                        target_keys.add(k)
        # the call expression's own nodes never read-flag their fresh taints
        self._exempt.update(id(n) for n in ast.walk(node))
        # donated positions -> taint unless rebound by this very statement
        for pos in wrapper.donated:
            if pos >= len(node.args):
                continue
            key = receiver_key(node.args[pos])
            if key is None:
                continue
            if key in target_keys:
                continue  # donate-and-rebind: the replacement lands now
            self.donated[key] = _Taint(node.lineno, callee_key)
            self.pending[key] = (set(target_keys), [])
        # every argument that zero-copy aliases a host numpy buffer is in
        # flight until a sync point
        for arg in node.args:
            for buf in self._aliased_buffers(arg):
                self.inflight[buf] = node.lineno

    def _aliased_buffers(self, arg: ast.expr) -> Set[str]:
        key = receiver_key(arg)
        if key is not None:
            if key in self.np_bufs:
                return {key}
            return set(self.aliases.get(key, ()))
        if isinstance(arg, ast.Call):
            chain = attr_chain(arg.func)
            if chain in ("jnp.asarray", "jax.numpy.asarray") and arg.args:
                inner = arg.args[0]
                ikey = receiver_key(inner)
                if ikey is not None:
                    if ikey in self.np_bufs:
                        return {ikey}
                    return set(self.aliases.get(ikey, ()))
                # jnp.asarray(buf.copy()) snapshots: nothing aliased
        return set()

    def _sync(self) -> None:
        self.inflight.clear()

    # -- bindings and mutations ------------------------------------------------
    def _apply_bindings(
        self,
        targets: Sequence[ast.expr],
        value: Optional[ast.expr],
        stmt: ast.stmt,
    ) -> None:
        # DN803 commit detection BEFORE the kill: self.<attr> = <temp of a
        # pending donation> closes the window; records seen inside it fire
        if value is not None:
            vkey = receiver_key(value)
            if vkey is not None:
                for key, (temps, records) in list(self.pending.items()):
                    if vkey in temps and any(
                        (receiver_key(t) or "").startswith("self.") or receiver_key(t) == key
                        for t in targets
                    ):
                        for rec in records:
                            self._flag(
                                rec, "DN803",
                                "watchdog/metrics record sequenced between the "
                                f"donating dispatch (line {self.donated[key].line if key in self.donated else '?'})"
                                f" and the commit of its replacement state "
                                f"'{key}': a RecompileBudgetWarning escalated "
                                "under warnings-as-errors here would discard "
                                "committed donated state — record after the "
                                "commit",
                            )
                        del self.pending[key]
        for t in targets:
            # subscript store on a tracked buffer is a mutation, not a rebind
            if isinstance(t, ast.Subscript):
                base = receiver_key(t.value)
                if base is not None:
                    self._check_mutated_key(base, t)
                continue
            if isinstance(t, (ast.Tuple, ast.List)):
                for el in t.elts:
                    self._apply_bindings([el], None, stmt)
                continue
            key = receiver_key(t)
            if key is None:
                continue
            self._kill(key)
            if value is None:
                continue
            # classify the new binding
            if isinstance(value, ast.Call):
                wrapper = self._match_local_jit(value)
                if wrapper is not None:
                    self.wrappers[key] = wrapper
                    continue
                chain = attr_chain(value.func) or ""
                root, _, ctor = chain.rpartition(".")
                if root in ("np", "numpy") and ctor in _NUMPY_CTORS:
                    self.np_bufs.add(key)
                    continue
                if chain in ("jnp.asarray", "jax.numpy.asarray") and value.args:
                    bufs = self._aliased_buffers(value)
                    if bufs:
                        self.aliases[key] = bufs
                    continue
                if isinstance(value.func, ast.Attribute) and value.func.attr == "copy":
                    base = receiver_key(value.func.value)
                    if base in self.np_bufs:
                        self.np_bufs.add(key)  # a fresh buffer, not an alias
                    continue

    def _match_local_jit(self, value: ast.Call) -> Optional[JitWrapper]:
        chain = attr_chain(value.func)
        if chain not in ("jax.jit", "jit"):
            return None
        donated: Set[int] = set()
        for kw in value.keywords:
            if kw.arg == "donate_argnums":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value, int) \
                            and not isinstance(n.value, bool):
                        donated.add(n.value)
        return JitWrapper(key="<local>", target=None, donated=frozenset(donated),
                          lineno=value.lineno)

    def _kill(self, key: str) -> None:
        self.donated.pop(key, None)
        self.inflight.pop(key, None)
        self.aliases.pop(key, None)
        self.np_bufs.discard(key)
        # any rebind of the donated key closes its commit window silently
        self.pending.pop(key, None)
        # a rebound name is no longer the jit wrapper it once was (the
        # _apply_bindings classifier re-adds it if the new value is jax.jit)
        self.wrappers.pop(key, None)

    def _check_mutation_target(self, target: ast.expr) -> None:
        if isinstance(target, ast.Subscript):
            base = receiver_key(target.value)
            if base is not None:
                self._check_mutated_key(base, target)
        else:
            key = receiver_key(target)
            if key is not None:
                self._check_mutated_key(key, target)

    def _check_mutated_key(self, key: str, node: ast.AST) -> None:
        if key in self.donated:
            t = self.donated.pop(key)
            self._flag(
                node, "DN801",
                f"'{key}' was donated to {t.wrapper} on line {t.line} and is "
                "mutated here before being rebound: on a donating backend "
                "this buffer no longer exists",
            )
            return
        if key in self.inflight:
            line = self.inflight.pop(key)
            self._flag(
                node, "DN802",
                f"host buffer '{key}' was handed to the jit dispatch on line "
                f"{line} and is mutated here with no sync point between: "
                "jax zero-copies numpy inputs, so the async dispatch still "
                "reads this memory — snapshot with .copy() at the call, or "
                "sync (int()/np.asarray()/block_until_ready) first",
            )

    def _flag(self, node: ast.AST, code: str, message: str) -> None:
        self.violations.append(
            Violation(
                self.ctx.path, getattr(node, "lineno", 1),
                getattr(node, "col_offset", 0), code, message,
            )
        )
