"""TS — trace-safety checker.

A function whose body is captured by tracing (``@to_static`` / ``@jax.jit``
decorated, or passed to ``jax.jit(...)`` / ``to_static(...)``) executes its
Python exactly once per compile, not once per call. Host side effects inside
such a body are therefore bugs of the recorded-at-trace-time class this
codebase has already been bitten by (PR 2's "record compiles only after the
trace succeeds" rule): they fire on compiles, not calls, and silently stop
firing when the compile cache hits.

Codes:

- TS101  ``print(...)`` inside a traced function
- TS102  ``time.*`` call inside a traced function
- TS103  ``os.environ`` / ``os.getenv`` access inside a traced function
- TS104  metrics-registry / recompile-watchdog call inside a traced function
- TS105  ``float()/int()/bool()/.item()/.numpy()/.tolist()`` on a traced
         function's parameter (forces device sync / breaks the trace)
- TS106  ``global`` declaration inside a traced function (trace-time global
         mutation)

Functions handed to ``shard_map(...)`` / ``pjit(...)`` are traced bodies
too (the tensor-parallel engine's per-shard collective seams): a flag read,
metrics call or print inside one fires per compile of the PARTITIONED
program — same recorded-at-trace-time bug class, now multiplied across the
mesh — so the same codes cover them.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from paddle_tpu.analysis.checkers._shared import (
    OBSERVABILITY_CALLS,
    OBSERVABILITY_ROOTS,
    attr_chain,
    attr_root,
    body_walk,
    func_params,
    is_os_environ,
)
from paddle_tpu.analysis.core import Checker, FileContext, Violation

_JIT_CHAINS = {
    "jax.jit", "to_static", "jit.to_static", "paddle_tpu.jit.to_static",
    # partitioned-program entry points: the callable handed to shard_map /
    # pjit is a traced body executed once per compile of the SPMD program
    # (all spellings — the repo itself prefers the modern jax.shard_map)
    "shard_map", "jax.shard_map", "jax.experimental.shard_map.shard_map",
    "pjit", "jax.pjit", "jax.experimental.pjit.pjit",
}
_SYNC_ATTRS = {"item", "numpy", "tolist"}


def _is_jit_decorator(dec: ast.AST) -> bool:
    chain = attr_chain(dec.func if isinstance(dec, ast.Call) else dec)
    return chain in _JIT_CHAINS


class _TracedFunctions(ast.NodeVisitor):
    """Collect every function whose body runs under trace: decorated defs,
    plus defs/lambdas/methods handed to ``jax.jit`` / ``to_static`` calls."""

    def __init__(self) -> None:
        self.by_name: Dict[str, List[ast.AST]] = {}
        self.methods: Dict[str, List[ast.AST]] = {}
        self.traced: Dict[int, ast.AST] = {}
        self._pending_names: Set[str] = set()
        self._pending_methods: Set[str] = set()

    def _record_def(self, node: ast.AST) -> None:
        self.by_name.setdefault(node.name, []).append(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._record_def(node)
        if any(_is_jit_decorator(d) for d in node.decorator_list):
            self.traced[id(node)] = node
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods.setdefault(item.name, []).append(item)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        chain = attr_chain(node.func)
        if chain in _JIT_CHAINS and node.args:
            target = node.args[0]
            if isinstance(target, ast.Lambda):
                self.traced[id(target)] = target
            elif isinstance(target, ast.Name):
                self._pending_names.add(target.id)
            elif isinstance(target, ast.Attribute) and isinstance(target.value, ast.Name):
                if target.value.id == "self":
                    self._pending_methods.add(target.attr)
        self.generic_visit(node)

    def resolve(self, tree: ast.Module) -> List[ast.AST]:
        self.visit(tree)
        for name in self._pending_names:
            for fn in self.by_name.get(name, ()):
                self.traced[id(fn)] = fn
        for name in self._pending_methods:
            for fn in self.methods.get(name, ()):
                self.traced[id(fn)] = fn
        return list(self.traced.values())


class TraceSafetyChecker(Checker):
    name = "trace-safety"
    codes = {
        "TS101": "print() inside a traced function",
        "TS102": "time.* call inside a traced function",
        "TS103": "os.environ access inside a traced function",
        "TS104": "metrics/watchdog call inside a traced function",
        "TS105": "host materialization of a traced function's parameter",
        "TS106": "global declaration inside a traced function",
    }

    def run(self, ctx: FileContext) -> List[Violation]:
        out: List[Violation] = []
        for fn in _TracedFunctions().resolve(ctx.tree):
            label = getattr(fn, "name", "<lambda>")
            params = func_params(fn)
            for node in body_walk(fn):
                v = self._check_node(node, params, label)
                if v is not None:
                    code, msg = v
                    out.append(
                        Violation(ctx.path, node.lineno, node.col_offset, code, msg)
                    )
        return out

    def _check_node(self, node: ast.AST, params: Set[str], label: str):
        where = f"in traced function '{label}'"
        if isinstance(node, ast.Global):
            return "TS106", f"global declaration {where}: trace-time global mutation"
        if is_os_environ(node) and not isinstance(node, ast.Call):
            return "TS103", f"os.environ access {where}: read once at trace, then baked"
        if not isinstance(node, ast.Call):
            return None
        chain = attr_chain(node.func)
        root = attr_root(node.func)
        if chain == "print" or (isinstance(node.func, ast.Name) and node.func.id == "print"):
            return "TS101", f"print() {where}: fires per compile, not per call"
        if root == "time" and isinstance(node.func, ast.Attribute):
            return "TS102", f"{chain}() {where}: measures trace time, not run time"
        if chain in ("os.getenv", "os.putenv"):
            return "TS103", f"{chain}() {where}: read once at trace, then baked"
        if root in OBSERVABILITY_ROOTS or (
            isinstance(node.func, ast.Name) and node.func.id in OBSERVABILITY_CALLS
        ):
            return "TS104", (
                f"observability call {where}: record at the jit call site, "
                "after the trace succeeds"
            )
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in ("float", "int", "bool")
            and len(node.args) == 1
            and isinstance(node.args[0], ast.Name)
            and node.args[0].id in params
        ):
            return "TS105", (
                f"{node.func.id}() on parameter '{node.args[0].id}' {where}: "
                "concretizes a tracer"
            )
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _SYNC_ATTRS
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in params
        ):
            return "TS105", (
                f".{node.func.attr}() on parameter '{node.func.value.id}' {where}: "
                "concretizes a tracer"
            )
        return None
