"""EH — exception hygiene checker.

Broad exception handlers are how a framework converts hard faults into
silent wrong answers (a Pallas lowering error swallowed into a "fallback"
that never fires again, a store outage read as "worker healthy"). The rules:

- EH401  bare ``except:`` — never allowed (it also catches KeyboardInterrupt
         and SystemExit);
- EH402  ``except Exception:`` (or BaseException, or a tuple containing one)
         whose body is only ``pass``/``...`` — a silent swallower; either
         handle/log it or suppress with a stated reason;
- EH403  broad ``except`` with no reason comment — every broad catch must
         state why breadth is correct, either on the handler line itself or
         as a comment-only line opening the handler body (both idioms are
         established in this codebase).
"""

from __future__ import annotations

import ast
import re
from typing import List

from paddle_tpu.analysis.core import Checker, FileContext, Violation

_BROAD = {"Exception", "BaseException"}

# lint-silencing tags are not reasons: a comment consisting only of these
# carries no information about WHY breadth is correct
_TAG_RES = (
    re.compile(r"noqa(?::\s*[A-Z]+\d*(?:\s*,\s*[A-Z]+\d*)*)?"),
    re.compile(r"type:\s*ignore(?:\[[^\]]*\])?"),
    re.compile(r"pragma:\s*no\s*cover"),
    re.compile(r"analysis:\s*disable=[A-Z0-9, ]+"),
)


def _states_reason(line: str) -> bool:
    if "#" not in line:
        return False
    comment = line.split("#", 1)[1].replace("#", " ")
    for tag in _TAG_RES:
        comment = tag.sub(" ", comment)
    return bool(re.search(r"[A-Za-z]", comment))


def _is_broad(type_node: ast.AST) -> bool:
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(e) for e in type_node.elts)
    return False


def _is_silent(body: List[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if (
            isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis
        ):
            continue
        return False
    return True


class ExceptionHygieneChecker(Checker):
    name = "exception-hygiene"
    codes = {
        "EH401": "bare except",
        "EH402": "broad except silently swallowing (body is only pass)",
        "EH403": "broad except without a reason comment (handler line or body-opening comment)",
    }

    def run(self, ctx: FileContext) -> List[Violation]:
        out: List[Violation] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            loc = (ctx.path, node.lineno, node.col_offset)
            if node.type is None:
                out.append(
                    Violation(*loc, "EH401",
                              "bare except: catches KeyboardInterrupt/SystemExit; "
                              "name the exception type")
                )
                continue
            if not _is_broad(node.type):
                continue
            if _is_silent(node.body):
                out.append(
                    Violation(*loc, "EH402",
                              "broad except with silent pass body swallows every "
                              "error; handle/log it, narrow the type, or suppress "
                              "with a stated reason")
                )
            elif not self._has_reason_comment(ctx, node):
                out.append(
                    Violation(*loc, "EH403",
                              "broad except without a reason comment; state why "
                              "catching Exception is correct (on this line or a "
                              "comment line opening the body), or narrow the type")
                )
        return out

    def _has_reason_comment(self, ctx: FileContext, node: ast.ExceptHandler) -> bool:
        if _states_reason(ctx.lines[node.lineno - 1]):
            return True
        # comment-only lines between the handler line and its first statement
        first = node.body[0].lineno if node.body else node.lineno + 1
        for idx in range(node.lineno, min(first - 1, len(ctx.lines))):
            stripped = ctx.lines[idx].lstrip()
            if stripped.startswith("#") and _states_reason(stripped):
                return True
        return False
