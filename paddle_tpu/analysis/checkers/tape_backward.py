"""TB — tape backward discipline checker.

The train path differentiates ops through the explicit tape: each fused op
records a GradNode whose ``vjp_fn`` runs a STANDALONE adjoint kernel. Running
``jax.grad``/``jax.vjp``/``jax.value_and_grad`` over a function that lowers a
``pallas_call`` instead asks jax to differentiate through the kernel — Mosaic
kernels carry no AD rule, so this either crashes at trace time or silently
falls back to a transposed program XLA cannot fuse. The sanctioned escape
hatch is ``jax.custom_vjp`` (the kernel pair defines its own rule); functions
protected that way are exempt.

Detection is resolved-name based and deliberately conservative:

1. a function TAINTS if its body calls ``pallas_call`` directly, or calls a
   same-file function that does (one hop — matching how this codebase wraps
   kernels in a single ``*_call`` builder);
2. ``jax.custom_vjp`` protection is honoured as a decorator, as the
   ``core = jax.custom_vjp(fn)`` assignment form (both ``fn`` and ``core``
   become exempt), and for factory functions that wire ``custom_vjp``
   around their nested kernels anywhere in their body;
3. only first arguments that RESOLVE are flagged: a Name bound to a tainted
   def, or a Lambda whose body calls one (or lowers ``pallas_call`` inline).
   A bare parameter passed through generic dispatch is unresolvable by
   design — the tape's own ``jax.vjp(fn, ...)`` over a caller-supplied pure
   function must stay clean.

Codes:

- TB901  jax autodiff applied over a function containing pallas_call
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from paddle_tpu.analysis.checkers._shared import attr_chain, body_walk
from paddle_tpu.analysis.core import Checker, FileContext, Violation

_AD_NAMES = {"grad", "vjp", "value_and_grad"}


def _last(chain: str) -> str:
    return chain.split(".")[-1]


class TapeBackwardChecker(Checker):
    name = "tape-backward"
    codes = {
        "TB901": "jax autodiff applied over a function containing pallas_call",
    }

    def run(self, ctx: FileContext) -> List[Violation]:
        tree = ctx.tree
        contains: Set[str] = set()  # defs lowering pallas_call directly
        calls_of: Dict[str, Set[str]] = {}  # def name -> called Names
        protected: Set[str] = set()  # custom_vjp-protected names

        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                called = calls_of.setdefault(node.name, set())
                for sub in body_walk(node):
                    # a factory that wires custom_vjp around its nested
                    # kernels (decorator or call form) owns its AD rule
                    if _last(attr_chain(sub) or "") == "custom_vjp":
                        protected.add(node.name)
                    if not isinstance(sub, ast.Call):
                        continue
                    chain = attr_chain(sub.func) or ""
                    if _last(chain) == "pallas_call":
                        contains.add(node.name)
                    elif isinstance(sub.func, ast.Name):
                        called.add(sub.func.id)
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    if _last(attr_chain(target) or "") == "custom_vjp":
                        protected.add(node.name)
            elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if _last(attr_chain(node.value.func) or "") == "custom_vjp":
                    for a in node.value.args:
                        if isinstance(a, ast.Name):
                            protected.add(a.id)
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            protected.add(t.id)

        # protection propagates one call hop too: a kernel factory that hands
        # its engines to a custom_vjp-wiring shell is covered by the shell
        for name, called in list(calls_of.items()):
            if called & protected:
                protected.add(name)
        kernels = contains - protected
        tainted = set(kernels)
        for name, called in calls_of.items():
            if called & kernels:
                tainted.add(name)
        tainted -= protected

        # `from jax import grad` aliases count the same as `jax.grad`
        ad_aliases: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module == "jax":
                for alias in node.names:
                    if alias.name in _AD_NAMES:
                        ad_aliases.add(alias.asname or alias.name)

        out: List[Violation] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            chain = attr_chain(node.func) or ""
            parts = chain.split(".")
            is_ad = (
                len(parts) == 2 and parts[0] == "jax" and parts[1] in _AD_NAMES
            ) or (isinstance(node.func, ast.Name) and node.func.id in ad_aliases)
            if not is_ad:
                continue
            hit = self._resolve_target(node.args[0], tainted)
            if hit is not None:
                out.append(
                    Violation(
                        ctx.path, node.lineno, node.col_offset, "TB901",
                        f"{_last(chain)}() over '{hit}' which lowers pallas_call: "
                        "kernels have no AD rule — record a tape GradNode with a "
                        "standalone adjoint kernel (or protect with jax.custom_vjp)",
                    )
                )
        return out

    @staticmethod
    def _resolve_target(target: ast.AST, tainted: Set[str]):
        if isinstance(target, ast.Name) and target.id in tainted:
            return target.id
        if isinstance(target, ast.Lambda):
            for sub in ast.walk(target.body):
                if not isinstance(sub, ast.Call):
                    continue
                chain = attr_chain(sub.func) or ""
                if _last(chain) == "pallas_call":
                    return "<lambda>"
                if isinstance(sub.func, ast.Name) and sub.func.id in tainted:
                    return sub.func.id
        return None
