"""PK — Pallas purity checker.

Kernel bodies and BlockSpec index maps handed to ``pl.pallas_call`` are
staged onto the TPU by Mosaic: any host state they touch is read once at
lowering and frozen into the compiled kernel. The ragged paged-attention
guarantees (zero-cost padding via the clamped index map + ``pl.when`` compute
skip) hold only while these functions stay pure functions of their refs and
grid indices.

Kernel discovery is two-pronged and documented rather than clever:

1. resolved — the first argument of every ``pallas_call(...)`` (a function
   name, a ``functools.partial(kernel, ...)``, possibly through one local
   ``kernel = partial(...)`` assignment, or an inline lambda), plus the index
   map of every ``BlockSpec(...)`` in the file (second positional argument or
   ``index_map=`` keyword);
2. convention — any function whose name ends in ``_kernel`` (this codebase's
   naming rule; kernels that reach ``pallas_call`` through a helper parameter,
   as in ``kernels/fused.py``, are only caught this way).

Codes:

- PK201  flag read inside a kernel body / index map
- PK202  metrics-registry / watchdog call inside a kernel body / index map
- PK203  kernel body / index map closes over mutable module state
- PK204  host I/O (print/open/os.environ/time) inside a kernel body / index map
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from paddle_tpu.analysis.checkers._shared import (
    OBSERVABILITY_CALLS,
    OBSERVABILITY_ROOTS,
    attr_chain,
    attr_root,
    body_walk,
    bound_names,
    is_os_environ,
)
from paddle_tpu.analysis.core import Checker, FileContext, Violation

_FLAG_CALLS = {"get_flags", "set_flags", "define_flag"}


def _mutable_module_globals(tree: ast.Module) -> Set[str]:
    """Module-level bindings a pure kernel must not read: plain assignments to
    non-constant values whose name is not an ALL_CAPS constant. Imports,
    function/class defs and literal constants are exempt."""
    out: Set[str] = set()
    for stmt in tree.body:
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        if value is None or isinstance(value, ast.Constant):
            continue
        if isinstance(value, ast.UnaryOp) and isinstance(value.operand, ast.Constant):
            continue
        for t in targets:
            if isinstance(t, ast.Name) and not t.id.strip("_").isupper():
                out.add(t.id)
    return out


class _KernelCollector(ast.NodeVisitor):
    def __init__(self) -> None:
        self.defs: Dict[str, List[ast.AST]] = {}
        self.kernels: Dict[int, Tuple[ast.AST, str]] = {}  # id -> (node, role)
        self._pending: List[Tuple[str, str]] = []  # (name, role)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.defs.setdefault(node.name, []).append(node)
        if node.name.endswith("_kernel"):
            self.kernels[id(node)] = (node, "kernel body")
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def _partial_target(self, call: ast.Call) -> Optional[str]:
        if attr_chain(call.func) in ("functools.partial", "partial") and call.args:
            if isinstance(call.args[0], ast.Name):
                return call.args[0].id
        return None

    def _resolve_kernel_arg(self, arg: ast.AST, scope: Optional[ast.AST]) -> None:
        if isinstance(arg, ast.Lambda):
            self.kernels[id(arg)] = (arg, "kernel body")
        elif isinstance(arg, ast.Call):
            name = self._partial_target(arg)
            if name:
                self._pending.append((name, "kernel body"))
        elif isinstance(arg, ast.Name):
            # follow one `k = functools.partial(fn, ...)` hop in the enclosing
            # function before falling back to a def of the same name
            target = arg.id
            if scope is not None:
                for node in ast.walk(scope):
                    if (
                        isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)
                        and any(
                            isinstance(t, ast.Name) and t.id == target
                            for t in node.targets
                        )
                    ):
                        name = self._partial_target(node.value)
                        if name:
                            target = name
            self._pending.append((target, "kernel body"))

    def collect(self, ctx: FileContext) -> List[Tuple[ast.AST, str]]:
        self.visit(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func) or ""
            if chain.split(".")[-1] == "pallas_call" and node.args:
                scope = next(
                    (
                        a
                        for a in ctx.ancestors(node)
                        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
                    ),
                    None,
                )
                self._resolve_kernel_arg(node.args[0], scope)
            elif chain.split(".")[-1] == "BlockSpec":
                imap: Optional[ast.AST] = None
                if len(node.args) >= 2:
                    imap = node.args[1]
                for kw in node.keywords:
                    if kw.arg == "index_map":
                        imap = kw.value
                if isinstance(imap, ast.Lambda):
                    self.kernels[id(imap)] = (imap, "index map")
                elif isinstance(imap, ast.Name):
                    self._pending.append((imap.id, "index map"))
        for name, role in self._pending:
            for fn in self.defs.get(name, ()):
                self.kernels.setdefault(id(fn), (fn, role))
        return list(self.kernels.values())


class PallasPurityChecker(Checker):
    name = "pallas-purity"
    codes = {
        "PK201": "flag read inside a Pallas kernel/index map",
        "PK202": "metrics/watchdog call inside a Pallas kernel/index map",
        "PK203": "Pallas kernel/index map closes over mutable module state",
        "PK204": "host I/O inside a Pallas kernel/index map",
    }

    def run(self, ctx: FileContext) -> List[Violation]:
        mutables = _mutable_module_globals(ctx.tree)
        out: List[Violation] = []
        for fn, role in _KernelCollector().collect(ctx):
            label = getattr(fn, "name", "<lambda>")
            local = bound_names(fn)
            for node in body_walk(fn):
                v = self._check_node(node, local, mutables, role, label)
                if v is not None:
                    code, msg = v
                    out.append(
                        Violation(ctx.path, node.lineno, node.col_offset, code, msg)
                    )
        return out

    def _check_node(
        self,
        node: ast.AST,
        local: Set[str],
        mutables: Set[str],
        role: str,
        label: str,
    ):
        where = f"in {role} '{label}'"
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id == "GLOBAL_FLAGS":
                return "PK201", f"flag registry reference {where}: kernels must not read flags"
            if node.id in mutables and node.id not in local:
                return "PK203", (
                    f"'{node.id}' {where} closes over mutable module state; "
                    "pass it as a kernel argument or bake it via functools.partial"
                )
        if is_os_environ(node) and not isinstance(node, ast.Call):
            return "PK204", f"os.environ access {where}"
        if not isinstance(node, ast.Call):
            return None
        chain = attr_chain(node.func)
        root = attr_root(node.func)
        if isinstance(node.func, ast.Name) and node.func.id in _FLAG_CALLS:
            return "PK201", f"{node.func.id}() {where}: kernels must not touch flags"
        if root in OBSERVABILITY_ROOTS or (
            isinstance(node.func, ast.Name) and node.func.id in OBSERVABILITY_CALLS
        ):
            return "PK202", f"observability call {where}"
        if isinstance(node.func, ast.Name) and node.func.id in ("print", "open"):
            return "PK204", f"{node.func.id}() {where}"
        if root == "time" and isinstance(node.func, ast.Attribute):
            return "PK204", f"{chain}() {where}"
        return None
