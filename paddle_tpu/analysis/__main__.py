"""CLI: ``python -m paddle_tpu.analysis [--format text|json|sarif] paths...``

Exit status 0 when every violation is suppressed (with a reason) or covered
by the ``--baseline`` snapshot, 1 when any NEW unsuppressed violation
remains, 2 on usage errors — so the same invocation works as a pre-commit
hook and as the tier-1 gate. ``--write-baseline`` snapshots the current
unsuppressed findings so the gate can tighten incrementally (new code is
held to zero while accepted debt burns down)."""

from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path
from typing import List, Optional, Set

from paddle_tpu.analysis.checkers import all_checkers, all_codes
from paddle_tpu.analysis.core import analyze_paths, iter_python_files
from paddle_tpu.analysis.reporters import (
    load_baseline,
    new_violations,
    render_json,
    render_sarif,
    render_text,
    write_baseline,
)


def _git_changed_files(ref: str) -> Optional[Set[Path]]:
    """Resolved paths changed vs ``ref`` plus untracked files, or None when
    git is unusable (not a repo, binary missing, bad ref)."""
    def _run(*argv: str) -> str:
        return subprocess.run(
            ["git", *argv], capture_output=True, text=True, check=True, timeout=30
        ).stdout

    try:
        root = Path(_run("rev-parse", "--show-toplevel").strip())
        names = _run("diff", "--name-only", ref).splitlines()
        names += _run("ls-files", "--others", "--exclude-standard").splitlines()
    except (OSError, subprocess.SubprocessError):
        return None
    return {(root / n.strip()).resolve() for n in names if n.strip()}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="AST static analysis: trace-safety (TS), Pallas purity (PK), "
        "Pallas geometry (PG), flag discipline (FD), exception hygiene (EH), "
        "robustness (RB), observability (OB), concurrency (CC), "
        "donation/lifetime (DN), tape backward discipline (TB), "
        "distributed protocol (CM).",
    )
    ap.add_argument("paths", nargs="*", help="files or directories to analyze")
    ap.add_argument("--format", choices=("text", "json", "sarif"), default="text")
    ap.add_argument(
        "--select",
        help="comma-separated code prefixes to run (e.g. TS,EH401); default all",
    )
    ap.add_argument(
        "--baseline", metavar="FILE",
        help="accept-known-findings snapshot: exit 1 only on unsuppressed "
        "violations NOT covered by the baseline",
    )
    ap.add_argument(
        "--write-baseline", metavar="FILE",
        help="write the current unsuppressed findings as a baseline snapshot "
        "and exit 0 (combine with --select to scope it)",
    )
    ap.add_argument(
        "--changed-only", nargs="?", const="HEAD", default=None, metavar="REF",
        help="scope the run to files changed vs a git ref (default HEAD) plus "
        "untracked files; falls back to a full run with a warning when git "
        "is unavailable — the pre-commit hook mode (tools/pre-commit-analysis)",
    )
    ap.add_argument(
        "--vmem-budget", type=int, default=None, metavar="BYTES",
        help="per-grid-step VMEM budget for PG903 in bytes "
        "(default 16 MiB/core)",
    )
    ap.add_argument(
        "--show-suppressed", action="store_true",
        help="include suppressed violations in text output",
    )
    ap.add_argument(
        "--timings", action="store_true",
        help="print per-phase (parse / index build / dataflow / geometry) "
        "and per-checker wall time to stderr, so the 30s tier-1 budget is "
        "attributable when a checker family blows it",
    )
    ap.add_argument(
        "--list-checkers", action="store_true", help="print codes and exit"
    )
    args = ap.parse_args(argv)

    if args.list_checkers:
        for code, desc in sorted(all_codes().items()):
            print(f"{code}  {desc}")
        return 0
    if not args.paths:
        ap.print_usage(sys.stderr)
        return 2

    select = [s.strip() for s in args.select.split(",")] if args.select else None
    if select is not None:
        # never-vacuous rule (same as missing-path and corrupt-baseline): a
        # typo'd prefix that matches nothing must not pass silently
        codes = all_codes()
        bad = [s for s in select if not s or not any(c.startswith(s) for c in codes)]
        if bad:
            print(
                f"error: --select matched no registered codes: "
                f"{', '.join(repr(s) for s in bad)}\n"
                f"valid codes: {', '.join(sorted(codes))}",
                file=sys.stderr,
            )
            return 2

    paths = list(args.paths)
    if args.changed_only is not None:
        changed = _git_changed_files(args.changed_only)
        if changed is None:
            print(
                "warning: git unavailable; --changed-only falling back to a "
                "full run",
                file=sys.stderr,
            )
        else:
            try:
                scoped = [
                    f for f in iter_python_files(paths) if f.resolve() in changed
                ]
            except FileNotFoundError as exc:
                print(f"error: {exc}", file=sys.stderr)
                return 2
            if not scoped:
                print(
                    f"no Python files changed vs {args.changed_only} under "
                    f"the given paths"
                )
                return 0
            paths = [str(f) for f in scoped]

    checkers = None
    if args.vmem_budget is not None:
        checkers = all_checkers()
        for c in checkers:
            if c.name == "pallas_geometry":
                c.vmem_budget = int(args.vmem_budget)

    timings = {} if args.timings else None
    try:
        violations = analyze_paths(
            paths, checkers=checkers, select=select, timings=timings
        )
    except FileNotFoundError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    if timings is not None:
        print("timings:", file=sys.stderr)
        for key in sorted(timings, key=lambda k: (not k.startswith("phase:"), -timings[k])):
            group, name = key.split(":", 1)
            print(f"  {group:8s}{name:24s}{timings[key]:8.3f}s", file=sys.stderr)

    if args.write_baseline:
        write_baseline(args.write_baseline, violations)
        n = sum(1 for v in violations if not v.suppressed)
        print(f"baseline written to {args.write_baseline} ({n} accepted finding(s))")
        return 0

    gate = [v for v in violations if not v.suppressed]
    if args.baseline:
        try:
            known = load_baseline(args.baseline)
        except (OSError, ValueError) as exc:
            # a missing/corrupt baseline must not turn the gate vacuous
            print(f"error: baseline unusable: {exc}", file=sys.stderr)
            return 2
        gate = new_violations(violations, known)

    if args.format == "json":
        print(render_json(violations))
    elif args.format == "sarif":
        print(render_sarif(violations, all_codes()))
    else:
        print(render_text(violations, show_suppressed=args.show_suppressed))
        if args.baseline:
            print(
                f"{len(gate)} NEW unsuppressed violation(s) vs baseline "
                f"{args.baseline}"
            )
    return 1 if gate else 0


if __name__ == "__main__":
    sys.exit(main())
