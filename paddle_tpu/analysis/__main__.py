"""CLI: ``python -m paddle_tpu.analysis [--format text|json] paths...``

Exit status 0 when every violation is suppressed (with a reason), 1 when any
unsuppressed violation remains, 2 on usage errors — so the same invocation
works as a pre-commit hook and as the tier-1 gate."""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from paddle_tpu.analysis.checkers import all_codes
from paddle_tpu.analysis.core import analyze_paths
from paddle_tpu.analysis.reporters import render_json, render_text


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="AST static analysis: trace-safety (TS), Pallas purity (PK), "
        "flag discipline (FD), exception hygiene (EH).",
    )
    ap.add_argument("paths", nargs="*", help="files or directories to analyze")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument(
        "--select",
        help="comma-separated code prefixes to run (e.g. TS,EH401); default all",
    )
    ap.add_argument(
        "--show-suppressed", action="store_true",
        help="include suppressed violations in text output",
    )
    ap.add_argument(
        "--list-checkers", action="store_true", help="print codes and exit"
    )
    args = ap.parse_args(argv)

    if args.list_checkers:
        for code, desc in sorted(all_codes().items()):
            print(f"{code}  {desc}")
        return 0
    if not args.paths:
        ap.print_usage(sys.stderr)
        return 2

    select = [s.strip() for s in args.select.split(",")] if args.select else None
    try:
        violations = analyze_paths(args.paths, select=select)
    except FileNotFoundError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    if args.format == "json":
        print(render_json(violations))
    else:
        print(render_text(violations, show_suppressed=args.show_suppressed))
    return 1 if any(not v.suppressed for v in violations) else 0


if __name__ == "__main__":
    sys.exit(main())
