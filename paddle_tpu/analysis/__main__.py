"""CLI: ``python -m paddle_tpu.analysis [--format text|json|sarif] paths...``

Exit status 0 when every violation is suppressed (with a reason) or covered
by the ``--baseline`` snapshot, 1 when any NEW unsuppressed violation
remains, 2 on usage errors — so the same invocation works as a pre-commit
hook and as the tier-1 gate. ``--write-baseline`` snapshots the current
unsuppressed findings so the gate can tighten incrementally (new code is
held to zero while accepted debt burns down)."""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from paddle_tpu.analysis.checkers import all_codes
from paddle_tpu.analysis.core import analyze_paths
from paddle_tpu.analysis.reporters import (
    load_baseline,
    new_violations,
    render_json,
    render_sarif,
    render_text,
    write_baseline,
)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="AST static analysis: trace-safety (TS), Pallas purity (PK), "
        "flag discipline (FD), exception hygiene (EH), robustness (RB), "
        "observability (OB), concurrency (CC), donation/lifetime (DN), "
        "tape backward discipline (TB).",
    )
    ap.add_argument("paths", nargs="*", help="files or directories to analyze")
    ap.add_argument("--format", choices=("text", "json", "sarif"), default="text")
    ap.add_argument(
        "--select",
        help="comma-separated code prefixes to run (e.g. TS,EH401); default all",
    )
    ap.add_argument(
        "--baseline", metavar="FILE",
        help="accept-known-findings snapshot: exit 1 only on unsuppressed "
        "violations NOT covered by the baseline",
    )
    ap.add_argument(
        "--write-baseline", metavar="FILE",
        help="write the current unsuppressed findings as a baseline snapshot "
        "and exit 0 (combine with --select to scope it)",
    )
    ap.add_argument(
        "--show-suppressed", action="store_true",
        help="include suppressed violations in text output",
    )
    ap.add_argument(
        "--list-checkers", action="store_true", help="print codes and exit"
    )
    args = ap.parse_args(argv)

    if args.list_checkers:
        for code, desc in sorted(all_codes().items()):
            print(f"{code}  {desc}")
        return 0
    if not args.paths:
        ap.print_usage(sys.stderr)
        return 2

    select = [s.strip() for s in args.select.split(",")] if args.select else None
    try:
        violations = analyze_paths(args.paths, select=select)
    except FileNotFoundError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(args.write_baseline, violations)
        n = sum(1 for v in violations if not v.suppressed)
        print(f"baseline written to {args.write_baseline} ({n} accepted finding(s))")
        return 0

    gate = [v for v in violations if not v.suppressed]
    if args.baseline:
        try:
            known = load_baseline(args.baseline)
        except (OSError, ValueError) as exc:
            # a missing/corrupt baseline must not turn the gate vacuous
            print(f"error: baseline unusable: {exc}", file=sys.stderr)
            return 2
        gate = new_violations(violations, known)

    if args.format == "json":
        print(render_json(violations))
    elif args.format == "sarif":
        print(render_sarif(violations, all_codes()))
    else:
        print(render_text(violations, show_suppressed=args.show_suppressed))
        if args.baseline:
            print(
                f"{len(gate)} NEW unsuppressed violation(s) vs baseline "
                f"{args.baseline}"
            )
    return 1 if gate else 0


if __name__ == "__main__":
    sys.exit(main())
