"""Violation reporters: human text, machine JSON, SARIF 2.1.0, baselines.

All render the same violation list. The JSON form is what the tier-1 gate
consumes; SARIF (``--format sarif``) is the interchange format CI code-
scanning UIs ingest — rule ids are the stable violation codes, suppressed
findings carry SARIF ``suppressions`` entries so they upload without
re-alerting.

Baselines (``--baseline known.json`` / ``--write-baseline known.json``) let
the gate tighten incrementally on a codebase with accepted findings: a
baseline is a multiset of ``path::code`` fingerprints (line numbers are
deliberately NOT part of the fingerprint — an unrelated edit shifting lines
must not resurrect an accepted finding); the CLI exits 1 only on
unsuppressed violations NOT covered by the baseline's count for their
fingerprint."""

from __future__ import annotations

import json
from typing import Dict, List, Sequence

from paddle_tpu.analysis.core import Violation

__all__ = [
    "render_text",
    "render_json",
    "render_sarif",
    "summarize",
    "baseline_fingerprints",
    "new_violations",
    "write_baseline",
    "load_baseline",
]

BASELINE_SCHEMA = "paddle_tpu.analysis.baseline/v1"


def summarize(violations: Sequence[Violation]) -> Dict[str, int]:
    live = [v for v in violations if not v.suppressed]
    per_code: Dict[str, int] = {}
    for v in live:
        per_code[v.code] = per_code.get(v.code, 0) + 1
    return {
        "total": len(violations),
        "unsuppressed": len(live),
        "suppressed": len(violations) - len(live),
        **{f"code:{c}": n for c, n in sorted(per_code.items())},
    }


def render_text(violations: Sequence[Violation], show_suppressed: bool = False) -> str:
    shown = [v for v in violations if show_suppressed or not v.suppressed]
    lines: List[str] = [v.format() for v in shown]
    s = summarize(violations)
    lines.append(
        f"{s['unsuppressed']} unsuppressed violation(s), "
        f"{s['suppressed']} suppressed"
    )
    return "\n".join(lines)


def render_sarif(violations: Sequence[Violation], rule_descriptions: Dict[str, str]) -> str:
    """SARIF 2.1.0 with stable rule ids (the violation codes). Suppressed
    findings are included with a SARIF suppression record (kind
    ``inSource``) so a code-scanning UI shows them as acknowledged rather
    than new."""
    used = sorted({v.code for v in violations} | set(rule_descriptions))
    rules = [
        {
            "id": code,
            "name": code,
            "shortDescription": {"text": rule_descriptions.get(code, code)},
        }
        for code in used
    ]
    rule_index = {code: i for i, code in enumerate(used)}
    results = []
    for v in violations:
        result = {
            "ruleId": v.code,
            "ruleIndex": rule_index[v.code],
            "level": "warning" if v.suppressed else "error",
            "message": {"text": v.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": v.path.replace("\\", "/")},
                        "region": {
                            "startLine": max(1, v.line),
                            "startColumn": max(1, v.col + 1),
                        },
                    }
                }
            ],
        }
        if v.suppressed:
            result["suppressions"] = [
                {"kind": "inSource", "justification": v.reason or ""}
            ]
        results.append(result)
    doc = {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "paddle_tpu.analysis",
                        "informationUri": "https://github.com/PaddlePaddle/Paddle",
                        "rules": rules,
                    }
                },
                "results": results,
            }
        ],
    }
    return json.dumps(doc, indent=1)


# -- baselines (accept-known-findings snapshots) ------------------------------

def baseline_fingerprints(violations: Sequence[Violation]) -> Dict[str, int]:
    """Multiset of ``path::code`` fingerprints over UNSUPPRESSED violations
    (suppressed ones are already accepted in-source, with a reason)."""
    out: Dict[str, int] = {}
    for v in violations:
        if v.suppressed:
            continue
        fp = f"{v.path.replace(chr(92), '/')}::{v.code}"
        out[fp] = out.get(fp, 0) + 1
    return out


def write_baseline(path: str, violations: Sequence[Violation]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(
            {"schema": BASELINE_SCHEMA, "findings": baseline_fingerprints(violations)},
            f, indent=1, sort_keys=True,
        )
        f.write("\n")


def load_baseline(path: str) -> Dict[str, int]:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or data.get("schema") != BASELINE_SCHEMA:
        raise ValueError(
            f"{path} is not a {BASELINE_SCHEMA} baseline (wrong shape/schema)"
        )
    findings = data.get("findings")
    if not isinstance(findings, dict) or not all(
        isinstance(k, str) and isinstance(c, int) and c >= 0
        for k, c in findings.items()
    ):
        raise ValueError(f"{path}: baseline 'findings' must map fingerprints to counts")
    return dict(findings)


def new_violations(
    violations: Sequence[Violation], baseline: Dict[str, int]
) -> List[Violation]:
    """Unsuppressed violations beyond the baseline's per-fingerprint count.
    Within one fingerprint the EARLIEST occurrences are treated as the known
    ones, so the reported new finding is the one furthest from the accepted
    set (stable given the driver's path/line sort)."""
    budget = dict(baseline)
    out: List[Violation] = []
    for v in violations:
        if v.suppressed:
            continue
        fp = f"{v.path.replace(chr(92), '/')}::{v.code}"
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
        else:
            out.append(v)
    return out


def render_json(violations: Sequence[Violation]) -> str:
    return json.dumps(
        {
            "violations": [
                {
                    "path": v.path,
                    "line": v.line,
                    "col": v.col,
                    "code": v.code,
                    "message": v.message,
                    "suppressed": v.suppressed,
                    "reason": v.reason,
                }
                for v in violations
            ],
            "summary": summarize(violations),
        },
        indent=1,
    )
