"""Violation reporters: human text and machine JSON.

Both render the same violation list; the JSON form is what CI and the tier-1
gate consume (``python -m paddle_tpu.analysis --format json ...``)."""

from __future__ import annotations

import json
from typing import Dict, List, Sequence

from paddle_tpu.analysis.core import Violation

__all__ = ["render_text", "render_json", "summarize"]


def summarize(violations: Sequence[Violation]) -> Dict[str, int]:
    live = [v for v in violations if not v.suppressed]
    per_code: Dict[str, int] = {}
    for v in live:
        per_code[v.code] = per_code.get(v.code, 0) + 1
    return {
        "total": len(violations),
        "unsuppressed": len(live),
        "suppressed": len(violations) - len(live),
        **{f"code:{c}": n for c, n in sorted(per_code.items())},
    }


def render_text(violations: Sequence[Violation], show_suppressed: bool = False) -> str:
    shown = [v for v in violations if show_suppressed or not v.suppressed]
    lines: List[str] = [v.format() for v in shown]
    s = summarize(violations)
    lines.append(
        f"{s['unsuppressed']} unsuppressed violation(s), "
        f"{s['suppressed']} suppressed"
    )
    return "\n".join(lines)


def render_json(violations: Sequence[Violation]) -> str:
    return json.dumps(
        {
            "violations": [
                {
                    "path": v.path,
                    "line": v.line,
                    "col": v.col,
                    "code": v.code,
                    "message": v.message,
                    "suppressed": v.suppressed,
                    "reason": v.reason,
                }
                for v in violations
            ],
            "summary": summarize(violations),
        },
        indent=1,
    )
