"""Autoregressive decoding: one compiled XLA program per (model, shape).

The reference's decode path is the inference stack's cache attention
(``paddle/phi/ops/yaml/ops.yaml:3074`` ``masked_multihead_attention_``,
``paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu``) driven
by a Python loop; the ``generate()`` surface mirrors the PaddleNLP
GenerationMixin API. TPU-native shape: prefill + ``lax.scan`` of single-token
steps over fixed-size KV-cache buffers, the whole thing inside ONE jit — no
per-step retraces, no growing shapes, every decode step is the same program.
"""

from __future__ import annotations

import functools
from typing import Any, List, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.nn.layer.layers import bind_param_arrays

__all__ = ["GenerationMixin"]


def _filter_logits(logits: jax.Array, temperature: float, top_k: int, top_p: float) -> jax.Array:
    """Standard sampling filters (temperature, top-k, nucleus/top-p)."""
    if temperature != 1.0:
        logits = logits / max(float(temperature), 1e-6)
    if top_k and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum_excl = jnp.cumsum(probs, axis=-1) - probs
        # smallest logit still inside the nucleus; everything below is cut
        kept_min = jnp.min(
            jnp.where(cum_excl > top_p, jnp.inf, sorted_desc), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < kept_min, -jnp.inf, logits)
    return logits


class GenerationMixin:
    """Adds ``generate()`` to a causal LM whose ``forward`` supports
    ``(input_ids, past_key_values, use_cache, cache_position)`` with
    static-cache decode semantics (see ``LlamaAttention``)."""

    # -- shared decode plumbing (one copy for generate/generate_beam) -------
    def _decode_prep(self, input_ids: Any, max_new_tokens: int,
                     eos_token_id: Optional[int], pad_token_id: Optional[int]):
        """Validate + normalize the common decode arguments. Returns
        ``(ids_array, pad_token_id)``; raises like ``generate`` always has."""
        from paddle_tpu.core.tensor import Tensor

        ids = input_ids._data if isinstance(input_ids, Tensor) else jnp.asarray(input_ids)
        ids = ids.astype(jnp.int32)
        if max_new_tokens < 0:
            raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
        max_pos = getattr(getattr(self, "config", None), "max_position_embeddings", None)
        if max_pos is not None and ids.shape[1] + max_new_tokens > max_pos:
            # the decode path's dynamic rope-table slice would silently clamp
            # past the table end and emit garbage — fail loudly instead
            raise ValueError(
                f"prompt ({ids.shape[1]}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_position_embeddings ({max_pos})"
            )
        if pad_token_id is None:
            pad_token_id = eos_token_id if eos_token_id is not None else 0
        return ids, int(pad_token_id)

    def _compiled(self, cfg: tuple, build) -> Any:
        """Per-model bounded FIFO cache of compiled decode programs."""
        cache = getattr(self, "_generate_jit_cache", None)
        if cache is None:
            cache = {}
            object.__setattr__(self, "_generate_jit_cache", cache)
        if cfg not in cache and len(cache) >= 16:
            # bounded: each entry pins a compiled executable (FIFO eviction)
            cache.pop(next(iter(cache)))
        if cfg not in cache:
            cache[cfg] = build()
        return cache[cfg]

    def generate(
        self,
        input_ids: Any,
        max_new_tokens: int = 32,
        do_sample: bool = False,
        temperature: float = 1.0,
        top_k: int = 0,
        top_p: float = 1.0,
        eos_token_id: Optional[int] = None,
        pad_token_id: Optional[int] = None,
        seed: int = 0,
    ) -> Any:
        """Greedy or sampling decode. Returns ``[B, prompt + max_new_tokens]``
        token ids (prompt included); after ``eos_token_id`` a sequence is
        padded with ``pad_token_id`` (defaults to eos)."""
        from paddle_tpu.core.tensor import Tensor

        ids, pad_token_id = self._decode_prep(
            input_ids, max_new_tokens, eos_token_id, pad_token_id
        )
        b, prompt = ids.shape
        if max_new_tokens == 0:
            return Tensor(ids)

        cfg = (
            b, prompt, int(max_new_tokens), bool(do_sample), float(temperature),
            int(top_k), float(top_p), eos_token_id, pad_token_id,
        )
        fn = self._compiled(
            cfg,
            lambda: jax.jit(
                functools.partial(
                    self._generate_impl,
                    max_new_tokens=int(max_new_tokens),
                    do_sample=bool(do_sample),
                    temperature=float(temperature),
                    top_k=int(top_k),
                    top_p=float(top_p),
                    eos_token_id=eos_token_id,
                    pad_token_id=int(pad_token_id),
                )
            ),
        )
        named = list(self.named_parameters())
        arrays = [p._data for _, p in named]
        out = fn(arrays, ids, jax.random.PRNGKey(seed))
        return Tensor(out)

    def generate_paged(
        self,
        input_ids: Any,
        max_new_tokens: int = 32,
        block_size: int = 16,
        num_blocks: Optional[int] = None,
        eos_token_id: Optional[int] = None,
        pad_token_id: Optional[int] = None,
    ) -> Any:
        """Greedy decode over the PAGED KV cache (reference
        ``block_multihead_attention_``): physical blocks are allocated to
        sequences as they grow and reclaimed at the end — the serving-side
        memory model, vs ``generate()``'s fixed dense buffers. The host
        allocator runs between steps; each decode step is one jitted program
        (block tables and lengths are data, so shapes never change).

        This runs ONE static batch to completion (a finished sequence holds
        its slot and blocks until all are done); for mixed-length serving
        traffic use ``paddle_tpu.inference.ContinuousBatchingEngine``, which
        admits/evicts per step over a shared pool with the same numerics —
        the engine's per-sequence outputs match this method token-for-token."""
        import numpy as np

        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.incubate.nn.functional import BlockKVCache, block_cache_prefill

        ids = input_ids._data if isinstance(input_ids, Tensor) else jnp.asarray(input_ids)
        ids = ids.astype(jnp.int32)
        b, prompt = ids.shape
        if max_new_tokens <= 0:
            return Tensor(ids)
        cfg = self.config
        kvh = cfg.num_key_value_heads
        hd = cfg.hidden_size // cfg.num_attention_heads
        max_len = prompt + max_new_tokens
        if getattr(cfg, "max_position_embeddings", None) and max_len > cfg.max_position_embeddings:
            raise ValueError(
                f"prompt ({prompt}) + max_new_tokens ({max_new_tokens}) exceeds "
                f"max_position_embeddings ({cfg.max_position_embeddings})"
            )
        mbs = -(-max_len // block_size)
        if num_blocks is None:
            num_blocks = b * mbs
        dtype = next(iter(self.parameters())).dtype
        L = cfg.num_hidden_layers
        mgr = BlockKVCache(num_blocks, block_size, kvh, hd, mbs, dtype=dtype)
        for i in range(b):
            mgr.allocate(i, prompt)
        tables = mgr.block_table(range(b))
        lens = jnp.full((b,), prompt, jnp.int32)

        if pad_token_id is None:
            pad_token_id = eos_token_id if eos_token_id is not None else 0

        # prefill: dense forward once, then pour each layer's K/V into blocks
        import paddle_tpu

        with paddle_tpu.no_grad():
            logits, dense_caches = self(Tensor(ids), use_cache=True)
        layer_caches = []
        for k_t, v_t in dense_caches:
            # paged layout [NB, H, BS, D] (see BlockKVCache)
            kc = jnp.zeros((num_blocks, kvh, block_size, hd), dtype)
            vc = jnp.zeros_like(kc)
            kc, vc = block_cache_prefill(kc, vc, k_t._data, v_t._data, tables, lens)
            layer_caches.append((kc, vc))
        tok = jnp.argmax(logits._data[:, -1, :].astype(jnp.float32), axis=-1).astype(jnp.int32)
        done = tok == eos_token_id if eos_token_id is not None else jnp.zeros((b,), bool)

        named = list(self.named_parameters())
        # one compiled decode program per geometry, cached across calls
        # (re-jitting per request would pay a full XLA compile per serve)
        step_cache = getattr(self, "_paged_step_cache", None)
        if step_cache is None:
            step_cache = {}
            object.__setattr__(self, "_paged_step_cache", step_cache)
        step_key = (b, L, num_blocks, block_size, mbs, str(dtype))
        if step_key not in step_cache and len(step_cache) >= 8:
            step_cache.pop(next(iter(step_cache)))

        @jax.jit
        def _paged_step(param_arrays, tok, caches, tables, lens):
            with bind_param_arrays(named, param_arrays):
                pkv = [
                    (Tensor(kc), Tensor(vc), Tensor(tables), Tensor(lens))
                    for kc, vc in caches
                ]
                with paddle_tpu.no_grad():
                    step_logits, new_caches = self(
                        Tensor(tok[:, None]),
                        past_key_values=pkv,
                        use_cache=True,
                        cache_position=Tensor(lens),
                    )
                nxt = jnp.argmax(
                    step_logits._data[:, -1, :].astype(jnp.float32), axis=-1
                ).astype(jnp.int32)
                out_caches = [(c[0]._data, c[1]._data) for c in new_caches]
                return nxt, out_caches

        step = step_cache.setdefault(step_key, _paged_step)

        arrays = [p._data for _, p in named]
        out_toks = [tok]
        for _ in range(max_new_tokens - 1):
            for i in range(b):
                mgr.allocate(i, 1)
            tables = mgr.block_table(range(b))
            nxt, layer_caches = step(arrays, tok, layer_caches, tables, lens)
            lens = lens + 1
            nxt = jnp.where(done, jnp.int32(pad_token_id), nxt)
            if eos_token_id is not None:
                done = done | (nxt == eos_token_id)
            out_toks.append(nxt)
            tok = nxt
        for i in range(b):
            mgr.free(i)
        return Tensor(jnp.concatenate([ids] + [t[:, None] for t in out_toks], axis=1))

    # traced: runs once per (shape, sampling config), then pure XLA
    def _generate_impl(
        self,
        param_arrays: List[Any],
        ids: jax.Array,
        key: jax.Array,
        *,
        max_new_tokens: int,
        do_sample: bool,
        temperature: float,
        top_k: int,
        top_p: float,
        eos_token_id: Optional[int],
        pad_token_id: int,
    ) -> jax.Array:
        import paddle_tpu
        from paddle_tpu.core.tensor import Tensor

        b, prompt = ids.shape
        s_total = prompt + max_new_tokens

        def choose(logits: jax.Array, k: jax.Array) -> jax.Array:
            logits = logits.astype(jnp.float32)
            if do_sample:
                return jax.random.categorical(
                    k, _filter_logits(logits, temperature, top_k, top_p), axis=-1
                ).astype(jnp.int32)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)

        named = list(self.named_parameters())
        with bind_param_arrays(named, param_arrays):
            with paddle_tpu.no_grad():
                logits, caches = self(Tensor(ids), use_cache=True)
            key, sub = jax.random.split(key)
            tok0 = choose(logits._data[:, -1, :], sub)
            done0 = (
                tok0 == eos_token_id
                if eos_token_id is not None
                else jnp.zeros((b,), bool)
            )
            pad_spec = ((0, 0), (0, s_total - prompt), (0, 0), (0, 0))
            cks = [jnp.pad(k_t._data, pad_spec) for k_t, _ in caches]
            cvs = [jnp.pad(v_t._data, pad_spec) for _, v_t in caches]

            def body(carry, _):
                tok, cks, cvs, pos, done, key = carry
                with paddle_tpu.no_grad():
                    step_logits, new_caches = self(
                        Tensor(tok[:, None]),
                        past_key_values=[
                            (Tensor(k), Tensor(v)) for k, v in zip(cks, cvs)
                        ],
                        use_cache=True,
                        cache_position=Tensor(pos),
                    )
                key, sub = jax.random.split(key)
                nxt = choose(step_logits._data[:, -1, :], sub)
                nxt = jnp.where(done, jnp.int32(pad_token_id), nxt)
                if eos_token_id is not None:
                    done = done | (nxt == eos_token_id)
                cks2 = [c[0]._data for c in new_caches]
                cvs2 = [c[1]._data for c in new_caches]
                return (nxt, cks2, cvs2, pos + 1, done, key), nxt

            # tok0 came from the prefill logits; the scan emits each step's
            # NEWLY chosen token, so only max_new_tokens - 1 decoder steps run
            # (emitting the carry instead would pay one full forward whose
            # result is discarded)
            init = (tok0, cks, cvs, jnp.int32(prompt), done0, key)
            _, toks = jax.lax.scan(body, init, None, length=max_new_tokens - 1)
        return jnp.concatenate([ids, tok0[:, None], toks.T], axis=1)

    # -- beam search --------------------------------------------------------
    def generate_beam(
        self,
        input_ids: Any,
        max_new_tokens: int = 32,
        num_beams: int = 4,
        length_penalty: float = 0.0,
        eos_token_id: Optional[int] = None,
        pad_token_id: Optional[int] = None,
    ) -> Any:
        """Beam-search decode (reference ``beam_search`` op +
        PaddleNLP ``BeamSearchScorer``): the whole search is ONE compiled
        scan — beams live as a folded batch axis, each step reorders the KV
        cache by backpointer, and the final sequences are reconstructed with
        the ``gather_tree`` op. Returns ``[B, prompt + max_new_tokens]``."""
        from paddle_tpu.core.tensor import Tensor

        if num_beams < 1:
            raise ValueError(f"num_beams must be >= 1, got {num_beams}")
        ids, pad_token_id = self._decode_prep(
            input_ids, max_new_tokens, eos_token_id, pad_token_id
        )
        b, prompt = ids.shape
        if max_new_tokens == 0:
            return Tensor(ids)

        cfg = ("beam", b, prompt, int(max_new_tokens), int(num_beams),
               float(length_penalty), eos_token_id, pad_token_id)
        fn = self._compiled(
            cfg,
            lambda: jax.jit(
                functools.partial(
                    self._generate_beam_impl,
                    max_new_tokens=int(max_new_tokens),
                    num_beams=int(num_beams),
                    length_penalty=float(length_penalty),
                    eos_token_id=eos_token_id,
                    pad_token_id=int(pad_token_id),
                )
            ),
        )
        named = list(self.named_parameters())
        arrays = [p._data for _, p in named]
        return Tensor(fn(arrays, ids))

    def _generate_beam_impl(
        self,
        param_arrays: List[Any],
        ids: jax.Array,
        *,
        max_new_tokens: int,
        num_beams: int,
        length_penalty: float,
        eos_token_id: Optional[int],
        pad_token_id: int,
    ) -> jax.Array:
        import paddle_tpu
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.ops.parity import gather_tree

        K = num_beams
        NEG = -1e9
        b, prompt = ids.shape
        s_total = prompt + max_new_tokens

        named = list(self.named_parameters())
        with bind_param_arrays(named, param_arrays):
            with paddle_tpu.no_grad():
                logits, caches = self(Tensor(ids), use_cache=True)
            logp0 = jax.nn.log_softmax(logits._data[:, -1, :].astype(jnp.float32))
            V = logp0.shape[-1]
            scores, tok0 = jax.lax.top_k(logp0, K)  # [B, K]
            tok0 = tok0.astype(jnp.int32)
            done = (
                tok0 == eos_token_id if eos_token_id is not None
                else jnp.zeros((b, K), bool)
            )
            lens = jnp.ones((b, K), jnp.int32)
            pad_spec = ((0, 0), (0, s_total - prompt), (0, 0), (0, 0))
            # beams fold into the batch axis: [B*K, S, H, D]
            cks = [jnp.repeat(jnp.pad(k_t._data, pad_spec), K, axis=0) for k_t, _ in caches]
            cvs = [jnp.repeat(jnp.pad(v_t._data, pad_spec), K, axis=0) for _, v_t in caches]
            # one-hot pad row: a finished beam only extends by pad, score frozen
            pad_row = jnp.full((V,), NEG, jnp.float32).at[pad_token_id].set(0.0)

            def body(carry, _):
                tok, scores, done, lens, cks, cvs, pos = carry
                with paddle_tpu.no_grad():
                    step_logits, new_caches = self(
                        Tensor(tok.reshape(-1)[:, None]),
                        past_key_values=[
                            (Tensor(k), Tensor(v)) for k, v in zip(cks, cvs)
                        ],
                        use_cache=True,
                        cache_position=Tensor(pos),
                    )
                logp = jax.nn.log_softmax(
                    step_logits._data[:, -1, :].astype(jnp.float32)
                ).reshape(b, K, V)
                logp = jnp.where(done[:, :, None], pad_row[None, None, :], logp)
                cand = (scores[:, :, None] + logp).reshape(b, K * V)
                new_scores, idx = jax.lax.top_k(cand, K)
                parent = (idx // V).astype(jnp.int32)  # new beam -> old beam
                new_tok = (idx % V).astype(jnp.int32)
                flat_parent = (jnp.arange(b)[:, None] * K + parent).reshape(-1)
                cks2 = [c[0]._data[flat_parent] for c in new_caches]
                cvs2 = [c[1]._data[flat_parent] for c in new_caches]
                done_g = jnp.take_along_axis(done, parent, axis=1)
                lens_g = jnp.take_along_axis(lens, parent, axis=1)
                lens2 = lens_g + jnp.where(done_g, 0, 1).astype(jnp.int32)
                done2 = done_g | (
                    new_tok == eos_token_id if eos_token_id is not None
                    else jnp.zeros_like(done_g)
                )
                return (new_tok, new_scores, done2, lens2, cks2, cvs2, pos + 1), (
                    new_tok, parent,
                )

            init = (tok0, scores, done, lens, cks, cvs, jnp.int32(prompt))
            (tok, scores, done, lens, _, _, _), (toks, parents) = jax.lax.scan(
                body, init, None, length=max_new_tokens - 1
            )
            # [T, B, K] with the step-0 layer (parents 0: all beams came from
            # the single prefill context)
            all_toks = jnp.concatenate([tok0[None], toks], axis=0)
            all_parents = jnp.concatenate(
                [jnp.zeros((1, b, K), jnp.int32), parents], axis=0
            )
            seqs = gather_tree(all_toks, all_parents)  # [T, B, K]
            seqs = seqs._data if hasattr(seqs, "_data") else seqs
            if length_penalty != 0.0:
                # reference BeamSearchScorer normalization: score divided by
                # ((5 + len) / 6) ** alpha over the FULL hypothesis length
                # (prompt + generated) — `lens ** alpha` over generated
                # tokens only ranks beams differently
                full_len = (prompt + lens).astype(jnp.float32)
                final = scores / jnp.power((5.0 + full_len) / 6.0, length_penalty)
            else:
                final = scores
            best = jnp.argmax(final, axis=-1)  # [B]
            best_seq = jnp.take_along_axis(
                seqs, best[None, :, None], axis=2
            )[:, :, 0]  # [T, B]
        return jnp.concatenate([ids, best_seq.T], axis=1)
