"""Device RNG state compat shims (reference ``python/paddle/framework/random.py``)."""

from __future__ import annotations

from typing import Any, List

from paddle_tpu.core.rng import get_rng_state, set_rng_state


def get_cuda_rng_state() -> List[Any]:
    """Accelerator RNG state (name kept for script compat; returns the global
    splittable-PRNG state)."""
    return [get_rng_state()]


def set_cuda_rng_state(state: List[Any]) -> None:
    set_rng_state(state[0])
