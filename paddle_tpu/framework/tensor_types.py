"""Auxiliary tensor types: TensorArray + SelectedRows.

Reference: ``paddle/phi/core/tensor_array.h`` (dynamic list of tensors backing
``paddle.tensor.array_*`` / static-RNN state) and
``paddle/phi/core/selected_rows.h:27`` (row-sparse gradient container used by
sparse embedding updates).

TPU-native framing: XLA programs are static, so a *dynamic* array only lives
at the Python level — inside jit, ``lax.scan`` replaces array_write loops
(see ``nn/layer/rnn.py``). TensorArray therefore serves eager code and API
portability. SelectedRows keeps (rows, values) unmaterialized so an embedding
gradient of a few rows doesn't densify the whole table until the optimizer
applies it — the same memory trade the reference makes.
"""

from __future__ import annotations

from typing import Any, Iterator, List, Optional, Sequence

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = [
    "TensorArray",
    "SelectedRows",
    "StringTensor",
    "create_array",
    "array_write",
    "array_read",
    "array_length",
]


class TensorArray:
    """Dynamic tensor list (reference ``tensor_array.h``)."""

    def __init__(self, tensors: Optional[Sequence[Tensor]] = None) -> None:
        self._items: List[Tensor] = list(tensors or [])

    def append(self, t: Any) -> None:
        self._items.append(t if isinstance(t, Tensor) else Tensor(t))

    def write(self, index: int, t: Any) -> None:
        t = t if isinstance(t, Tensor) else Tensor(t)
        if index == len(self._items):
            self._items.append(t)
        elif 0 <= index < len(self._items):
            self._items[index] = t
        else:
            raise IndexError(
                f"array_write index {index} out of range [0, {len(self._items)}]"
            )

    def read(self, index: int) -> Tensor:
        return self._items[index]

    def stack(self, axis: int = 0) -> Tensor:
        from paddle_tpu.ops.manipulation import stack

        return stack(self._items, axis=axis)

    def __len__(self) -> int:
        return len(self._items)

    def __getitem__(self, i: int) -> Tensor:
        return self._items[i]

    def __iter__(self) -> Iterator[Tensor]:
        return iter(self._items)

    def __repr__(self) -> str:
        return f"TensorArray(len={len(self._items)})"


def create_array(dtype: Any = "float32", initialized_list: Any = None) -> TensorArray:
    """``paddle.tensor.create_array`` parity."""
    return TensorArray(initialized_list)


def array_write(x: Any, i: Any, array: Optional[TensorArray] = None) -> TensorArray:
    if array is None:
        array = TensorArray()
    array.write(int(i), x)
    return array


def array_read(array: TensorArray, i: Any) -> Tensor:
    return array.read(int(i))


def array_length(array: TensorArray) -> int:
    return len(array)


class SelectedRows:
    """Row-sparse value container (reference ``selected_rows.h:27``):
    ``rows[i]`` is the logical row of dense slice ``value[i]``. Keeps sparse
    embedding gradients O(touched rows) until applied."""

    def __init__(self, rows: Any, value: Any, height: int) -> None:
        self._rows = jnp.asarray(
            rows._data if isinstance(rows, Tensor) else rows, jnp.int32
        )
        v = value._data if isinstance(value, Tensor) else jnp.asarray(value)
        if v.shape[0] != self._rows.shape[0]:
            raise ValueError(
                f"value rows ({v.shape[0]}) != rows index length ({self._rows.shape[0]})"
            )
        self._value = v
        self._height = int(height)

    @property
    def rows(self) -> Tensor:
        return Tensor(self._rows)

    @property
    def value(self) -> Tensor:
        return Tensor(self._value)

    @property
    def height(self) -> int:
        return self._height

    @property
    def shape(self) -> List[int]:
        return [self._height] + list(self._value.shape[1:])

    def to_dense(self) -> Tensor:
        """Scatter-add into the dense logical shape (duplicate rows sum,
        matching gradient-accumulation semantics)."""
        dense = jnp.zeros((self._height,) + self._value.shape[1:], self._value.dtype)
        return Tensor(dense.at[self._rows].add(self._value))

    def merge_rows(self) -> "SelectedRows":
        """Coalesce duplicate rows (reference ``MergeAdd``)."""
        uniq, inv = jnp.unique(self._rows, return_inverse=True, size=self._rows.shape[0],
                               fill_value=self._height)
        merged = jnp.zeros((uniq.shape[0],) + self._value.shape[1:], self._value.dtype)
        merged = merged.at[inv].add(self._value)
        keep = uniq < self._height
        return SelectedRows(uniq[keep], merged[keep], self._height)

    def __repr__(self) -> str:
        return f"SelectedRows(nrows={self._rows.shape[0]}, height={self._height})"


class StringTensor:
    """String tensor (reference ``paddle/phi/core/string_tensor.h``): host-side
    ndarray of UTF-8 strings feeding tokenizer-style preprocessing. TPU
    programs never consume strings — this container exists at the input
    pipeline boundary (faster_tokenizer analog), so storage is numpy object
    dtype, not a device buffer."""

    def __init__(self, data: Any, name: str = "") -> None:
        import numpy as np

        arr = np.asarray(data, dtype=object)
        self._data = arr
        self.name = name

    @property
    def shape(self) -> List[int]:
        return list(self._data.shape)

    def numpy(self):
        return self._data

    def __len__(self) -> int:
        return int(self._data.shape[0]) if self._data.ndim else 1

    def __getitem__(self, idx: Any) -> Any:
        out = self._data[idx]
        return StringTensor(out) if getattr(out, "ndim", 0) else out

    def __repr__(self) -> str:
        return f"StringTensor(shape={self.shape})"
