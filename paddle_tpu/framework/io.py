"""paddle.save / paddle.load (reference ``python/paddle/framework/io.py:773/:1020``).

Pickle-compatible state_dict serialization: Tensors are stored as numpy
arrays (bfloat16 kept via ml_dtypes-aware numpy)."""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict

import numpy as np

from paddle_tpu.core.tensor import Tensor


def _to_serializable(obj: Any) -> Any:
    if isinstance(obj, Tensor):
        return {"__paddle_tpu_tensor__": True, "data": np.asarray(obj.numpy()), "name": obj.name}
    if isinstance(obj, dict):
        return {k: _to_serializable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_serializable(v) for v in obj)
    return obj


def _from_serializable(obj: Any) -> Any:
    if isinstance(obj, dict):
        if obj.get("__paddle_tpu_tensor__"):
            t = Tensor(obj["data"])
            t.name = obj.get("name", t.name)
            return t
        return {k: _from_serializable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_serializable(v) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = 4, **configs: Any) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_serializable(obj), f, protocol=protocol)


def load(path: str, **configs: Any) -> Any:
    with open(path, "rb") as f:
        return _from_serializable(pickle.load(f))
