"""ParamAttr: parameter attribute bundle (reference ``python/paddle/base/param_attr.py``)."""

from __future__ import annotations

from typing import Any, Optional


class ParamAttr:
    def __init__(
        self,
        name: Optional[str] = None,
        initializer: Any = None,
        learning_rate: float = 1.0,
        regularizer: Any = None,
        trainable: bool = True,
        do_model_average: bool = True,
        need_clip: bool = True,
    ) -> None:
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr: Any) -> Optional["ParamAttr"]:
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if attr is False:
            return None
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        # an Initializer instance
        return ParamAttr(initializer=attr)
