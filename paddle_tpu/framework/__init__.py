"""Framework glue: save/load IO, ParamAttr, random compat."""

from paddle_tpu.framework.param_attr import ParamAttr  # noqa: F401
from paddle_tpu.framework.tensor_types import (  # noqa: F401
    SelectedRows,
    StringTensor,
    TensorArray,
    array_length,
    array_read,
    array_write,
    create_array,
)
