"""Framework glue: save/load IO, ParamAttr, random compat."""

from paddle_tpu.framework.param_attr import ParamAttr  # noqa: F401
