"""``paddle_tpu.quantization`` — QAT / PTQ framework.

Reference: ``python/paddle/quantization/`` (QuantConfig + factory-built
observers/quanters, ``qat.py`` QAT wrapping layers with fake-quant,
``ptq.py`` PTQ inserting observers then converting).

TPU-native shape: int8 storage is a *memory/bandwidth* optimization on TPU
(the MXU computes bf16/int8 via XLA's native dot); fake-quant runs as a
quantize-dequantize pair with a straight-through-estimator gradient
(``jax.custom_vjp`` identity), so QAT trains through the rounding. Conversion
produces layers holding int8 weights + per-channel scales, dequantized on the
fly — XLA fuses the dequant into the matmul.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import call_op
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer

__all__ = [
    "QuantConfig",
    "QAT",
    "PTQ",
    "AbsmaxObserver",
    "FakeQuanterWithAbsMax",
    "QuantedLinear",
    "quantize_linear",
    "dequantize_linear",
]


# ---------------------------------------------------------------------------
# quant/dequant primitives
# ---------------------------------------------------------------------------


def _scales_absmax(w: jnp.ndarray, axis: Optional[int], bits: int) -> jnp.ndarray:
    qmax = float(2 ** (bits - 1) - 1)
    if axis is None:
        m = jnp.max(jnp.abs(w))
    else:
        red = tuple(i for i in range(w.ndim) if i != axis)
        m = jnp.max(jnp.abs(w), axis=red, keepdims=False)
    return jnp.maximum(m, 1e-8) / qmax


def quantize_linear(x: Any, scale: Any, bits: int = 8, axis: Optional[int] = None) -> Tensor:
    """Real quantization: float → int8 (reference ``quantize_linear`` op)."""
    qmax = 2 ** (bits - 1) - 1
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    s = scale._data if isinstance(scale, Tensor) else jnp.asarray(scale)
    if axis is not None and s.ndim == 1:
        shape = [1] * arr.ndim
        shape[axis] = s.shape[0]
        s = s.reshape(shape)
    q = jnp.clip(jnp.round(arr / s), -qmax - 1, qmax).astype(jnp.int8)
    return Tensor(q)


def dequantize_linear(q: Any, scale: Any, axis: Optional[int] = None) -> Tensor:
    arr = q._data if isinstance(q, Tensor) else jnp.asarray(q)
    s = scale._data if isinstance(scale, Tensor) else jnp.asarray(scale)
    if axis is not None and s.ndim == 1:
        shape = [1] * arr.ndim
        shape[axis] = s.shape[0]
        s = s.reshape(shape)
    return Tensor(arr.astype(s.dtype) * s)


import functools


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fake_quant(x: jnp.ndarray, scale: jnp.ndarray, qmax: float = 127.0) -> jnp.ndarray:
    return jnp.clip(jnp.round(x / scale), -qmax - 1, qmax) * scale


def _fq_fwd(x, scale, qmax):
    return _fake_quant(x, scale, qmax), (x, scale)


def _fq_bwd(qmax, res, g):
    # straight-through estimator: pass the gradient through inside the
    # representable range, zero outside (reference fake_quantize grad)
    x, scale = res
    inside = (jnp.abs(x) <= scale * qmax).astype(g.dtype)
    return g * inside, jnp.zeros_like(scale)


_fake_quant.defvjp(_fq_fwd, _fq_bwd)


# ---------------------------------------------------------------------------
# observers / quanters (reference base_observer.py / base_quanter.py)
# ---------------------------------------------------------------------------


class AbsmaxObserver(Layer):
    """PTQ observer: tracks the running abs-max of what flows through
    (reference ``observers/abs_max.py``)."""

    def __init__(self, quant_bits: int = 8, axis: Optional[int] = None) -> None:
        super().__init__()
        self.quant_bits = quant_bits
        self.axis = axis
        self._absmax: Optional[jnp.ndarray] = None

    def forward(self, x: Tensor) -> Tensor:
        arr = x._data
        if self.axis is None:
            m = jnp.max(jnp.abs(arr))
        else:
            red = tuple(i for i in range(arr.ndim) if i != self.axis)
            m = jnp.max(jnp.abs(arr), axis=red)
        self._absmax = m if self._absmax is None else jnp.maximum(self._absmax, m)
        return x

    def scales(self) -> Tensor:
        if self._absmax is None:
            raise RuntimeError("observer saw no data; run calibration first")
        qmax = float(2 ** (self.quant_bits - 1) - 1)
        return Tensor(jnp.maximum(self._absmax, 1e-8) / qmax)


class FakeQuanterWithAbsMax(Layer):
    """QAT quanter: quantize-dequantize with an STE gradient (reference
    ``quanters/abs_max.py`` FakeQuanterWithAbsMaxObserver)."""

    def __init__(self, quant_bits: int = 8, axis: Optional[int] = None) -> None:
        super().__init__()
        self.quant_bits = quant_bits
        self.axis = axis

    def forward(self, x: Tensor) -> Tensor:
        axis = self.axis
        bits = self.quant_bits

        def fn(a: jnp.ndarray) -> jnp.ndarray:
            s = jax.lax.stop_gradient(_scales_absmax(a, axis, bits))
            if axis is not None:
                shape = [1] * a.ndim
                shape[axis] = s.shape[0]
                s = s.reshape(shape)
            return _fake_quant(a, s, float(2 ** (bits - 1) - 1))

        return call_op("fake_quant", fn, x)


# ---------------------------------------------------------------------------
# config + wrapped layers
# ---------------------------------------------------------------------------


class QuantConfig:
    """Which layers get quantized, and how (reference ``config.py``).

    ``activation``/``weight`` are quanter/observer prototypes — their
    ``quant_bits``/``axis`` drive the layers QAT/PTQ builds."""

    def __init__(self, activation: Any = None, weight: Any = None) -> None:
        self.activation = activation
        self.weight = weight
        self._layer_types: List[type] = []
        self._layers: List[Layer] = []

    def _weight_bits(self) -> int:
        return int(getattr(self.weight, "quant_bits", 8) or 8)

    def _act_bits(self) -> int:
        return int(getattr(self.activation, "quant_bits", 8) or 8)

    def add_type_config(self, layer_type: Any, activation: Any = None, weight: Any = None) -> None:
        types = layer_type if isinstance(layer_type, (list, tuple)) else [layer_type]
        self._layer_types.extend(types)
        if activation is not None:
            self.activation = activation
        if weight is not None:
            self.weight = weight

    def add_layer_config(self, layer: Any, activation: Any = None, weight: Any = None) -> None:
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        self._layers.extend(layers)

    def _should_quant(self, layer: Layer) -> bool:
        from paddle_tpu.nn import Linear

        # explicit selections are exclusive (reference config semantics);
        # the no-config default quantizes every Linear
        if self._layers:
            return any(layer is l for l in self._layers)  # noqa: E741
        if self._layer_types:
            return isinstance(layer, tuple(self._layer_types))
        return isinstance(layer, Linear)


class QuantedLinear(Layer):
    """Inference form: int8 weight + per-output-channel scales.

    ``kernel="weight_only"`` (default) dequantizes on the fly — XLA fuses the
    dequant multiply into the matmul read, so the win is HBM footprint/
    bandwidth. ``kernel="llm.int8"`` additionally quantizes the activation
    per row and contracts int8 x int8 -> int32 on the MXU
    (``llm_int8_linear``) — the true int8 dot path. With an ``act_scale``
    (from PTQ calibration) the input is statically quantize-dequantized
    through the observed range first."""

    def __init__(self, linear: Any, bits: int = 8, act_scale: Any = None,
                 kernel: str = "weight_only") -> None:
        super().__init__()
        if kernel not in ("weight_only", "llm.int8"):
            raise ValueError(f"kernel must be weight_only/llm.int8, got {kernel!r}")
        if kernel == "llm.int8" and bits != 8:
            raise ValueError("llm.int8 kernel requires bits=8")
        w = linear.weight._data  # [in, out]
        qmax = float(2 ** (bits - 1) - 1)
        scales = _scales_absmax(w, axis=1, bits=bits)
        self.qweight = Tensor(
            jnp.clip(jnp.round(w / scales[None, :]), -qmax - 1, qmax).astype(jnp.int8)
        )
        self.scales = Tensor(scales)
        self.act_scale = (
            None if act_scale is None
            else (act_scale if isinstance(act_scale, Tensor) else Tensor(jnp.asarray(act_scale)))
        )
        self.bias = linear.bias
        self.bits = bits
        self.kernel = kernel

    def forward(self, x: Tensor) -> Tensor:
        qw = self.qweight
        sc = self.scales
        qmax = float(2 ** (self.bits - 1) - 1)
        has_act = self.act_scale is not None
        if has_act:
            def pre(a, a_s):
                return jnp.clip(jnp.round(a / a_s), -qmax - 1, qmax) * a_s

            x = call_op("quant_act", pre, x, self.act_scale)
        if self.kernel == "llm.int8":
            return llm_int8_linear(x, qw, self.bias, sc)
        return weight_only_linear(x, qw, self.bias, sc)


class _ObservedLinear(Layer):
    """PTQ calibration form: observer on the input activation."""

    def __init__(self, linear: Any, observer: AbsmaxObserver) -> None:
        super().__init__()
        self.inner = linear
        self.act_observer = observer

    def forward(self, x: Tensor) -> Tensor:
        return self.inner(self.act_observer(x))


class _QATLinear(Layer):
    """QAT form: fake-quant on weight (per-channel) and activation."""

    def __init__(
        self,
        linear: Any,
        weight_quanter: Optional[FakeQuanterWithAbsMax] = None,
        act_quanter: Optional[FakeQuanterWithAbsMax] = None,
    ) -> None:
        super().__init__()
        self.inner = linear
        self.weight_quanter = weight_quanter or FakeQuanterWithAbsMax(8, axis=1)
        self.act_quanter = act_quanter or FakeQuanterWithAbsMax(8, axis=None)

    def forward(self, x: Tensor) -> Tensor:
        x = self.act_quanter(x)
        w = self.weight_quanter(self.inner.weight)
        out = x @ w
        if self.inner.bias is not None:
            out = out + self.inner.bias
        return out


def _replace_sublayers(model: Layer, predicate: Callable, build: Callable) -> int:
    n = 0
    for parent in model.sublayers(include_self=True):
        for name, child in list(parent.named_children()):
            if predicate(child):
                setattr(parent, name, build(child))
                n += 1
    return n


class Quantization:
    def __init__(self, config: QuantConfig) -> None:
        self._config = config


class QAT(Quantization):
    """Quantization-aware training (reference ``qat.py``)."""

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        if not inplace:
            model = copy.deepcopy(model)
        cfg = self._config
        _replace_sublayers(
            model,
            cfg._should_quant,
            lambda lin: _QATLinear(
                lin,
                weight_quanter=FakeQuanterWithAbsMax(cfg._weight_bits(), axis=1),
                act_quanter=FakeQuanterWithAbsMax(cfg._act_bits(), axis=None),
            ),
        )
        return model

    def convert(self, model: Layer, inplace: bool = False, kernel: str = "weight_only") -> Layer:
        """Fold trained fake-quant layers into int8 inference layers.
        ``kernel="llm.int8"`` selects the true int8 MXU dot path."""
        if not inplace:
            model = copy.deepcopy(model)
        _replace_sublayers(
            model,
            lambda l: isinstance(l, _QATLinear),  # noqa: E741
            lambda q: QuantedLinear(q.inner, bits=q.weight_quanter.quant_bits, kernel=kernel),
        )
        return model


class PTQ(Quantization):
    """Post-training quantization (reference ``ptq.py``): insert observers,
    run calibration batches, convert."""

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        if not inplace:
            model = copy.deepcopy(model)
        cfg = self._config
        _replace_sublayers(
            model,
            cfg._should_quant,
            lambda lin: _ObservedLinear(lin, AbsmaxObserver(cfg._act_bits())),
        )
        return model

    def convert(self, model: Layer, inplace: bool = False, kernel: str = "weight_only") -> Layer:
        """Calibration results feed the converted layers: the observer's
        activation scale becomes the static input quantization range.
        ``kernel="llm.int8"`` selects the true int8 MXU dot path."""
        if not inplace:
            model = copy.deepcopy(model)
        cfg = self._config

        def build(obs: "_ObservedLinear") -> QuantedLinear:
            act_scale = (
                obs.act_observer.scales() if obs.act_observer._absmax is not None else None
            )
            return QuantedLinear(
                obs.inner, bits=cfg._weight_bits(), act_scale=act_scale, kernel=kernel
            )

        _replace_sublayers(
            model,
            lambda l: isinstance(l, _ObservedLinear),  # noqa: E741
            build,
        )
        return model


# ---------------------------------------------------------------------------
# Weight-only / LLM int8 serving primitives (reference ``weight_quantize`` /
# ``weight_dequantize`` / ``weight_only_linear`` / ``llm_int8_linear`` ops,
# ``paddle/phi/kernels/gpu/weight_only_linear_kernel.cu``). TPU-native form:
# int8 weights live in HBM at half the bf16 footprint; ``weight_only_linear``
# dequantizes inside the matmul read (XLA fuses), ``llm_int8_linear``
# dynamically quantizes activations and runs a TRUE int8 x int8 -> int32
# MXU contraction via ``preferred_element_type``.
# ---------------------------------------------------------------------------


def weight_quantize(x: Any, algo: str = "weight_only_int8", group_size: int = -1):
    """Quantize a weight ``[in, out]`` to int8 with per-output-channel absmax
    scales. Returns ``(int8_weight, scales)`` like the reference op."""
    if algo not in ("weight_only_int8", "llm.int8"):
        raise NotImplementedError(f"weight_quantize algo {algo!r} (int4 needs Mosaic packing)")
    w = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    scales = _scales_absmax(w, axis=1, bits=8)
    q = jnp.clip(jnp.round(w / scales[None, :]), -128, 127).astype(jnp.int8)
    return Tensor(q), Tensor(scales)


def weight_dequantize(x: Any, scale: Any, algo: str = "weight_only_int8", out_dtype: str = "float32"):
    q = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    s = scale._data if isinstance(scale, Tensor) else jnp.asarray(scale)
    from paddle_tpu.core.dtypes import convert_dtype

    return Tensor((q.astype(s.dtype) * s[None, :]).astype(convert_dtype(out_dtype)))


def weight_only_linear(x: Any, weight: Any, bias: Any = None, weight_scale: Any = None,
                       weight_dtype: str = "int8", arch: Any = None, group_size: int = -1):
    """out = x @ dequant(weight) + bias with int8 weights resident in HBM.
    The dequant multiply fuses into the matmul read — HBM traffic for the
    weight is halved vs bf16, the contraction still runs bf16 on the MXU."""
    if weight_dtype != "int8":
        raise NotImplementedError("weight_only_linear supports int8 on TPU")

    def fn(a, q, s, *rest):
        w = (q.astype(s.dtype) * s[None, :]).astype(a.dtype)
        out = a @ w
        b = next(iter(rest), None)
        if b is not None:
            out = out + b
        return out

    extras = [] if bias is None else [bias]
    return call_op("weight_only_linear", fn, x, weight, weight_scale, *extras)


def llm_int8_linear(x: Any, weight: Any, bias: Any = None, weight_scale: Any = None,
                    threshold: float = 6.0):
    """True int8 path (reference ``llm_int8_linear``): dynamic per-row absmax
    quantization of the activation, int8 x int8 -> int32 on the MXU
    (``preferred_element_type=int32``), rescale to the activation dtype.
    The reference's outlier decomposition (|x| > threshold columns in fp16)
    is folded in by clamping to the quantization range — outlier columns are
    rare in the serving shapes this targets."""

    def fn(a, q, s, *rest):
        a2 = a.reshape((-1, a.shape[-1]))
        row_scale = jnp.max(jnp.abs(a2), axis=-1, keepdims=True) / 127.0
        row_scale = jnp.maximum(row_scale, 1e-8)
        qa = jnp.clip(jnp.round(a2 / row_scale), -128, 127).astype(jnp.int8)
        acc = jax.lax.dot_general(
            qa, q, (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
        )
        out = acc.astype(jnp.float32) * row_scale * s[None, :].astype(jnp.float32)
        out = out.reshape(a.shape[:-1] + (q.shape[1],)).astype(a.dtype)
        b = next(iter(rest), None)
        if b is not None:
            out = out + b
        return out

    extras = [] if bias is None else [bias]
    return call_op("llm_int8_linear", fn, x, weight, weight_scale, *extras)


def apply_per_channel_scale(x: Any, scales: Any):
    """Reference ``apply_per_channel_scale``: x * scales over the last dim
    (smooth-quant activation pre-scaling)."""

    def fn(a, s):
        return a * s

    return call_op("apply_per_channel_scale", fn, x, scales)


__all__ += [
    "weight_quantize",
    "weight_dequantize",
    "weight_only_linear",
    "llm_int8_linear",
    "apply_per_channel_scale",
]
