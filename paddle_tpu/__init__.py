"""paddle_tpu: a TPU-native deep-learning framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of the reference
PaddlePaddle fork (kircle888/Paddle): an imperative ("dygraph") Tensor API with
eager autograd, an nn.Layer module system, optimizers, bf16 AMP, trace-to-XLA
jit capture, and first-class SPMD distributed training (dp/mp/pp/sharding/sep)
over `jax.sharding.Mesh` device meshes.

Layer map (cf. reference SURVEY.md §1):
  - core/      Tensor over jax.Array + eager autograd tape  (≈ fluid/eager)
  - ops/       op registry + functional tensor ops          (≈ phi/kernels + ops.yaml)
  - nn/        Layer system + functional nn ops             (≈ python/paddle/nn)
  - optimizer/ functional-core optimizers + LR schedulers   (≈ python/paddle/optimizer)
  - amp/       bf16 autocast + loss scaling                 (≈ python/paddle/amp)
  - jit/       trace-to-StableHLO capture                   (≈ paddle.jit + CINN; XLA is the compiler)
  - distributed/ mesh, placements, collectives, parallelism (≈ python/paddle/distributed)
  - kernels/   Pallas TPU kernels (flash attention, flashmask, ring attention)
"""

from paddle_tpu import version as _version

__version__ = _version.__version__

# ---- core runtime -----------------------------------------------------------
from paddle_tpu.core.dtypes import (  # noqa: F401
    bfloat16,
    bool_ as bool,  # noqa: A001 - mirrors paddle.bool
    complex64,
    complex128,
    dtype,
    float16,
    float32,
    float64,
    int8,
    int16,
    int32,
    int64,
    uint8,
)
from paddle_tpu.core.device import (  # noqa: F401
    CPUPlace,
    TPUPlace,
    device,
    get_device,
    set_device,
)
from paddle_tpu.core.tensor import Tensor  # noqa: F401
from paddle_tpu.core.autograd import enable_grad, is_grad_enabled, no_grad, set_grad_enabled  # noqa: F401
from paddle_tpu.core.rng import get_rng_state, seed, set_rng_state  # noqa: F401
from paddle_tpu.flags import get_flags, set_flags  # noqa: F401

# ---- ops: creation + math + manipulation + ... ------------------------------
from paddle_tpu.ops.creation import (  # noqa: F401
    arange,
    assign,
    clone,
    create_parameter,
    diag,
    diagflat,
    empty,
    empty_like,
    eye,
    full,
    full_like,
    linspace,
    logspace,
    meshgrid,
    ones,
    ones_like,
    to_tensor,
    tril,
    triu,
    zeros,
    zeros_like,
)
from paddle_tpu.ops.math import *  # noqa: F401,F403
from paddle_tpu.ops.manipulation import *  # noqa: F401,F403
from paddle_tpu.ops.reduction import *  # noqa: F401,F403
from paddle_tpu.ops.comparison import *  # noqa: F401,F403
from paddle_tpu.ops.logic import *  # noqa: F401,F403
from paddle_tpu.ops.search import *  # noqa: F401,F403
from paddle_tpu.ops.linalg import (  # noqa: F401
    bmm,
    trace,
    cross,
    dist,
    dot,
    einsum,
    histogram,
    bincount,
    matmul,
    mm,
    mv,
    norm,
    t,
    transpose,
)
from paddle_tpu.ops.random import (  # noqa: F401
    bernoulli,
    multinomial,
    normal,
    poisson,
    rand,
    randint,
    randint_like,
    randn,
    randperm,
    standard_normal,
    uniform,
)
from paddle_tpu.ops.parity import *  # noqa: F401,F403

# ---- subpackages ------------------------------------------------------------
from paddle_tpu import amp  # noqa: F401
from paddle_tpu import audio  # noqa: F401
from paddle_tpu import autograd  # noqa: F401
from paddle_tpu import distributed  # noqa: F401
from paddle_tpu import distribution  # noqa: F401
from paddle_tpu import fft  # noqa: F401
from paddle_tpu import signal  # noqa: F401
from paddle_tpu import hapi  # noqa: F401
from paddle_tpu import io  # noqa: F401
from paddle_tpu import jit  # noqa: F401
from paddle_tpu import linalg  # noqa: F401
from paddle_tpu import metric  # noqa: F401
from paddle_tpu.hapi import Model  # noqa: F401
from paddle_tpu import nn  # noqa: F401
from paddle_tpu import optimizer  # noqa: F401
from paddle_tpu import profiler  # noqa: F401
from paddle_tpu import observability  # noqa: F401
from paddle_tpu import static  # noqa: F401
from paddle_tpu import text  # noqa: F401
from paddle_tpu import generation  # noqa: F401
from paddle_tpu import inference  # noqa: F401
from paddle_tpu import serving  # noqa: F401
from paddle_tpu import sparse  # noqa: F401
from paddle_tpu import incubate  # noqa: F401
from paddle_tpu import quantization  # noqa: F401

from paddle_tpu.framework.io import load, save  # noqa: F401
from paddle_tpu.framework.tensor_types import (  # noqa: F401
    SelectedRows,
    StringTensor,
    TensorArray,
    create_array,
)
from paddle_tpu.framework.random import get_cuda_rng_state  # noqa: F401

# paddle-API aliases
from paddle_tpu.nn.layer.layers import Layer  # noqa: F401
from paddle_tpu.core.tensor import Parameter  # noqa: F401
from paddle_tpu.distributed.parallel import DataParallel  # noqa: F401

grad = autograd.grad  # noqa: F401


def disable_static() -> None:
    """Dygraph is the default execution mode; kept for API parity."""


def enable_static() -> None:  # pragma: no cover - compat stub
    raise NotImplementedError(
        "paddle_tpu has no legacy static-graph mode; use paddle_tpu.jit.to_static "
        "to capture a program into a compiled XLA executable."
    )


def in_dynamic_mode() -> bool:
    return True


# Tensor-method parity pass: bind module-level ops the reference also exposes
# as methods (runs last so every op surface above is importable)
from paddle_tpu.ops.parity import bind_missing_tensor_methods as _bind_methods  # noqa: E402

_bind_methods()
