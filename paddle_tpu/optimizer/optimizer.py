"""Optimizer base.

Reference: ``python/paddle/optimizer/optimizer.py`` (param groups, master
weights, grad clip integration). TPU-native design: every optimizer defines a
**functional core** — ``init_state(param) -> state`` and
``update(param, grad, state, *, lr, step) -> (new_param, new_state)`` over raw
jax arrays — and the eager ``.step()`` runs one fused, jit-compiled XLA program
over all parameters (the analog of the reference's multi_tensor/fused optimizer
kernels, e.g. ``fused_adam``). The same functional core is reused by
``paddle_tpu.jit`` captured train steps and by the ZeRO sharded optimizer.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.errors import InvalidArgumentError


class Optimizer:
    def __init__(
        self,
        learning_rate: Union[float, "paddle_tpu.optimizer.lr.LRScheduler"] = 0.001,
        parameters: Optional[Sequence[Any]] = None,
        weight_decay: Optional[Union[float, Any]] = None,
        grad_clip: Any = None,
        multi_precision: bool = False,
        name: Optional[str] = None,
    ) -> None:
        if parameters is None:
            raise InvalidArgumentError(
                "parameters is required in dygraph mode (pass model.parameters())"
            )
        # param groups: list of dicts {params, learning_rate?, weight_decay?}
        params = list(parameters)
        if params and isinstance(params[0], dict):
            self._param_groups = params
            self._parameters = [p for g in params for p in g["params"]]
        else:
            self._param_groups = [{"params": params}]
            self._parameters = params
        self._learning_rate = learning_rate
        self._weight_decay = self._wd_value(weight_decay)
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._step_count = 0
        # Device-side step counter + lr override: these make step() traceable
        # by paddle_tpu.jit (a python-int step would be baked into the XLA
        # program as a constant).
        self._step_buf: Optional[jax.Array] = None
        self._lr_array: Optional[jax.Array] = None
        self._accumulators: Dict[int, Dict[str, jax.Array]] = {}
        self._jit_step_fn: Optional[Callable] = None

    def _param_weight_decay(self, p: Any, wd: float) -> float:
        """Per-parameter weight-decay override hook (AdamW's
        apply_decay_param_fun)."""
        return wd

    @staticmethod
    def _wd_value(weight_decay: Any) -> float:
        if weight_decay is None:
            return 0.0
        if hasattr(weight_decay, "_coeff"):  # L2Decay regularizer object
            return float(weight_decay._coeff)
        return float(weight_decay)

    # -- functional core (overridden by each algorithm) -----------------------
    def init_state(self, param: jax.Array) -> Dict[str, jax.Array]:
        return {}

    def update(
        self,
        param: jax.Array,
        grad: jax.Array,
        state: Dict[str, jax.Array],
        *,
        lr: jax.Array,
        step: jax.Array,
        weight_decay: float,
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        raise NotImplementedError

    # -- lr -------------------------------------------------------------------
    def get_lr(self) -> float:
        from paddle_tpu.optimizer.lr import LRScheduler

        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value: float) -> None:
        self._learning_rate = float(value)

    # -- state management -----------------------------------------------------
    def _state_for(self, p: Tensor) -> Dict[str, jax.Array]:
        key = id(p)
        if key not in self._accumulators:
            low_prec = jnp.dtype(p.dtype) in (
                jnp.dtype(jnp.bfloat16),
                jnp.dtype(jnp.float16),
            )
            if self._multi_precision and low_prec:
                # fp32 master weight AND fp32 moments (reference
                # multi_precision semantics: all accumulators in fp32).
                master = p.data.astype(jnp.float32)
                state = self.init_state(master)
                state["master_weight"] = master
            else:
                state = self.init_state(p.data)
            self._accumulators[key] = state
        return self._accumulators[key]

    # -- the step -------------------------------------------------------------
    def step(self) -> None:
        params_grads = [(p, p.grad) for p in self._parameters if not p.stop_gradient and p.grad is not None]
        if not params_grads:
            self._advance_lr()
            return
        if self._grad_clip is not None:
            # clip over the full set (global norm spans param groups)
            params_grads = self._grad_clip(params_grads)
        if self._step_buf is None:
            self._step_buf = jnp.zeros((), jnp.int32)
        base_lr = self._lr_array if self._lr_array is not None else jnp.asarray(self.get_lr(), jnp.float32)
        step = self._step_buf + 1

        # Bucket by (group lr, group wd, per-param lr factor) so param-group
        # overrides are honored (reference: optimizer.py _param_groups).
        grad_of = {id(p): g for p, g in params_grads}
        buckets: Dict[Tuple[Optional[float], float, float], List[Tensor]] = {}
        for group in self._param_groups:
            g_lr = group.get("learning_rate")
            g_wd = group.get("weight_decay")
            wd = self._weight_decay if g_wd is None else self._wd_value(g_wd)
            for p in group["params"]:
                if id(p) not in grad_of:
                    continue
                factor = float(getattr(p, "optimize_attr", {}).get("learning_rate", 1.0))
                wd_p = self._param_weight_decay(p, wd)
                buckets.setdefault((g_lr, wd_p, factor), []).append(p)

        for (g_lr, wd, factor), params in buckets.items():
            lr = jnp.asarray(g_lr, jnp.float32) if g_lr is not None else base_lr
            if factor != 1.0:
                lr = lr * factor
            self._run_fused(params, [grad_of[id(p)] for p in params], lr, step, wd)
        self._step_buf = step
        self._step_count += 1
        self._advance_lr()

    def _run_fused(self, params: List[Tensor], grads: List[Tensor], lr: Any, step: Any, weight_decay: float) -> None:
        states = [self._state_for(p) for p in params]
        p_arrays = [p.data for p in params]
        g_arrays = [g.data for g in grads]

        if self._jit_step_fn is None:
            update = self.update

            def fused(ps, gs, sts, lr_, step_, wd):
                new_ps, new_sts = [], []
                for p_, g_, st in zip(ps, gs, sts):
                    if "master_weight" in st:
                        mp = st["master_weight"]
                        inner = {k: v for k, v in st.items() if k != "master_weight"}
                        new_mp, new_inner = update(
                            mp, g_.astype(jnp.float32), inner, lr=lr_, step=step_, weight_decay=wd
                        )
                        new_inner["master_weight"] = new_mp
                        new_ps.append(new_mp.astype(p_.dtype))
                        new_sts.append(new_inner)
                    else:
                        np_, nst = update(p_, g_, st, lr=lr_, step=step_, weight_decay=wd)
                        new_ps.append(np_)
                        new_sts.append(nst)
                return new_ps, new_sts

            # One fused XLA program for the whole step, cached across calls
            # (weight_decay is static: it appears in python-level branches).
            self._jit_step_fn = jax.jit(fused, static_argnums=(5,))

        new_p_arrays, new_states = self._jit_step_fn(
            p_arrays, g_arrays, states, lr, step, weight_decay
        )
        with paddle_tpu.no_grad():
            for p, new_data, new_state in zip(params, new_p_arrays, new_states):
                p._data = new_data
                self._accumulators[id(p)] = new_state

    def _advance_lr(self) -> None:
        from paddle_tpu.optimizer.lr import LRScheduler

        if isinstance(self._learning_rate, LRScheduler) and self._learning_rate.auto_step:
            pass  # schedulers advance via user-called scheduler.step() in paddle

    def clear_grad(self, set_to_zero: bool = False) -> None:
        for p in self._parameters:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss: Tensor, startup_program: Any = None, parameters: Any = None, no_grad_set: Any = None) -> None:
        loss.backward()
        self.step()

    # -- serialization --------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        sd: Dict[str, Any] = {"_step_count": self._step_count}
        for i, p in enumerate(self._parameters):
            st = self._accumulators.get(id(p))
            if st is not None:
                for k, v in st.items():
                    sd[f"{p.name}__{k}"] = Tensor(v)
        from paddle_tpu.optimizer.lr import LRScheduler

        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state_dict: Dict[str, Any]) -> None:
        self._step_count = int(state_dict.get("_step_count", 0))
        # the bias-correction time (t in m̂ = m/(1-β₁ᵗ)) lives in the
        # device-side _step_buf, which must resume in lockstep with
        # _step_count — leaving it at zero makes a restored Adam re-run
        # warmup-sized steps and diverge from the uninterrupted trajectory
        self._step_buf = (
            jnp.asarray(self._step_count, jnp.int32) if self._step_count else None
        )
        for p in self._parameters:
            prefix = f"{p.name}__"
            st = {}
            for k, v in state_dict.items():
                if isinstance(k, str) and k.startswith(prefix):
                    st[k[len(prefix):]] = v.data if isinstance(v, Tensor) else jnp.asarray(v)
            if st:
                self._accumulators[id(p)] = st
        from paddle_tpu.optimizer.lr import LRScheduler

        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
